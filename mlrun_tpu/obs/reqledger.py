"""Per-request phase ledger for the serving path
(docs/observability.md "Request attribution, exemplars & trace assembly").

``obs/goodput.py`` made every wall-second of a *training run*
attributable; this module is the serving analog: every engine request
carries a phase-transition ledger so "where did this request's time go"
has an exact answer. Entering a phase closes the previous one at the
SAME clock read, so the per-phase seconds sum to the request wall **by
construction** — the identical zero-tolerance closure invariant
``GoodputLedger`` holds for runs (fake-clock asserted in tests).

Phases (docs/observability.md has the table):

- ``admission``          submit-side checks (canary resolution, 404
                         lookup) and scheduler-side claim bookkeeping
                         (page reservation, prefix match)
- ``rate_limit_wait``    per-tenant token-bucket check at submit
- ``queue_wait``         enqueued → claimed off the admission queue
                         (paged: including head-of-line page waits)
- ``adapter_load_wait``  materializing the tenant's LoRA factors in the
                         device bank at admission
- ``promote``            importing host-KV-tier pages back into the
                         device pool at admission instead of prefilling
                         the covered blocks (docs/serving.md
                         "Hierarchical KV")
- ``prefill``            first prefill dispatch → first token (chunked:
                         spans every chunk tick, decode ticks between
                         chunks included — that IS the request's prefill
                         latency; chunk count rides in the notes)
- ``handoff``            prefill→decode path: slot-cache serialize on
                         the prefill replica, import on the decode one
- ``decode_active``      a decode dispatch that advanced this request's
                         row was running
- ``decode_stall``       the slot held a row but the scheduler was doing
                         something else (admission work, other ticks)
- ``redispatch_backoff`` fleet re-dispatch backoff timers (attributed
                         out-of-band by ``EngineFleet``)
- ``fetch``              pulling a reassigned hot prefix's pages from
                         the previous ring owner before dispatch
                         (attributed out-of-band by ``EngineFleet``)
- ``network``            dispatch/transfer remainder at the fleet or
                         RemoteStep boundary: hop wall minus the
                         server-side attributed time

Stdlib only at module level (the ``obs/metrics.py`` bottom-layer rule);
the one metric family lives here like the goodput families live in
``obs/goodput.py``.
"""

from __future__ import annotations

import threading
import time
from typing import Callable

from .metrics import REGISTRY

# canonical phase names (anything else folds into "other" at export)
PHASES = ("admission", "rate_limit_wait", "queue_wait",
          "adapter_load_wait", "promote", "prefill", "handoff",
          "decode_active", "decode_stall", "redispatch_backoff",
          "fetch", "network", "other")

REQUEST_PHASE_SECONDS = REGISTRY.histogram(
    "mlt_request_phase_seconds",
    "Per-request wall seconds by ledger phase (admission, "
    "rate_limit_wait, queue_wait, adapter_load_wait, promote, prefill, "
    "handoff, decode_active, decode_stall, redispatch_backoff, fetch, "
    "network, other); "
    "phases sum to the request wall by construction. Bounded adapter "
    "label like the TTFT family (docs/serving.md \"Multi-tenant LoRA\")",
    labels=("phase", "adapter"), max_label_sets=1024, overflow="drop",
    buckets=(0.0001, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
             0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0))


def ledger_enabled() -> bool:
    """``mlconf.serving.llm.request_ledger`` (lazy import — this module
    stays bottom-layer); True when config is unreadable so the default
    path is the instrumented one."""
    try:
        from ..config import mlconf

        return bool(mlconf.serving.llm.get("request_ledger", True))
    except Exception:  # noqa: BLE001 - config must not gate telemetry
        return True


class RequestLedger:
    """One request's phase-transition ledger.

    The owner calls :meth:`enter` at every phase boundary; the elapsed
    clock time since the previous boundary is attributed to the phase
    being LEFT. Because the close of one phase and the open of the next
    share a single clock read, no instant is ever double-counted or
    dropped: ``Σ phase seconds == wall`` exactly (the acceptance
    invariant, fake-clock asserted).

    Ownership moves submit-thread → scheduler-thread → (fleet callback
    threads for :meth:`attribute`); a lock keeps each transition atomic.
    ``clock`` is injectable for deterministic tests.
    """

    __slots__ = ("trace_id", "notes", "_clock", "_t0", "_t_last",
                 "_phase", "_seconds", "_out_of_band", "_closed", "_lock")

    def __init__(self, trace_id: str = "",
                 clock: Callable[[], float] = time.perf_counter,
                 phase: str = "admission"):
        self.trace_id = trace_id
        self.notes: dict = {}      # free-form context (chunks, cached_prefix)
        self._clock = clock
        now = clock()
        self._t0 = now
        self._t_last = now
        self._phase = phase
        self._seconds: dict[str, float] = {}
        self._out_of_band = 0.0    # attribute() seconds (outside the span)
        self._closed = False
        self._lock = threading.Lock()

    # -- phase transitions ---------------------------------------------------
    @property
    def current_phase(self) -> str:
        return self._phase

    def enter(self, phase: str) -> float:
        """Close the current phase at this instant and start ``phase``.
        Returns the seconds attributed to the phase being left."""
        now = self._clock()
        with self._lock:
            if self._closed:
                return 0.0
            elapsed = max(0.0, now - self._t_last)
            if elapsed:
                self._seconds[self._phase] = \
                    self._seconds.get(self._phase, 0.0) + elapsed
            self._t_last = now
            self._phase = phase
        return elapsed

    def attribute(self, phase: str, seconds: float):
        """Add out-of-band seconds to ``phase`` (fleet backoff timers,
        network remainders measured by a caller that owns the outer
        wall). Advances the wall total with them — attribution still
        sums to wall."""
        seconds = float(seconds)
        if seconds <= 0:
            return
        with self._lock:
            self._seconds[phase] = self._seconds.get(phase, 0.0) + seconds
            self._out_of_band += seconds

    def note(self, key: str, value):
        self.notes[key] = value

    def close(self, final_phase: str | None = None) -> dict:
        """Attribute the trailing open interval (renamed to
        ``final_phase`` when given) and return the timing summary.
        Idempotent — a second close returns the same summary."""
        if not self._closed:
            if final_phase is not None:
                with self._lock:
                    self._phase = final_phase
            self.enter(self._phase)
            with self._lock:
                self._closed = True
        return self.summary()

    # -- views ---------------------------------------------------------------
    def wall_seconds(self) -> float:
        with self._lock:
            span = (self._t_last if self._closed else self._clock()) \
                - self._t0
            return max(0.0, span) + self._out_of_band

    def phases(self) -> dict[str, float]:
        with self._lock:
            return {phase: seconds
                    for phase, seconds in sorted(self._seconds.items())
                    if seconds > 0}

    def summary(self) -> dict:
        """JSON-friendly timing payload (the v2 ``"timing"`` debug field
        and the bench/test view). ``attribution_closed`` is the closure
        invariant check: phases must sum to wall exactly (modulo float
        addition noise)."""
        phases = self.phases()
        wall = self.wall_seconds()
        attributed = sum(phases.values())
        out = {
            "wall_s": wall,
            "phases": phases,
            "attribution_closed": abs(wall - attributed) < 1e-6,
        }
        if self.trace_id:
            out["trace_id"] = self.trace_id
        if self.notes:
            out.update(self.notes)
        return out


def merge_timing(into: dict, other: dict | None) -> dict:
    """Sum another hop's timing phases into ``into`` (the fleet's
    prefill-side + decode-side merge): same-named phases add, notes of
    the later hop win, walls add. Closure is preserved — both inputs
    sum to their walls, so the merge sums to the summed wall."""
    if not other:
        return into
    phases = into.setdefault("phases", {})
    for phase, seconds in (other.get("phases") or {}).items():
        phases[phase] = phases.get(phase, 0.0) + seconds
    into["wall_s"] = into.get("wall_s", 0.0) + other.get("wall_s", 0.0)
    for key, value in other.items():
        if key not in ("phases", "wall_s", "attribution_closed"):
            into.setdefault(key, value)
    return into


def retire_adapter_phases(adapter: str):
    """Drop a retired adapter's per-phase series — the series-lifecycle
    contract the TTFT/ITL families follow: the continuous-tuning loop
    mints new versioned adapter ids over time, and without pruning the
    churn would exhaust the family's label-set cap (past it,
    ``overflow="drop"`` silently stops attributing NEW tenants).
    Idempotent; the ``""`` base series is never retired. Called from
    ``AdapterRegistry.retire`` (the canary promote/rollback path —
    exactly where version churn happens); ``max_label_sets`` + drop
    stays the backstop for adapters never formally retired."""
    if not adapter:
        return
    for phase in PHASES:
        REQUEST_PHASE_SECONDS.remove(phase=phase, adapter=adapter)


def export_phases(timing: dict, adapter: str = ""):
    """Flush one finished request's phase breakdown onto
    ``mlt_request_phase_seconds{phase,adapter}``; the request's trace id
    rides each observation as the histogram exemplar so a latency alert
    can name the culprit trace (docs/observability.md)."""
    trace_id = timing.get("trace_id") or None
    for phase, seconds in (timing.get("phases") or {}).items():
        if phase not in PHASES:
            phase = "other"
        REQUEST_PHASE_SECONDS.observe(seconds, exemplar=trace_id,
                                      phase=phase, adapter=adapter)
