"""Goodput/badput accounting for the run lifecycle
(docs/observability.md "Goodput & badput").

"Exploring the limits of Concurrency in ML Training on Google TPUs"
(PAPERS.md) makes efficiency-per-wall-second the headline metric at pod
scale — and a run that spends half its wall clock in
preempt→resubmit→re-compile cycles used to report the same
``mlt_train_step_seconds`` as a healthy one. This module attributes
EVERY wall-second of a run to either **goodput** (productive step time)
or a typed **badput** bucket, two ways:

- :class:`GoodputLedger` — an in-process phase-transition ledger the
  training loop drives (``Trainer.fit`` promotes its existing timings —
  input wait, h2d, dispatch, compile, metric flush — to first-class
  phases). Attribution sums to wall time *by construction*: entering a
  phase closes the previous one at the same clock read, so no second is
  ever counted twice or dropped.
- :func:`record_badput` — out-of-band attribution for lifecycle gaps
  the run process never sees (the monitor's retry backoff, the
  preemption→resubmission downtime, a stall's silent window), written
  straight onto the counters from the service side.

Exported families (flowing through the existing federation/timeseries
path, so ``SLO(kind="goodput")`` burn-rate objectives in ``obs/slo.py``
evaluate them unchanged):

- ``mlt_goodput_seconds_total{run}`` — productive step seconds
- ``mlt_badput_seconds_total{run,bucket}`` — typed unproductive seconds
- ``mlt_goodput_wall_seconds_total{run}`` — total attributed seconds
  (= goodput + sum over badput buckets, the burn-rate denominator)
- ``mlt_goodput_fraction{run}`` — rolling goodput / wall gauge

Stdlib only at module level (bottom-layer rule shared with
``obs/metrics.py`` / ``obs/flight.py``).
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Callable, Optional

from .metrics import REGISTRY

# the one productive phase; everything else is a badput bucket
GOODPUT_PHASE = "step"

# typed badput buckets (docs/observability.md has the table):
#   compile              cold XLA compile blocking the first dispatch
#   re_warm              first-dispatch warmup on a RESUMED run (trace +
#                        persistent-cache load — the elasticity tax)
#   data_wait            host blocked in next(data_iter) (input-bound)
#   h2d                  host->device batch transfer on the sync path
#   metric_flush         log-point metric reads/drains
#   checkpoint           checkpoint save/restore (incl. the preemption
#                        final save and the resume restore)
#   preemption_downtime  eviction -> replacement-resource gap (monitor)
#   resubmit_gap         retry backoff before a non-preemption resubmit
#   stall                heartbeat-silent window before a stall abort
#   reshard              elastic slice-loss recovery: survivor mesh
#                        rebuild + checkpoint reshard-restore + the
#                        post-reshard recompile (and the grow-back put)
#   degraded             capacity lost while running at reduced world
#                        size: of every step-second at W' of W devices,
#                        the (1 - W'/W) share is attributed here — the
#                        price elasticity pays INSTEAD of
#                        preemption_downtime + re_warm full stops
#   init                 loop entry before the first phase transition
#   other                attributable to no instrumented phase
BADPUT_BUCKETS = ("compile", "re_warm", "data_wait", "h2d", "metric_flush",
                  "checkpoint", "preemption_downtime", "resubmit_gap",
                  "stall", "reshard", "degraded", "init", "other")

# one run-admission gate bounds the ``run`` label across ALL four
# families (below): per-family overflow="drop" alone would desync them
# — e.g. a badput series landing while its wall series is dropped
# breaks the bad<=total invariant SLO(kind="goodput") burn rates divide
# on. The per-family max_label_sets are sized ABOVE the gate so the
# gate is the only bound that ever fires.
RUN_LABEL_BUDGET = 256

GOODPUT_SECONDS = REGISTRY.counter(
    "mlt_goodput_seconds_total",
    "Productive (train-step dispatch) wall seconds per run",
    labels=("run",), max_label_sets=512, overflow="drop")
BADPUT_SECONDS = REGISTRY.counter(
    "mlt_badput_seconds_total",
    "Unproductive wall seconds per run by typed bucket (compile, "
    "re_warm, data_wait, h2d, metric_flush, checkpoint, "
    "preemption_downtime, resubmit_gap, stall, reshard, degraded, "
    "init, other)",
    labels=("run", "bucket"), max_label_sets=8192, overflow="drop")
WALL_SECONDS = REGISTRY.counter(
    "mlt_goodput_wall_seconds_total",
    "Total attributed wall seconds per run (goodput + every badput "
    "bucket — the burn-rate denominator for SLO(kind='goodput'))",
    labels=("run",), max_label_sets=512, overflow="drop")
GOODPUT_FRACTION = REGISTRY.gauge(
    "mlt_goodput_fraction",
    "goodput seconds / attributed wall seconds per run (the paper's "
    "efficiency-per-wall-second headline number)",
    labels=("run",), max_label_sets=512, overflow="drop")

_admit_lock = threading.Lock()
_admitted_runs: set = set()


def _admit_run(run: str) -> bool:
    """Atomic cross-family admission for a ``run`` label value: either
    every family gets the run's series or none does. ``""`` (the
    anonymous shared series) is always admitted; a retired run frees
    its slot."""
    if not run:
        return True
    with _admit_lock:
        if run in _admitted_runs:
            return True
        if len(_admitted_runs) >= RUN_LABEL_BUDGET:
            return False
        _admitted_runs.add(run)
        return True


def retire_run(run: str):
    """Drop a run's per-run series from every goodput family — the same
    series-lifecycle contract fleet replicas and adapters follow: a
    long-lived service attributing badput for a rotating run population
    must not consume the families' label-set budget forever (past it,
    ``overflow="drop"`` silently stops attributing NEW runs)."""
    if not run:
        return  # "" is the shared anonymous series, never retired
    GOODPUT_SECONDS.remove(run=run)
    WALL_SECONDS.remove(run=run)
    GOODPUT_FRACTION.remove(run=run)
    for bucket in BADPUT_BUCKETS:
        BADPUT_SECONDS.remove(run=run, bucket=bucket)
    with _admit_lock:
        _admitted_runs.discard(run)


# finished runs whose series are KEPT so the terminal attribution (the
# stall window, the final fraction) survives until federation scrapes
# it; past the bound the oldest retires — bounded well inside the
# families' label-set budgets
RECENT_RUNS_KEPT = 64
_recent_lock = threading.Lock()
_recent_runs: list[str] = []


def release_run(run: str):
    """Queue a finished run for series retirement (the monitor calls
    this when it forgets a run's resource). The most recent
    ``RECENT_RUNS_KEPT`` finished runs stay scrapeable; older ones are
    retired via :func:`retire_run`."""
    if not run:
        return
    evicted = []
    with _recent_lock:
        if run in _recent_runs:
            _recent_runs.remove(run)
        _recent_runs.append(run)
        while len(_recent_runs) > RECENT_RUNS_KEPT:
            evicted.append(_recent_runs.pop(0))
    for old in evicted:
        retire_run(old)


def record_badput(bucket: str, seconds: float, run: str = ""):
    """Out-of-band badput attribution (service-side lifecycle gaps the
    run process cannot time itself: retry backoff, preemption downtime,
    stall windows). Also advances the wall denominator so the
    goodput-fraction burn rate sees the downtime."""
    seconds = float(seconds)
    if seconds <= 0 or not _admit_run(run):
        return
    BADPUT_SECONDS.inc(seconds, run=run, bucket=bucket)
    WALL_SECONDS.inc(seconds, run=run)


class GoodputLedger:
    """Per-run step-phase ledger. The owner calls :meth:`enter` at every
    phase boundary; the elapsed clock time since the previous boundary is
    attributed to the phase being LEFT, so the per-phase seconds sum to
    the clock span exactly — the acceptance invariant
    ``goodput + Σ badput == wall`` (± one tick) holds by construction.

    ``clock`` is injectable (fake-clock tests); all methods are
    single-owner (the training loop) except :meth:`attribute`, which is
    thread-safe for out-of-band additions.
    """

    def __init__(self, run: str = "",
                 clock: Callable[[], float] = time.monotonic):
        self.run = run
        self._clock = clock
        self._lock = threading.Lock()
        now = clock()
        self._t0 = now
        self._t_last = now
        self._phase = "init"
        self._seconds: dict[str, float] = {}
        self._out_of_band = 0.0   # attribute() seconds (not in the span)
        self._exported: dict[str, float] = {}  # per-phase flushed seconds
        self._exported_wall = 0.0
        self._closed = False

    # -- phase transitions ---------------------------------------------------
    @property
    def current_phase(self) -> str:
        return self._phase

    def enter(self, phase: str) -> float:
        """Close the current phase at this instant and start ``phase``.
        Returns the seconds attributed to the phase being left."""
        now = self._clock()
        with self._lock:
            elapsed = max(0.0, now - self._t_last)
            if elapsed:
                self._seconds[self._phase] = \
                    self._seconds.get(self._phase, 0.0) + elapsed
            self._t_last = now
            self._phase = phase
        return elapsed

    @contextlib.contextmanager
    def phase(self, phase: str):
        """Scoped phase: enter ``phase``, and on exit return to the phase
        that was active before (its clock restarts at the exit instant)."""
        previous = self._phase
        self.enter(phase)
        try:
            yield self
        finally:
            self.enter(previous)

    def attribute(self, phase: str, seconds: float):
        """Add out-of-band seconds to ``phase`` (e.g. a warmup compile
        that ran before this ledger's window). Advances the wall total
        with them — attribution still sums to wall."""
        seconds = float(seconds)
        if seconds <= 0:
            return
        with self._lock:
            self._seconds[phase] = self._seconds.get(phase, 0.0) + seconds
            self._out_of_band += seconds

    def transfer(self, src: str, dst: str, seconds: float):
        """Reclassify seconds already attributed to ``src`` into ``dst``
        (the first dispatch lands in ``step`` but is compile-class time;
        the compile measurement arrives after the fact). Clamped to what
        ``src`` actually holds — wall stays invariant."""
        with self._lock:
            available = self._seconds.get(src, 0.0)
            moved = max(0.0, min(float(seconds), available))
            if not moved:
                return
            self._seconds[src] = available - moved
            self._seconds[dst] = self._seconds.get(dst, 0.0) + moved

    def close(self, final_phase: str | None = None) -> dict:
        """Attribute the trailing open interval (to ``final_phase`` when
        given, else the current phase), export deltas to the metric
        families, and return the summary. Idempotent."""
        if not self._closed:
            if final_phase is not None:
                # rename the OPEN interval (no attribution yet): the
                # trailing time belongs to final_phase, not to whatever
                # phase the loop happened to be in when it died
                with self._lock:
                    self._phase = final_phase
            self.enter(self._phase)
            self._closed = True
        self.export()
        return self.summary()

    # -- views ---------------------------------------------------------------
    def wall_seconds(self) -> float:
        """Attributed wall so far: the clock span plus out-of-band
        additions (the open interval counts — a stalled loop's fraction
        decays instead of freezing)."""
        with self._lock:
            span = max(0.0, self._clock() - self._t0) \
                if not self._closed else \
                sum(self._seconds.values()) - self._out_of_band
            return span + self._out_of_band

    def goodput_seconds(self) -> float:
        with self._lock:
            return self._seconds.get(GOODPUT_PHASE, 0.0)

    def badput(self) -> dict[str, float]:
        with self._lock:
            return {phase: seconds
                    for phase, seconds in sorted(self._seconds.items())
                    if phase != GOODPUT_PHASE and seconds > 0}

    def goodput_fraction(self) -> float:
        wall = self.wall_seconds()
        return (self.goodput_seconds() / wall) if wall > 0 else 0.0

    def summary(self) -> dict:
        """JSON-friendly breakdown (the bench/test/debug view)."""
        with self._lock:
            attributed = dict(self._seconds)
        goodput = attributed.pop(GOODPUT_PHASE, 0.0)
        return {
            "run": self.run,
            "wall_s": self.wall_seconds(),
            "goodput_s": goodput,
            "goodput_fraction": self.goodput_fraction(),
            "badput": {k: v for k, v in sorted(attributed.items()) if v > 0},
            "badput_s": sum(attributed.values()),
        }

    # -- metric export -------------------------------------------------------
    def export(self):
        """Flush attribution deltas since the last export onto the
        counter families and refresh the fraction gauge. Called at log
        points and at close — counters only ever advance, so federated
        ``increase()`` windows stay correct across flushes."""
        if not _admit_run(self.run):
            return  # over the run-label budget: drop atomically
        with self._lock:
            snapshot = dict(self._seconds)
        total = 0.0
        for phase, seconds in snapshot.items():
            delta = seconds - self._exported.get(phase, 0.0)
            if delta <= 0:
                continue
            self._exported[phase] = seconds
            total += delta
            if phase == GOODPUT_PHASE:
                GOODPUT_SECONDS.inc(delta, run=self.run)
            else:
                bucket = phase if phase in BADPUT_BUCKETS else "other"
                BADPUT_SECONDS.inc(delta, run=self.run, bucket=bucket)
        if total > 0:
            WALL_SECONDS.inc(total, run=self.run)
            self._exported_wall += total
        GOODPUT_FRACTION.set(self.goodput_fraction(), run=self.run)
