"""Black-box flight recorder for the run lifecycle
(docs/observability.md "Flight recorder & debug endpoints").

A crashed or stall-aborted run used to leave nothing to debug with: the
metrics families say *how much* went wrong, spans say *where* a request
went, but the sequence of decisions leading into a failure — retries
scheduled, chaos injections firing, breaker trips, scheduler admissions,
the preemption signal — was only reconstructable from interleaved log
lines, if the logs survived at all. This module is the aircraft-style
black box: a bounded, lock-cheap ring of structured events that every
layer appends to for free, dumped as a JSONL post-mortem artifact the
moment something dies (``monitor_runs`` stall aborts,
``PreemptionGuard.on_preempted``, ``Trainer`` exception exits, engine
``_fail_pending`` crashes) and readable live via ``GET /debug/flight``
on the serving gateway and the service API.

Design constraints (the ``chaos/registry.py`` /  ``obs/metrics.py``
bottom-layer rules):

- **Stdlib only at module level.** Every layer (chaos included, via the
  pushed-in fire observer in ``obs/__init__``) records without import
  cycles; config is imported lazily for the dump directory.
- **Lock-cheap when recording.** One lock + deque append per event; no
  formatting, no IO. Serialization cost is paid only at dump/read time.
- **Bounded.** The ring holds the last N events (default 4096,
  ``mlconf.observability.flight.ring``); a hot loop can record every
  decision without growing the process.
- **Dump never raises.** A post-mortem writer that throws during an
  unwind would mask the original failure; ``dump`` returns the artifact
  path or ``None``.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Optional

_DEFAULT_RING = 4096

# monotonically increasing per-process sequence so readers can order
# events even when two land inside one clock tick
_seq_lock = threading.Lock()
_seq = 0


def _next_seq() -> int:
    global _seq
    with _seq_lock:
        _seq += 1
        return _seq


class FlightRecorder:
    """Bounded ring of structured events. One process-wide instance
    (:func:`get_flight_recorder`); tests may build isolated ones."""

    def __init__(self, ring: int = _DEFAULT_RING):
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=max(16, int(ring)))
        self._dir: Optional[str] = None
        self.dumps = 0                      # post-mortems written
        self.last_dump_path: Optional[str] = None

    # -- configuration -------------------------------------------------------
    def configure(self, ring: int | None = None, directory: str | None = None):
        if ring is not None:
            with self._lock:
                self._ring = deque(self._ring, maxlen=max(16, int(ring)))
        if directory is not None:
            self._dir = directory or None
        return self

    def _dump_dir(self) -> str:
        if self._dir:
            return self._dir
        try:
            from ..config import mlconf

            configured = str(
                mlconf.observability.flight.get("dir", "") or "")
            if configured:
                return configured
        except Exception:  # noqa: BLE001 - config must not gate a post-mortem
            pass
        import tempfile

        return os.path.join(tempfile.gettempdir(), "mlt-flight")

    # -- recording -----------------------------------------------------------
    def record(self, kind: str, **data) -> dict:
        """Append one structured event. Hot-path cheap: timestamp +
        sequence + one locked deque append; values should already be
        JSON-friendly scalars (the dump serializes with ``default=str``
        so a stray object degrades to its repr, never an error)."""
        event = {"t": time.time(), "seq": _next_seq(), "kind": kind}
        if data:
            event.update(data)
        with self._lock:
            self._ring.append(event)
        return event

    # -- reading -------------------------------------------------------------
    def events(self, kind: str | None = None, limit: int = 0) -> list[dict]:
        """Snapshot of the ring, oldest first; ``kind`` filters by exact
        event kind or a ``prefix.*`` wildcard, ``limit`` keeps only the
        newest N after filtering (0 = all)."""
        with self._lock:
            snapshot = list(self._ring)
        if kind:
            if kind.endswith(".*"):
                prefix = kind[:-1]
                snapshot = [e for e in snapshot
                            if e["kind"].startswith(prefix)]
            else:
                snapshot = [e for e in snapshot if e["kind"] == kind]
        if limit > 0:
            snapshot = snapshot[-limit:]
        return snapshot

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def clear(self):
        with self._lock:
            self._ring.clear()

    # -- post-mortem dump ----------------------------------------------------
    def dump(self, reason: str, path: str | None = None,
             extra: dict | None = None) -> Optional[str]:
        """Drain the ring into a JSONL artifact: one header object (the
        reason + event count), then one event per line, oldest first.
        Returns the artifact path, or ``None`` when nothing could be
        written — a failing post-mortem writer must never mask the
        failure being post-mortemed. The ring is NOT cleared: a second
        failure in the same process still sees the shared history."""
        events = self.events()
        try:
            if path is None:
                directory = self._dump_dir()
                os.makedirs(directory, exist_ok=True)
                safe = "".join(c if c.isalnum() or c in "-_" else "-"
                               for c in str(reason)) or "dump"
                path = os.path.join(
                    directory,
                    f"flight-{safe}-{int(time.time() * 1000)}"
                    f"-{os.getpid()}.jsonl")
            else:
                directory = os.path.dirname(path)
                if directory:
                    os.makedirs(directory, exist_ok=True)
            header = {"flight_dump": True, "reason": str(reason),
                      "t": time.time(), "events": len(events),
                      "pid": os.getpid()}
            if extra:
                header.update(extra)
            with open(path, "w") as fp:
                fp.write(json.dumps(header, default=str) + "\n")
                for event in events:
                    fp.write(json.dumps(event, default=str) + "\n")
        except Exception:  # noqa: BLE001 - never raise out of a post-mortem
            return None
        with self._lock:
            self.dumps += 1
            self.last_dump_path = path
        return path


# process-wide recorder: trainer, monitor, engines, breakers, chaos
_recorder = FlightRecorder()


def get_flight_recorder() -> FlightRecorder:
    return _recorder


def record(kind: str, **data) -> dict:
    """Module-level convenience for the one process-wide recorder."""
    return _recorder.record(kind, **data)


def configure_from_mlconf() -> FlightRecorder:
    """Apply ``mlconf.observability.flight`` (ring size, dump dir) to the
    process recorder; lazy config import keeps this module bottom-layer."""
    try:
        from ..config import mlconf

        conf = mlconf.observability.get("flight")
        if conf is None:
            return _recorder
        ring = conf.get("ring")
        directory = str(conf.get("dir", "") or "")
        _recorder.configure(ring=int(ring) if ring else None,
                            directory=directory or None)
    except Exception:  # noqa: BLE001 - observability must not block startup
        pass
    return _recorder
