"""Lightweight cross-service tracer — spans, the ``X-MLT-Trace`` header
contract, and JSONL/ring export.

A request entering the serving gateway gets a root span; each graph step,
outbound ``RemoteStep``/``BatchHttpRequests`` call, and LLM scheduler
phase (prefill/decode) becomes a child span. The trace id rides the
``X-MLT-Trace: <trace_id>-<parent_span_id>`` header across HTTP hops, so
a nested GraphServer's spans join the caller's trace — the span JSONL of
both sides shares one trace id and the parent links line up. The run
lifecycle (submit → schedule → running → retry/resume) uses a
deterministic trace id derived from the run uid (:func:`trace_id_for`),
so every monitor decision about a run lands on one timeline.

Export targets:

- an in-memory ring (always on; tests and ``/__stats__``-style
  introspection read it), and
- a JSONL file (one span object per line) when a path is configured —
  the per-run span artifact that can be joined with an XLA device trace
  in TensorBoard because ``utils/profiler.annotate`` stamps the active
  trace id into ``jax.profiler.TraceAnnotation`` region names.

Stdlib only (same bottom-layer rule as ``obs/metrics.py`` and
``chaos/registry.py``): the tracer must be importable below every layer
that emits spans.
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import os
import threading
import time
import uuid
from collections import deque
from dataclasses import dataclass, field
from typing import Optional

# header the serving/service layers understand (case-insensitive):
#   X-MLT-Trace: <32-hex trace id>-<16-hex parent span id>
# (a bare trace id with no span part is accepted too)
TRACE_HEADER = "x-mlt-trace"

_HEX = set("0123456789abcdef")


def _is_hex(value: str) -> bool:
    return bool(value) and set(value) <= _HEX


def new_trace_id() -> str:
    return uuid.uuid4().hex


def new_span_id() -> str:
    return uuid.uuid4().hex[:16]


def trace_id_for(seed: str) -> str:
    """Deterministic trace id for an out-of-band correlation key (run
    uid): every lifecycle span of one run shares a trace without any
    header plumbing through k8s manifests."""
    return hashlib.md5(str(seed).encode()).hexdigest()  # noqa: S324


def _header_text(value) -> str:
    """Header keys/values may arrive as bytes from raw ASGI/WSGI layers;
    decode rather than str() (which would mangle b"x-mlt-trace" into
    "b'x-mlt-trace'" and silently drop the caller's trace)."""
    if isinstance(value, (bytes, bytearray)):
        return bytes(value).decode("latin-1", "replace")
    return str(value)


def parse_trace_header(headers: dict | None
                       ) -> tuple[Optional[str], Optional[str]]:
    """(trace_id, parent_span_id) from request headers; (None, None) when
    absent or malformed — a garbage header must never fail a request.
    The contract is load-bearing for cross-replica trace assembly
    (docs/observability.md), so malformed shapes (mixed-case names, bare
    trace ids, overlong/non-hex/empty span parts, bytes values) are
    pinned by tests."""
    if not headers:
        return None, None
    value = None
    for key, candidate in headers.items():
        if _header_text(key).lower() == TRACE_HEADER:
            value = _header_text(candidate)
            break
    if not value:
        return None, None
    trace_id, _, parent = value.strip().lower().partition("-")
    if not _is_hex(trace_id) or len(trace_id) > 64:
        return None, None
    if parent and (not _is_hex(parent) or len(parent) > 32):
        parent = ""
    return trace_id, parent or None


def format_trace_header(trace_id: str, span_id: str | None = None) -> str:
    return f"{trace_id}-{span_id}" if span_id else trace_id


@dataclass
class Span:
    name: str
    trace_id: str
    span_id: str = field(default_factory=new_span_id)
    parent_id: Optional[str] = None
    start: float = field(default_factory=time.time)
    end: Optional[float] = None
    status: str = "ok"
    attrs: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start": self.start,
            "end": self.end,
            "duration_s": (self.end - self.start)
            if self.end is not None else None,
            "status": self.status,
            "attrs": self.attrs,
        }


class Tracer:
    """Span factory + exporter. One process-wide instance by default
    (:func:`get_tracer`); tests may build isolated instances (e.g. one
    per GraphServer) to assert on each side of an HTTP hop."""

    # JSONL rotation default: one predecessor kept, so the on-disk span
    # footprint of a long-running replica is bounded at ~2x this
    DEFAULT_MAX_BYTES = 64 * 1024 * 1024

    def __init__(self, ring: int = 2048, path: str | None = None,
                 max_bytes: int | None = None):
        self._ring: deque[Span] = deque(maxlen=max(1, int(ring)))
        self._path = path or None
        self._max_bytes = int(max_bytes if max_bytes is not None
                              else self.DEFAULT_MAX_BYTES)
        self._size: Optional[int] = None  # bytes in the active file
        self._file_lock = threading.Lock()
        self._local = threading.local()
        self._lock = threading.Lock()

    # -- configuration -------------------------------------------------------
    def configure(self, path: str | None = None, ring: int | None = None,
                  max_bytes: int | None = None):
        if ring is not None:
            with self._lock:
                self._ring = deque(self._ring, maxlen=max(1, int(ring)))
        if path is not None:
            with self._file_lock:
                self._path = path or None
                self._size = None  # re-measured on the next export
        if max_bytes is not None:
            self._max_bytes = int(max_bytes)
        return self

    @property
    def path(self) -> Optional[str]:
        return self._path

    # -- span lifecycle ------------------------------------------------------
    def _stack(self) -> list:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def current(self) -> Optional[Span]:
        """Innermost active span on THIS thread (None off-request)."""
        stack = self._stack()
        return stack[-1] if stack else None

    def start_span(self, name: str, trace_id: str | None = None,
                   parent_id: str | None = None, attrs: dict | None = None,
                   activate: bool = False) -> Span:
        """Open a span. Without an explicit trace/parent the thread's
        current span (if any) becomes the parent; otherwise a fresh
        trace starts. ``activate`` pushes it on the thread-local stack so
        nested code (engine submit, outbound calls) sees it as current."""
        if trace_id is None:
            current = self.current()
            if current is not None:
                trace_id = current.trace_id
                if parent_id is None:
                    parent_id = current.span_id
            else:
                trace_id = new_trace_id()
        span = Span(name=name, trace_id=trace_id, parent_id=parent_id,
                    attrs=dict(attrs or {}))
        if activate:
            self._stack().append(span)
        return span

    def end_span(self, span: Span, status: str | None = None):
        if span.end is not None:
            return
        span.end = time.time()
        if status:
            span.status = status
        stack = self._stack()
        if stack and stack[-1] is span:
            stack.pop()
        self._export(span)

    @contextlib.contextmanager
    def span(self, name: str, trace_id: str | None = None,
             parent_id: str | None = None, attrs: dict | None = None):
        """Context-managed activated span; errors mark status and
        propagate."""
        span = self.start_span(name, trace_id=trace_id, parent_id=parent_id,
                               attrs=attrs, activate=True)
        try:
            yield span
        except BaseException:
            self.end_span(span, status="error")
            raise
        self.end_span(span)

    def emit(self, name: str, trace_id: str, parent_id: str | None = None,
             start: float | None = None, end: float | None = None,
             status: str = "ok", attrs: dict | None = None) -> Span:
        """Record an already-finished span (scheduler phases measured with
        perf counters resolve start/end after the fact)."""
        now = time.time()
        span = Span(name=name, trace_id=trace_id, parent_id=parent_id,
                    start=start if start is not None else now,
                    status=status, attrs=dict(attrs or {}))
        span.end = end if end is not None else now
        self._export(span)
        return span

    # -- header propagation --------------------------------------------------
    def inject(self, headers: dict | None = None,
               span: Span | None = None) -> dict:
        """Headers dict carrying the trace context of ``span`` (or the
        thread's current span). A copy is returned; absent context leaves
        the headers untouched."""
        headers = dict(headers or {})
        span = span or self.current()
        if span is not None:
            headers["X-MLT-Trace"] = format_trace_header(
                span.trace_id, span.span_id)
        return headers

    # -- export --------------------------------------------------------------
    def _export(self, span: Span):
        with self._lock:
            self._ring.append(span)
        path = self._path
        if path:
            try:
                line = json.dumps(span.to_dict(), default=str) + "\n"
                with self._file_lock:
                    directory = os.path.dirname(path)
                    if directory:
                        os.makedirs(directory, exist_ok=True)
                    if self._size is None:
                        try:
                            self._size = os.path.getsize(path)
                        except OSError:
                            self._size = 0
                    # size-capped rotation (mlconf.observability.
                    # trace_max_bytes): rotate BEFORE the write that
                    # would cross the cap, keeping exactly one `.1`
                    # predecessor — a long-running emit loop never holds
                    # more than 2x the cap on disk
                    if self._max_bytes > 0 and self._size \
                            and self._size + len(line) > self._max_bytes:
                        os.replace(path, path + ".1")
                        self._size = 0
                    with open(path, "a") as fp:
                        fp.write(line)
                    self._size += len(line)
            except OSError:
                # span export must never fail the traced operation
                pass

    # -- introspection (tests / smoke) ---------------------------------------
    def spans(self, trace_id: str | None = None,
              name: str | None = None) -> list[Span]:
        with self._lock:
            snapshot = list(self._ring)
        return [s for s in snapshot
                if (trace_id is None or s.trace_id == trace_id)
                and (name is None or s.name == name)]

    def clear(self):
        with self._lock:
            self._ring.clear()


# process-wide tracer: serving gateway, service API, engines, run monitor
tracer = Tracer()


def get_tracer() -> Tracer:
    return tracer


def configure_from_mlconf():
    """Apply ``mlconf.observability`` to the global tracer (called by the
    serving gateway and service entrypoints; imports config lazily so
    this module stays bottom-layer)."""
    try:
        from ..config import mlconf

        obs_conf = mlconf.get("observability")
        if obs_conf is None:
            return tracer
        path = str(obs_conf.get("trace_path") or "") or None
        ring = obs_conf.get("trace_ring")
        max_bytes = obs_conf.get("trace_max_bytes")
        tracer.configure(path=path, ring=int(ring) if ring else None,
                         max_bytes=(int(max_bytes)
                                    if max_bytes is not None else None))
    except Exception:  # noqa: BLE001 - observability must not block startup
        pass
    return tracer
