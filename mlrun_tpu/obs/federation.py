"""Metrics federation: Prometheus text parsing + fleet-wide aggregation
(docs/observability.md "Federation").

PR 4 gave every process a registry and a ``/metrics`` endpoint; PR 7 put
N engine replicas behind one fleet. Nothing could *see across* them: a
replica's queue depth, page headroom, and TTFT histogram are meaningless
for scaling decisions until they are merged into one fleet-wide view.
This module is that ingest path:

- :func:`parse_prometheus` — the strict text-format (0.0.4) parser that
  previously lived in ``tests/test_observability.py``; promoted here so
  the federation ingest and the format tests share one source of truth.
- :class:`MetricsAggregator` — ingests per-replica ``/metrics`` scrapes
  (``ingest_text``) and in-process ``EngineFleet.stats()`` feeds
  (``ingest_stats``) into one merged view with per-family merge
  semantics: counters and histogram samples SUM across sources, gauges
  take the newest source's value (``"last"``) or ``"max"``/``"sum"``
  per family. The PR 7 ``replica`` label is preserved verbatim — two
  replicas' ``mlt_llm_queue_depth{replica=...}`` series stay distinct;
  merging only collapses *identical* (name, label-set) series reported
  by different sources.
- Staleness bounds: a source not refreshed within ``stale_after``
  seconds drops out of the merged view (a dead replica must not pin its
  last queue depth into the autoscaler's signals forever).
- Cardinality budget: total series across live sources is bounded;
  overflow drops deterministically and counts, so a misbehaving replica
  cannot multiply series unboundedly through the federation layer.

Design constraints (mirrors ``obs/metrics.py``): stdlib only at module
level — ``from_mlconf`` constructors lazy-import the config.
"""

from __future__ import annotations

import math
import re
import threading
from typing import Optional

_SAMPLE_RE = re.compile(
    r'^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)'
    r'(?:\{(?P<labels>.*)\})?'
    r' (?P<value>[+-]?(?:[0-9]+(?:\.[0-9]+)?(?:e[+-]?[0-9]+)?|Inf|NaN))$',
    re.IGNORECASE)
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')

_HISTOGRAM_SUFFIXES = ("_bucket", "_sum", "_count")


class PromParseError(ValueError):
    """A scrape violated the exposition format contract."""


# quote-aware labels group (a literal "}" inside an escaped label value
# must not end the clause early) + the same Inf/NaN value forms the
# sample regex accepts — the renderer's own output must always parse,
# or one odd exemplar poisons a replica's entire federated scrape
_EXEMPLAR_LABEL = r'[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*"'
_EXEMPLAR_RE = re.compile(
    r'^\{(?P<labels>(?:' + _EXEMPLAR_LABEL +
    r'(?:,' + _EXEMPLAR_LABEL + r')*)?)\} '
    r'(?P<value>[+-]?(?:[0-9]+(?:\.[0-9]+)?(?:e[+-]?[0-9]+)?|Inf|NaN))'
    r'(?: (?P<ts>[0-9]+(?:\.[0-9]+)?))?$', re.IGNORECASE)


def parse_exposition(text: str):
    """Parse Prometheus text exposition (format 0.0.4) — and the
    OpenMetrics variant our renderer produces (``# EOF`` trailer plus
    per-bucket exemplar clauses after `` # ``).

    Returns ``(samples, types, exemplars)`` where ``samples`` maps
    ``(name, frozenset((label, value), ...))`` to a float, ``types``
    maps each family name to ``counter``/``gauge``/``histogram``, and
    ``exemplars`` maps sample keys to
    ``{"labels", "value", "ts"}`` dicts.

    Strict by design — this parses OUR renderer's output (and sibling
    replicas running the same code), so any malformed line, unknown
    comment, or typed family without a HELP line raises
    :class:`PromParseError` instead of being skipped.
    """
    samples: dict[tuple, float] = {}
    types: dict[str, str] = {}
    exemplars: dict[tuple, dict] = {}
    helped: set[str] = set()
    for line in text.strip().splitlines():
        if not line:
            continue
        if line == "# EOF":
            continue  # OpenMetrics trailer
        if line.startswith("# HELP "):
            helped.add(line.split()[2])
            continue
        if line.startswith("# TYPE "):
            _, _, family, type_name = line.split(maxsplit=3)
            if type_name not in ("counter", "gauge", "histogram"):
                raise PromParseError(f"unknown metric type: {line!r}")
            types[family] = type_name
            continue
        if line.startswith("#"):
            raise PromParseError(f"unknown comment line: {line!r}")
        exemplar = None
        # exemplar detection guards: split at the LAST " # ", require a
        # "{"-opening clause AND a well-formed sample on the left — a
        # literal " # {" inside a quoted label value (label values are
        # client-supplied, e.g. adapter ids) must fall through to the
        # whole-line sample parse, not poison the scrape as a
        # "malformed exemplar"
        sample_part, sep, exemplar_part = line.rpartition(" # ")
        if sep and exemplar_part.lstrip().startswith("{") \
                and _SAMPLE_RE.match(sample_part):
            ex_match = _EXEMPLAR_RE.match(exemplar_part.strip())
            if not ex_match:
                raise PromParseError(f"malformed exemplar: {line!r}")
            exemplar = {
                "labels": dict(_LABEL_RE.findall(
                    ex_match.group("labels") or "")),
                "value": float(ex_match.group("value")),
                "ts": (float(ex_match.group("ts"))
                       if ex_match.group("ts") else None),
            }
            line = sample_part
        match = _SAMPLE_RE.match(line)
        if not match:
            raise PromParseError(f"malformed sample line: {line!r}")
        labels = frozenset(_LABEL_RE.findall(match.group("labels") or ""))
        value = match.group("value")
        key = (match.group("name"), labels)
        samples[key] = (
            math.inf if value == "+Inf"
            else -math.inf if value == "-Inf" else float(value))
        if exemplar is not None:
            exemplars[key] = exemplar
    if not set(types) <= helped:
        raise PromParseError(
            f"typed families missing HELP: {sorted(set(types) - helped)}")
    return samples, types, exemplars


def parse_prometheus(text: str):
    """Back-compat two-tuple view of :func:`parse_exposition` (the
    format tests and every pre-exemplar caller use this shape)."""
    samples, types, _ = parse_exposition(text)
    return samples, types


def check_histogram_consistency(samples: dict, family: str):
    """Assert ``family``'s bucket series are cumulative and
    non-decreasing, ``+Inf`` equals ``_count``, and ``_sum`` is present —
    per label group. Raises :class:`PromParseError` on violation (the
    merged view must stay a valid histogram, not just each source)."""
    groups: dict[frozenset, dict] = {}
    for (name, labels), value in samples.items():
        if not name.startswith(family):
            continue
        suffix = name[len(family):]
        if suffix not in _HISTOGRAM_SUFFIXES:
            continue
        base = frozenset(kv for kv in labels if kv[0] != "le")
        groups.setdefault(base, {})[
            (suffix, dict(labels).get("le"))] = value
    if not groups:
        raise PromParseError(f"no samples for histogram {family}")
    for base, series in groups.items():
        buckets = sorted(
            ((math.inf if le == "+Inf" else float(le)), value)
            for (suffix, le), value in series.items()
            if suffix == "_bucket")
        counts = [value for _, value in buckets]
        if counts != sorted(counts):
            raise PromParseError(
                f"non-cumulative buckets for {family}: {sorted(base)}")
        if not buckets or buckets[-1][0] != math.inf:
            raise PromParseError(f"{family} missing +Inf bucket")
        if buckets[-1][1] != series.get(("_count", None)):
            raise PromParseError(
                f"{family} +Inf bucket != _count: {sorted(base)}")
        if ("_sum", None) not in series:
            raise PromParseError(f"{family} missing _sum: {sorted(base)}")


def sample_kind(name: str, types: dict) -> tuple[str, str]:
    """Resolve a sample line's merge family + kind: histogram component
    samples (``_bucket``/``_sum``/``_count``) map back to their base
    family, an OpenMetrics counter sample (``foo_total`` under
    ``# TYPE foo counter``) back to its stripped family; unknown names
    default to gauge semantics."""
    if name in types:
        return name, types[name]
    for suffix in _HISTOGRAM_SUFFIXES:
        if name.endswith(suffix):
            base = name[: -len(suffix)]
            if types.get(base) == "histogram":
                return base, "histogram"
    if name.endswith("_total") \
            and types.get(name[: -len("_total")]) == "counter":
        return name[: -len("_total")], "counter"
    return name, "gauge"


class _Source:
    __slots__ = ("samples", "types", "at", "exemplars")

    def __init__(self, samples: dict, types: dict, at: float,
                 exemplars: Optional[dict] = None):
        self.samples = samples
        self.types = types
        self.at = float(at)
        self.exemplars = exemplars or {}


class MetricsAggregator:
    """Merged fleet-wide view over per-source sample sets.

    ``gauge_merge`` maps a gauge family to ``"last"`` (newest source
    wins — the default), ``"max"``, or ``"sum"`` for the rare gauge
    where cross-source addition is meaningful (e.g. in-flight counts).
    Counters and histograms always sum.

    Feed each underlying producer through exactly ONE channel — either
    its ``/metrics`` scrape or its in-process stats feed — or the merged
    counters double-count.
    """

    def __init__(self, stale_after: float = 60.0,
                 max_series: int = 4096,
                 gauge_merge: Optional[dict] = None):
        if stale_after <= 0:
            raise ValueError("stale_after must be > 0")
        if max_series <= 0:
            raise ValueError("max_series must be > 0")
        self.stale_after = float(stale_after)
        self.max_series = int(max_series)
        self.gauge_merge = dict(gauge_merge or {})
        self.dropped_series = 0  # series lost to the cardinality budget
        self._lock = threading.Lock()
        self._sources: dict[str, _Source] = {}

    @classmethod
    def from_mlconf(cls, **overrides) -> "MetricsAggregator":
        from ..config import mlconf

        fed = mlconf.observability.federation
        kwargs = {"stale_after": float(fed.stale_after_s),
                  "max_series": int(fed.max_series)}
        kwargs.update(overrides)
        return cls(**kwargs)

    # -- ingest --------------------------------------------------------------
    def ingest_text(self, source: str, text: str, at: float):
        """Ingest one ``/metrics`` scrape from ``source`` (replaces the
        source's previous sample set). ``at`` is the scrape timestamp —
        passed explicitly so staleness is testable without wall-clock
        sleeps. Raises :class:`PromParseError` on a malformed scrape.
        An OpenMetrics scrape's exemplars ride along with their bucket
        samples (readable via :meth:`exemplars`) without counting
        against the cardinality budget — they are annotations on
        existing series, not series."""
        samples, types, exemplars = parse_exposition(text)
        self._store(source, samples, types, at, exemplars)

    def ingest_stats(self, source: str, stats: dict, at: float,
                     engine: str = "fleet"):
        """Ingest an in-process ``EngineFleet.stats`` feed, mapped onto
        the same canonical families a scrape produces so the merged view
        is uniform:

        - per-replica ``queue_depth`` / ``free_page_frac`` →
          ``mlt_llm_queue_depth`` / ``mlt_llm_free_page_frac`` gauges,
        - per-replica cumulative ``requests``/``completed`` →
          ``mlt_llm_events_total`` counters,
        - fleet dispatch counters → ``mlt_fleet_dispatches_total``
          (outcome ok/redispatch/failed/no_replica),
        - fleet TTFT percentiles → ``mlt_fleet_ttft_seconds`` gauges
          with a ``quantile`` label.
        """
        samples: dict[tuple, float] = {}
        types = {"mlt_llm_queue_depth": "gauge",
                 "mlt_llm_free_page_frac": "gauge",
                 "mlt_llm_events_total": "counter",
                 "mlt_fleet_dispatches_total": "counter",
                 "mlt_fleet_ttft_seconds": "gauge"}

        def put(name, value, **labels):
            samples[(name, frozenset(labels.items()))] = float(value)

        for rid, per in (stats.get("per_replica") or {}).items():
            if "queue_depth" in per:
                put("mlt_llm_queue_depth", per["queue_depth"],
                    engine=engine, replica=rid)
            if per.get("free_page_frac") is not None:
                put("mlt_llm_free_page_frac", per["free_page_frac"],
                    engine=engine, replica=rid)
            for event in ("requests", "completed"):
                if event in per:
                    put("mlt_llm_events_total", per[event],
                        engine=engine, replica=rid, event=event)
        for key, outcome in (("dispatches", "ok"),
                             ("redispatches", "redispatch"),
                             ("failed", "failed"),
                             ("no_replica", "no_replica")):
            if key in stats:
                put("mlt_fleet_dispatches_total", stats[key],
                    replica="", outcome=outcome)
        for key, quantile in (("ttft_p50_s", "0.5"), ("ttft_p95_s", "0.95")):
            if key in stats:
                put("mlt_fleet_ttft_seconds", stats[key],
                    quantile=quantile)
        self._store(source, samples, types, at)

    def _store(self, source: str, samples: dict, types: dict, at: float,
               exemplars: Optional[dict] = None):
        with self._lock:
            # evict sources already past the staleness bound relative to
            # this scrape — a dead replica's frozen sample set must not
            # keep consuming the cardinality budget (if it comes back,
            # its next scrape re-ingests in full)
            for name in [n for n, s in self._sources.items()
                         if n != source and at - s.at > self.stale_after]:
                del self._sources[name]
            other = sum(len(s.samples) for name, s in self._sources.items()
                        if name != source)
            allowed = self.max_series - other
            if len(samples) > allowed:
                # deterministic truncation: keep the lexicographically
                # first `allowed` series so repeated over-budget scrapes
                # drop the SAME tail, not a churning random subset
                keep = sorted(samples, key=lambda k: (k[0], sorted(k[1])))
                dropped = len(samples) - max(allowed, 0)
                self.dropped_series += dropped
                samples = {key: samples[key]
                           for key in keep[:max(allowed, 0)]}
            if exemplars:
                # exemplars never extend the series set — one whose
                # bucket sample fell to the truncation goes with it
                exemplars = {key: ex for key, ex in exemplars.items()
                             if key in samples}
            self._sources[source] = _Source(samples, types, at, exemplars)

    def forget(self, source: str):
        """Drop a source outright (a removed replica's scrape target)."""
        with self._lock:
            self._sources.pop(source, None)

    # -- merged view ---------------------------------------------------------
    def sources(self, now: float) -> dict:
        """Per-source freshness: ``{name: {at, fresh, series}}``."""
        with self._lock:
            return {name: {"at": src.at,
                           "fresh": now - src.at <= self.stale_after,
                           "series": len(src.samples)}
                    for name, src in self._sources.items()}

    def _fresh(self, now: float) -> list[tuple[str, _Source]]:
        return [(name, src) for name, src in sorted(self._sources.items())
                if now - src.at <= self.stale_after]

    def merged(self, now: float):
        """The fleet-wide view at ``now``: ``(samples, types)`` in the
        same shape :func:`parse_prometheus` returns, merged across fresh
        sources with per-family semantics."""
        with self._lock:
            fresh = self._fresh(now)
            merged: dict[tuple, float] = {}
            newest: dict[tuple, float] = {}
            types: dict[str, str] = {}
            for _, src in fresh:
                types.update(src.types)
            for _, src in fresh:
                for key, value in src.samples.items():
                    family, kind = sample_kind(key[0], types)
                    if key not in merged:
                        merged[key] = value
                        newest[key] = src.at
                        continue
                    if kind in ("counter", "histogram"):
                        merged[key] += value
                    else:
                        mode = self.gauge_merge.get(family, "last")
                        if mode == "sum":
                            merged[key] += value
                        elif mode == "max":
                            merged[key] = max(merged[key], value)
                        elif src.at >= newest[key]:  # last
                            merged[key] = value
                            newest[key] = src.at
        return merged, types

    def snapshot_to(self, store, now: float):
        """Record the fleet view into a ``TimeSeriesStore``: gauges from
        the merged view, but counter/histogram samples PER SOURCE (extra
        ``source`` label). A summed cumulative series would DROP when a
        source goes stale or is forgotten, and the store's reset
        convention would read that drop as a counter restart — inflating
        windowed ``increase()``/``quantile()`` by the survivors' full
        totals. Per-source rings just go quiet instead. Windowed reads
        sum across label sets, so fleet-wide queries are unchanged."""
        samples, types = self.merged(now)
        for (name, labels), value in samples.items():
            _, kind = sample_kind(name, types)
            if kind == "gauge" and math.isfinite(value):
                store.record(name, value, now, labels=dict(labels),
                             kind="gauge")
        with self._lock:
            fresh = self._fresh(now)
        for src_name, src in fresh:
            for (name, labels), value in src.samples.items():
                _, kind = sample_kind(name, src.types)
                if kind in ("counter", "histogram") \
                        and math.isfinite(value):
                    store.record(
                        name, value, now,
                        labels={**dict(labels), "source": src_name},
                        kind="counter")

    # -- queries -------------------------------------------------------------
    def value(self, name: str, now: float, **labels) -> Optional[float]:
        samples, _ = self.merged(now)
        return samples.get((name, frozenset(
            {k: str(v) for k, v in labels.items()}.items())))

    def family(self, name: str, now: float) -> dict:
        """Exact-name samples: ``{labels-frozenset: value}``."""
        samples, _ = self.merged(now)
        return {labels: value for (n, labels), value in samples.items()
                if n == name}

    def label_values(self, name: str, label: str, now: float) -> set:
        """Distinct values of ``label`` across ``name``'s merged series
        (e.g. the live ``replica`` set under ``mlt_llm_queue_depth``)."""
        return {dict(labels).get(label)
                for labels in self.family(name, now)
                if dict(labels).get(label) is not None}

    def series_count(self, now: float) -> int:
        samples, _ = self.merged(now)
        return len(samples)

    def sum_family(self, name: str, now: float,
                   match: Optional[dict] = None) -> float:
        """Sum a family's merged samples, optionally filtered by a label
        subset — the fleet-total shortcut the autoscaler's signals use."""
        match_items = set((match or {}).items())
        return sum(value for labels, value in self.family(name, now).items()
                   if match_items <= set(labels))

    def min_family(self, name: str, now: float) -> Optional[float]:
        values = list(self.family(name, now).values())
        return min(values) if values else None

    # -- exemplars -----------------------------------------------------------
    def exemplars(self, family: str, now: float,
                  match: Optional[dict] = None) -> list[dict]:
        """Exemplars carried through fresh sources for ``family``'s
        bucket series (label-subset filtered): ``{source, series, le,
        value, labels, ts}`` entries, the same shape the in-process
        ``Histogram.exemplars`` read produces — so the SLO evaluator's
        breach-forensics lookup works over a federated view too."""
        match_items = set((k, str(v)) for k, v in (match or {}).items())
        with self._lock:
            fresh = self._fresh(now)
        out = []
        for src_name, src in fresh:
            for (name, labels), exemplar in src.exemplars.items():
                if name != family + "_bucket":
                    continue
                series = dict(labels)
                le = series.pop("le", None)
                if not match_items <= set(
                        (k, str(v)) for k, v in series.items()):
                    continue
                out.append({
                    "source": src_name, "series": series,
                    "le": (math.inf if le == "+Inf"
                           else float(le) if le else None),
                    "value": exemplar["value"],
                    "labels": dict(exemplar["labels"]),
                    "ts": exemplar.get("ts"),
                })
        return out

    def breach_exemplars(self, family: str, labels: Optional[dict],
                         threshold: float, k: int,
                         now: Optional[float] = None) -> list[dict]:
        """The federated counterpart of ``obs.slo.registry_exemplars``
        — same (family, labels, threshold, k) lookup signature, so a
        central evaluator over remote replicas' OpenMetrics scrapes
        wires it in as ``SLOEvaluator(..., exemplar_lookup=
        aggregator.breach_exemplars)``: top-``k`` carried exemplars by
        value over ``threshold``. ``now`` defaults to the wall clock
        (the production adapter; tests pass it explicitly)."""
        import time

        found = self.exemplars(family,
                               time.time() if now is None else now,
                               match=labels)
        over = [e for e in found if e["value"] > threshold]
        return sorted(over, key=lambda e: -e["value"])[:max(0, int(k))]
