"""Shared handler cores for the ``/debug`` endpoints
(docs/observability.md "Flight recorder & debug endpoints").

The serving gateway (``serving/asgi.py``) and the service API
(``service/api/operations.py``) expose the same ``/debug/flight``,
``/debug/trace/<trace_id>`` and ``/debug/profile`` contract; the
parsing, validation, and response shapes live HERE once so the two
route layers stay thin and cannot drift. Both cores raise
``ValueError`` on a bad request — the route layer maps that to its own
400 envelope.

Safety: the profile endpoints are reachable over HTTP (the gateway one
without auth, like ``/__drain__``), so client-supplied ``output_dir``
is REJECTED — traces always land under the process's default trace dir
— and ``key`` is restricted to a path-segment-safe charset so it cannot
traverse out of it. ``/debug/trace`` validates the trace id against the
header contract's hex charset before it goes anywhere near a peer URL.
"""

from __future__ import annotations

import re

from .flight import get_flight_recorder

_SAFE_KEY_RE = re.compile(r"^[A-Za-z0-9._-]{1,64}$")
_TRACE_ID_RE = re.compile(r"^[0-9a-f]{1,64}$")


def flight_snapshot(kind: str = "", limit=0) -> dict:
    """The GET /debug/flight payload: ring snapshot (oldest first,
    optional exact/``prefix.*`` kind filter, newest-N ``limit``), dump
    count, and the last post-mortem artifact path."""
    try:
        limit = int(limit or 0)
    except (TypeError, ValueError):
        raise ValueError("limit must be an int")
    recorder = get_flight_recorder()
    return {
        "events": recorder.events(kind=(kind or None), limit=limit),
        "ring": len(recorder),
        "dumps": recorder.dumps,
        "last_dump": recorder.last_dump_path,
    }


def trace_peers() -> list[str]:
    """Peer base URLs whose span rings join the waterfall
    (``mlconf.observability.trace_peers`` — process replicas behind
    ``RemoteStep``/the fleet; in-process replicas already share the
    process tracer's ring)."""
    try:
        from ..config import mlconf

        return [str(p) for p in
                (mlconf.observability.get("trace_peers") or [])]
    except Exception:  # noqa: BLE001 - config must not break a debug read
        return []


def trace_peer_timeout() -> float:
    """Per-peer fan-out timeout (``mlconf.observability.
    trace_peer_timeout_s``) — resolved HERE so the two route layers
    stay thin and cannot drift."""
    try:
        from ..config import mlconf

        return float(mlconf.observability.get("trace_peer_timeout_s",
                                              1.0))
    except Exception:  # noqa: BLE001 - config must not break a debug read
        return 1.0


def trace_snapshot(trace_id: str, peers=None, timeout: float | None = None,
                   local_only: bool = False) -> dict:
    """The GET /debug/trace/<trace_id> payload: one assembled waterfall
    (docs/observability.md "Request attribution, exemplars & trace
    assembly").

    Reads the local span ring, then fans out to each peer replica's
    ``/debug/trace`` (``local=1`` so peers never re-fan) with a
    PER-REPLICA timeout — a dead replica degrades the waterfall (its
    entry lands in ``sources`` with the error and ``partial`` flips
    true), it never 504s the assembly. On the merged spans the blocking
    critical path and per-phase totals are computed
    (``obs/traceview.py``)."""
    trace_id = str(trace_id or "").strip().lower()
    if not _TRACE_ID_RE.match(trace_id):
        raise ValueError("trace id must be 1-64 hex chars (the "
                         "X-MLT-Trace contract)")
    if timeout is None:
        timeout = trace_peer_timeout()
    from .tracing import get_tracer
    from .traceview import assemble, merge_spans

    local = [span.to_dict()
             for span in get_tracer().spans(trace_id=trace_id)]
    sources: dict = {"local": {"spans": len(local), "ok": True}}
    span_sets = [local]
    partial = False
    if not local_only:
        peer_list = trace_peers() if peers is None else list(peers)
        if peer_list:
            import concurrent.futures
            import time

            import requests

            def fetch(peer):
                url = (f"{str(peer).rstrip('/')}/debug/trace/"
                       f"{trace_id}?local=1")
                resp = requests.get(url, timeout=timeout)
                resp.raise_for_status()
                return resp.json().get("spans") or []

            # concurrent fan-out under a WALL deadline: every peer gets
            # one thread (bounded) and the whole assembly waits at most
            # ~2x the per-peer timeout — a dead or byte-dribbling
            # replica (requests' timeout= is per-read, not wall) lands
            # in `sources` as failed instead of stalling the forensics
            # read; its straggler thread is abandoned, never joined
            pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=min(32, len(peer_list)))
            try:
                futures = {str(peer): pool.submit(fetch, peer)
                           for peer in peer_list}
                deadline = time.monotonic() + 2.0 * timeout
                for peer, future in futures.items():
                    try:
                        peer_spans = future.result(timeout=max(
                            0.0, deadline - time.monotonic()))
                        span_sets.append(peer_spans)
                        sources[peer] = {"spans": len(peer_spans),
                                         "ok": True}
                    except Exception as exc:  # noqa: BLE001 - a dead
                        # replica degrades the waterfall, never 504s it
                        sources[peer] = {"ok": False,
                                         "error": str(exc) or
                                         type(exc).__name__}
                        partial = True
            finally:
                pool.shutdown(wait=False, cancel_futures=True)
    out = assemble(trace_id, merge_spans(*span_sets))
    out["sources"] = sources
    out["partial"] = partial
    return out


def profile_request(body: dict) -> dict:
    """The POST /debug/profile core: disarm, or arm an on-demand XLA
    capture for the next N steps/seconds (``utils/profiler``)."""
    from ..utils.profiler import arm_profile, disarm_profile

    body = body or {}
    if body.get("disarm"):
        # stop_active: the HTTP disarm is the operator remedy for a
        # capture whose claiming loop went away
        return {"disarmed": disarm_profile(stop_active=True)}
    if body.get("output_dir"):
        # the arm request crosses an HTTP boundary: a client-chosen
        # directory would be an arbitrary-path write primitive
        raise ValueError("output_dir is not accepted over HTTP — traces "
                         "land under the process's default trace dir")
    key = str(body.get("key", "") or "xla-trace")
    if not _SAFE_KEY_RE.match(key) or not key.strip("."):
        # a pure-dot key ("." / "..") would resolve OUT of the traces
        # dir despite matching the charset
        raise ValueError("key must match [A-Za-z0-9._-]{1,64} and "
                         "contain a non-dot character")
    try:
        steps = int(body.get("steps", 0) or 0)
        seconds = float(body.get("seconds", 0) or 0)
    except (TypeError, ValueError):
        raise ValueError("steps must be an int and seconds a number")
    return arm_profile(steps=steps, seconds=seconds, key=key)
