"""Shared handler cores for the ``/debug`` endpoints
(docs/observability.md "Flight recorder & debug endpoints").

The serving gateway (``serving/asgi.py``) and the service API
(``service/api/operations.py``) expose the same ``/debug/flight`` and
``/debug/profile`` contract; the parsing, validation, and response
shapes live HERE once so the two route layers stay thin and cannot
drift. Both cores raise ``ValueError`` on a bad request — the route
layer maps that to its own 400 envelope.

Safety: the profile endpoints are reachable over HTTP (the gateway one
without auth, like ``/__drain__``), so client-supplied ``output_dir``
is REJECTED — traces always land under the process's default trace dir
— and ``key`` is restricted to a path-segment-safe charset so it cannot
traverse out of it.
"""

from __future__ import annotations

import re

from .flight import get_flight_recorder

_SAFE_KEY_RE = re.compile(r"^[A-Za-z0-9._-]{1,64}$")


def flight_snapshot(kind: str = "", limit=0) -> dict:
    """The GET /debug/flight payload: ring snapshot (oldest first,
    optional exact/``prefix.*`` kind filter, newest-N ``limit``), dump
    count, and the last post-mortem artifact path."""
    try:
        limit = int(limit or 0)
    except (TypeError, ValueError):
        raise ValueError("limit must be an int")
    recorder = get_flight_recorder()
    return {
        "events": recorder.events(kind=(kind or None), limit=limit),
        "ring": len(recorder),
        "dumps": recorder.dumps,
        "last_dump": recorder.last_dump_path,
    }


def profile_request(body: dict) -> dict:
    """The POST /debug/profile core: disarm, or arm an on-demand XLA
    capture for the next N steps/seconds (``utils/profiler``)."""
    from ..utils.profiler import arm_profile, disarm_profile

    body = body or {}
    if body.get("disarm"):
        # stop_active: the HTTP disarm is the operator remedy for a
        # capture whose claiming loop went away
        return {"disarmed": disarm_profile(stop_active=True)}
    if body.get("output_dir"):
        # the arm request crosses an HTTP boundary: a client-chosen
        # directory would be an arbitrary-path write primitive
        raise ValueError("output_dir is not accepted over HTTP — traces "
                         "land under the process's default trace dir")
    key = str(body.get("key", "") or "xla-trace")
    if not _SAFE_KEY_RE.match(key) or not key.strip("."):
        # a pure-dot key ("." / "..") would resolve OUT of the traces
        # dir despite matching the charset
        raise ValueError("key must match [A-Za-z0-9._-]{1,64} and "
                         "contain a non-dot character")
    try:
        steps = int(body.get("steps", 0) or 0)
        seconds = float(body.get("seconds", 0) or 0)
    except (TypeError, ValueError):
        raise ValueError("steps must be an int and seconds a number")
    return arm_profile(steps=steps, seconds=seconds, key=key)
