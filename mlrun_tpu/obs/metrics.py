"""Thread-safe metrics registry with Prometheus text exposition.

The serving and run-lifecycle paths accumulated rich internal state —
TTFT/ITL percentiles in the LLM engines, breaker/shed counters in
``serving/resilience.py``, retry/heartbeat state in the run monitor — but
it lived in ad-hoc dicts with no exposition format, no labels, and no
histograms. This module is the one spine: ``Counter`` / ``Gauge`` /
``Histogram`` families with label sets, bounded cardinality (a typed
:class:`CardinalityError` on overflow, or silent drop for hot paths that
must never raise), and ``render()`` producing the Prometheus text format
served at ``/metrics`` by the serving gateway and the service API.

Design constraints (mirrors ``chaos/registry.py``):

- **Bottom layer.** Stdlib only — no mlrun_tpu imports — so every layer
  (chaos included) can hook it without cycles.
- **Cheap when hot.** An ``inc``/``observe`` is one lock + dict update;
  expensive work (collector callbacks, formatting) happens only at
  scrape time.
- **Bounded.** Every metric caps its label-set count; overflow either
  raises the typed error (default — misconfigured labels fail loudly in
  tests) or drops the new series and counts the drop (``overflow="drop"``
  for production hot paths fed with runtime-derived label values).
"""

from __future__ import annotations

import math
import re
import threading
import time
from typing import Callable, Iterable, Optional

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

# default bucket bounds for latency histograms (seconds) — spans TTFT on
# a warm TPU engine (~ms) through deadline-class request times
DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                   0.5, 1.0, 2.5, 5.0, 10.0, 30.0)

DEFAULT_MAX_LABEL_SETS = 256


class MetricError(RuntimeError):
    """Base for registry misuse (name clash, bad labels)."""


class CardinalityError(MetricError):
    """A metric exceeded its label-set bound — the series was NOT
    created. Raised instead of growing unbounded (a runaway label value
    would otherwise eat the process from inside a counter)."""


def _escape_label(value: str) -> str:
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _escape_help(value: str) -> str:
    return str(value).replace("\\", "\\\\").replace("\n", "\\n")


def _fmt(value: float) -> str:
    if value != value:  # NaN
        return "NaN"
    if value in (math.inf, -math.inf):
        return "+Inf" if value > 0 else "-Inf"
    if float(value).is_integer() and abs(value) < 1e15:
        return str(int(value))
    return repr(float(value))


class _Metric:
    """Shared series bookkeeping: label validation, cardinality bound,
    per-metric lock."""

    type_name = ""

    def __init__(self, name: str, help: str = "",
                 labels: Iterable[str] = (),
                 max_label_sets: int | None = None,
                 overflow: str = "raise"):
        if not _NAME_RE.match(name):
            raise MetricError(f"invalid metric name '{name}'")
        for label in labels:
            if not _LABEL_RE.match(label):
                raise MetricError(
                    f"metric '{name}': invalid label name '{label}'")
        if overflow not in ("raise", "drop"):
            raise MetricError(
                f"metric '{name}': overflow must be 'raise' or 'drop'")
        self.name = name
        self.help = help
        self.labelnames = tuple(labels)
        self.max_label_sets = (DEFAULT_MAX_LABEL_SETS
                               if max_label_sets is None
                               else int(max_label_sets))
        self.overflow = overflow
        self.dropped = 0  # series lost to the cardinality bound (drop mode)
        self._lock = threading.Lock()
        self._series: dict[tuple, object] = {}

    def _key(self, labels: dict) -> tuple:
        if set(labels) != set(self.labelnames):
            raise MetricError(
                f"metric '{self.name}' takes labels "
                f"{sorted(self.labelnames)}, got {sorted(labels)}")
        return tuple(str(labels[name]) for name in self.labelnames)

    def _get_or_create(self, key: tuple, factory: Callable):
        """Caller holds ``self._lock``. Returns None when the series was
        dropped by the cardinality bound in drop mode."""
        series = self._series.get(key)
        if series is None:
            if len(self._series) >= self.max_label_sets:
                if self.overflow == "drop":
                    self.dropped += 1
                    return None
                raise CardinalityError(
                    f"metric '{self.name}' exceeded its label-set bound "
                    f"({self.max_label_sets}); refusing to create series "
                    f"for labels {dict(zip(self.labelnames, key))}")
            series = factory()
            self._series[key] = series
        return series

    def remove(self, **labels):
        """Drop one series (engines remove their gauges on stop so a
        process churning short-lived engines doesn't pin stale series)."""
        key = self._key(labels)
        with self._lock:
            self._series.pop(key, None)

    def clear(self):
        with self._lock:
            self._series.clear()
            self.dropped = 0

    def _labels_suffix(self, key: tuple, extra: str = "") -> str:
        parts = [f'{name}="{_escape_label(value)}"'
                 for name, value in zip(self.labelnames, key)]
        if extra:
            parts.append(extra)
        return "{" + ",".join(parts) + "}" if parts else ""

    def render(self, openmetrics: bool = False) -> list[str]:
        raise NotImplementedError


class Counter(_Metric):
    """Monotone counter. ``inc`` adds; ``set_total`` syncs to an absolute
    monotone total (for collectors mirroring an existing cumulative stat,
    e.g. an engine's ``prefix_hits``) and never moves backwards."""

    type_name = "counter"

    def inc(self, value: float = 1.0, **labels):
        if value < 0:
            raise MetricError(
                f"counter '{self.name}' cannot decrease (inc {value})")
        key = self._key(labels)
        with self._lock:
            if self._get_or_create(key, float) is not None:
                self._series[key] += value

    def set_total(self, value: float, **labels):
        key = self._key(labels)
        with self._lock:
            current = self._get_or_create(key, float)
            if current is not None and value > current:
                self._series[key] = float(value)

    def value(self, **labels) -> float:
        key = self._key(labels)
        with self._lock:
            return float(self._series.get(key, 0.0))

    def om_family(self) -> str:
        """OpenMetrics family name: the spec says a counter family
        ``foo`` exposes samples ``foo_total`` — so the TYPE/HELP lines
        must strip our ``_total`` suffix, or a strict scraper (stock
        Prometheus negotiates OpenMetrics by default) rejects the whole
        scrape expecting ``foo_total_total`` samples."""
        return self.name[:-len("_total")] \
            if self.name.endswith("_total") else self.name

    def render(self, openmetrics: bool = False) -> list[str]:
        with self._lock:
            items = sorted(self._series.items())
        # OpenMetrics: sample name = family + "_total". Families already
        # named *_total keep their sample names byte-identical (only the
        # TYPE/HELP family name changes); the rare counter without the
        # suffix gains it in the OM variant only.
        name = self.om_family() + "_total" if openmetrics else self.name
        return [f"{name}{self._labels_suffix(key)} {_fmt(value)}"
                for key, value in items]


class Gauge(_Metric):
    """Point-in-time value (queue depth, free-page fraction, breaker
    state)."""

    type_name = "gauge"

    def set(self, value: float, **labels):
        key = self._key(labels)
        with self._lock:
            if self._get_or_create(key, float) is not None:
                self._series[key] = float(value)

    def inc(self, value: float = 1.0, **labels):
        key = self._key(labels)
        with self._lock:
            if self._get_or_create(key, float) is not None:
                self._series[key] += value

    def dec(self, value: float = 1.0, **labels):
        self.inc(-value, **labels)

    def value(self, **labels) -> float:
        key = self._key(labels)
        with self._lock:
            return float(self._series.get(key, 0.0))

    def render(self, openmetrics: bool = False) -> list[str]:
        with self._lock:
            items = sorted(self._series.items())
        return [f"{self.name}{self._labels_suffix(key)} {_fmt(value)}"
                for key, value in items]


class _HistogramSeries:
    __slots__ = ("counts", "sum", "count", "exemplars")

    def __init__(self, n_buckets: int):
        self.counts = [0] * n_buckets  # per-bucket (non-cumulative)
        self.sum = 0.0
        self.count = 0
        # one exemplar slot per bucket (+Inf included), allocated on the
        # first exemplar so exemplar-free histograms pay nothing; each
        # slot is (value, labels-dict, unix-ts), last write wins
        self.exemplars: Optional[list] = None


class Histogram(_Metric):
    """Fixed-bound histogram; exposition emits cumulative ``_bucket``
    series (with the implicit ``+Inf``), ``_sum`` and ``_count``."""

    type_name = "histogram"

    def __init__(self, name: str, help: str = "",
                 labels: Iterable[str] = (),
                 buckets: Iterable[float] | None = None,
                 max_label_sets: int | None = None,
                 overflow: str = "raise"):
        super().__init__(name, help, labels, max_label_sets, overflow)
        bounds = tuple(sorted(float(b) for b in (buckets or DEFAULT_BUCKETS)))
        if not bounds:
            raise MetricError(f"histogram '{name}' needs >= 1 bucket bound")
        self.buckets = bounds

    def observe(self, value: float, exemplar=None, **labels):
        """``exemplar`` optionally attaches a trace reference to the
        observation's bucket (docs/observability.md "Request
        attribution, exemplars & trace assembly"): a trace-id string or
        a small labels dict. One slot per bucket, last write wins —
        lock-cheap (the same per-metric lock the counts take, one tuple
        assignment), rendered only on the OpenMetrics content type."""
        key = self._key(labels)
        with self._lock:
            series = self._get_or_create(
                key, lambda: _HistogramSeries(len(self.buckets)))
            if series is None:
                return
            bucket_index = len(self.buckets)  # +Inf slot
            for index, bound in enumerate(self.buckets):
                if value <= bound:
                    series.counts[index] += 1
                    bucket_index = index
                    break
            series.sum += value
            series.count += 1
            if exemplar is not None:
                if series.exemplars is None:
                    series.exemplars = [None] * (len(self.buckets) + 1)
                if not isinstance(exemplar, dict):
                    exemplar = {"trace_id": str(exemplar)}
                series.exemplars[bucket_index] = (
                    float(value), exemplar, time.time())

    def value(self, **labels) -> dict:
        key = self._key(labels)
        with self._lock:
            series = self._series.get(key)
            if series is None:
                return {"count": 0, "sum": 0.0}
            return {"count": series.count, "sum": series.sum}

    def exemplars(self, match: Optional[dict] = None) -> list[dict]:
        """Exemplars across series whose labels contain ``match`` (the
        SLO evaluator's breach-forensics read): one entry per occupied
        bucket slot — ``{series, le, value, labels, ts}`` — so "worst
        offenders" is a sort by value over this list."""
        match_items = set((k, str(v)) for k, v in (match or {}).items())
        out = []
        with self._lock:
            items = [(key, series.exemplars)
                     for key, series in self._series.items()
                     if series.exemplars is not None]
        bounds = list(self.buckets) + [math.inf]
        for key, slots in items:
            series_labels = dict(zip(self.labelnames, key))
            if not match_items <= set(
                    (k, str(v)) for k, v in series_labels.items()):
                continue
            for index, slot in enumerate(slots):
                if slot is None:
                    continue
                value, labels, ts = slot
                out.append({"series": series_labels, "le": bounds[index],
                            "value": value, "labels": dict(labels),
                            "ts": ts})
        return out

    @staticmethod
    def _exemplar_suffix(slot) -> str:
        """OpenMetrics exemplar clause for one bucket line:
        `` # {label="value",...} <value> <timestamp>``."""
        if slot is None:
            return ""
        value, labels, ts = slot
        body = ",".join(f'{k}="{_escape_label(v)}"'
                        for k, v in sorted(labels.items()))
        return f" # {{{body}}} {_fmt(value)} {ts:.3f}"

    def render(self, openmetrics: bool = False) -> list[str]:
        with self._lock:
            items = sorted(
                (key, list(series.counts), series.sum, series.count,
                 list(series.exemplars) if series.exemplars else None)
                for key, series in self._series.items())
        lines = []
        for key, counts, total, count, exemplars in items:
            cumulative = 0
            for index, (bound, bucket_count) in enumerate(
                    zip(self.buckets, counts)):
                cumulative += bucket_count
                le = 'le="' + _fmt(bound) + '"'
                extra = self._exemplar_suffix(exemplars[index]) \
                    if openmetrics and exemplars else ""
                lines.append(f"{self.name}_bucket"
                             f"{self._labels_suffix(key, le)} "
                             f"{cumulative}{extra}")
            le_inf = 'le="+Inf"'
            extra = self._exemplar_suffix(exemplars[-1]) \
                if openmetrics and exemplars else ""
            lines.append(f"{self.name}_bucket"
                         f"{self._labels_suffix(key, le_inf)} "
                         f"{count}{extra}")
            lines.append(
                f"{self.name}_sum{self._labels_suffix(key)} {_fmt(total)}")
            lines.append(
                f"{self.name}_count{self._labels_suffix(key)} {count}")
        return lines


class MetricsRegistry:
    """Process-wide metric families + scrape-time collectors.

    ``counter``/``gauge``/``histogram`` are get-or-create: re-declaring
    the same name with the same type returns the existing family (so
    module reloads and multiple importers agree); a type clash is a
    :class:`MetricError`.

    Collectors are callables invoked at scrape time, for state that is
    cheaper to read on demand than to push per-event (engine queue
    depth, breaker states). A collector returning ``False`` is removed —
    the weakref-friendly retirement contract for collectors bound to
    short-lived objects.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, _Metric] = {}
        self._collectors: list[Callable] = []

    def _declare(self, cls, name: str, help: str, **kwargs) -> _Metric:
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if not isinstance(existing, cls):
                    raise MetricError(
                        f"metric '{name}' already registered as "
                        f"{existing.type_name}, not {cls.type_name}")
                return existing
            metric = cls(name, help, **kwargs)
            self._metrics[name] = metric
            return metric

    def counter(self, name: str, help: str = "", **kwargs) -> Counter:
        return self._declare(Counter, name, help, **kwargs)

    def gauge(self, name: str, help: str = "", **kwargs) -> Gauge:
        return self._declare(Gauge, name, help, **kwargs)

    def histogram(self, name: str, help: str = "", **kwargs) -> Histogram:
        return self._declare(Histogram, name, help, **kwargs)

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def add_collector(self, collector: Callable) -> Callable:
        with self._lock:
            self._collectors.append(collector)
        return collector

    def remove_collector(self, collector: Callable):
        with self._lock:
            if collector in self._collectors:
                self._collectors.remove(collector)

    def collect(self):
        """Run scrape-time collectors; retire the ones reporting False
        (their backing object is gone)."""
        with self._lock:
            collectors = list(self._collectors)
        retired = []
        for collector in collectors:
            try:
                if collector() is False:
                    retired.append(collector)
            except Exception:  # noqa: BLE001 - one bad collector must not
                # take the whole scrape down
                retired.append(collector)
        for collector in retired:
            self.remove_collector(collector)

    def render(self, openmetrics: bool = False) -> str:
        """Text exposition: Prometheus 0.0.4 by default; with
        ``openmetrics`` the histogram bucket lines additionally carry
        their exemplars in OpenMetrics syntax and the body ends with
        ``# EOF`` (served behind content negotiation — the default
        scrape format stays byte-identical to before)."""
        self.collect()
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        lines = []
        for metric in metrics:
            family = metric.om_family() \
                if openmetrics and isinstance(metric, Counter) \
                else metric.name
            lines.append(f"# HELP {family} "
                         f"{_escape_help(metric.help or metric.name)}")
            lines.append(f"# TYPE {family} {metric.type_name}")
            lines.extend(metric.render(openmetrics=openmetrics))
        if openmetrics:
            lines.append("# EOF")
        return "\n".join(lines) + "\n"

    def reset(self):
        """Zero every series (tests); families and collectors survive."""
        with self._lock:
            metrics = list(self._metrics.values())
        for metric in metrics:
            metric.clear()


# the process-wide registry /metrics renders
REGISTRY = MetricsRegistry()

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"
# negotiated via the Accept header on the /metrics endpoints — the only
# format whose bucket lines carry exemplars
OPENMETRICS_CONTENT_TYPE = (
    "application/openmetrics-text; version=1.0.0; charset=utf-8")


def wants_openmetrics(accept: str | None) -> bool:
    """Content negotiation for the /metrics endpoints: OpenMetrics only
    when the client asks for it by name (Prometheus text 0.0.4 stays
    the default for every other Accept value)."""
    return bool(accept) and "application/openmetrics-text" in accept
