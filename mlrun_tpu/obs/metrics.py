"""Thread-safe metrics registry with Prometheus text exposition.

The serving and run-lifecycle paths accumulated rich internal state —
TTFT/ITL percentiles in the LLM engines, breaker/shed counters in
``serving/resilience.py``, retry/heartbeat state in the run monitor — but
it lived in ad-hoc dicts with no exposition format, no labels, and no
histograms. This module is the one spine: ``Counter`` / ``Gauge`` /
``Histogram`` families with label sets, bounded cardinality (a typed
:class:`CardinalityError` on overflow, or silent drop for hot paths that
must never raise), and ``render()`` producing the Prometheus text format
served at ``/metrics`` by the serving gateway and the service API.

Design constraints (mirrors ``chaos/registry.py``):

- **Bottom layer.** Stdlib only — no mlrun_tpu imports — so every layer
  (chaos included) can hook it without cycles.
- **Cheap when hot.** An ``inc``/``observe`` is one lock + dict update;
  expensive work (collector callbacks, formatting) happens only at
  scrape time.
- **Bounded.** Every metric caps its label-set count; overflow either
  raises the typed error (default — misconfigured labels fail loudly in
  tests) or drops the new series and counts the drop (``overflow="drop"``
  for production hot paths fed with runtime-derived label values).
"""

from __future__ import annotations

import math
import re
import threading
from typing import Callable, Iterable, Optional

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

# default bucket bounds for latency histograms (seconds) — spans TTFT on
# a warm TPU engine (~ms) through deadline-class request times
DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                   0.5, 1.0, 2.5, 5.0, 10.0, 30.0)

DEFAULT_MAX_LABEL_SETS = 256


class MetricError(RuntimeError):
    """Base for registry misuse (name clash, bad labels)."""


class CardinalityError(MetricError):
    """A metric exceeded its label-set bound — the series was NOT
    created. Raised instead of growing unbounded (a runaway label value
    would otherwise eat the process from inside a counter)."""


def _escape_label(value: str) -> str:
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _escape_help(value: str) -> str:
    return str(value).replace("\\", "\\\\").replace("\n", "\\n")


def _fmt(value: float) -> str:
    if value != value:  # NaN
        return "NaN"
    if value in (math.inf, -math.inf):
        return "+Inf" if value > 0 else "-Inf"
    if float(value).is_integer() and abs(value) < 1e15:
        return str(int(value))
    return repr(float(value))


class _Metric:
    """Shared series bookkeeping: label validation, cardinality bound,
    per-metric lock."""

    type_name = ""

    def __init__(self, name: str, help: str = "",
                 labels: Iterable[str] = (),
                 max_label_sets: int | None = None,
                 overflow: str = "raise"):
        if not _NAME_RE.match(name):
            raise MetricError(f"invalid metric name '{name}'")
        for label in labels:
            if not _LABEL_RE.match(label):
                raise MetricError(
                    f"metric '{name}': invalid label name '{label}'")
        if overflow not in ("raise", "drop"):
            raise MetricError(
                f"metric '{name}': overflow must be 'raise' or 'drop'")
        self.name = name
        self.help = help
        self.labelnames = tuple(labels)
        self.max_label_sets = (DEFAULT_MAX_LABEL_SETS
                               if max_label_sets is None
                               else int(max_label_sets))
        self.overflow = overflow
        self.dropped = 0  # series lost to the cardinality bound (drop mode)
        self._lock = threading.Lock()
        self._series: dict[tuple, object] = {}

    def _key(self, labels: dict) -> tuple:
        if set(labels) != set(self.labelnames):
            raise MetricError(
                f"metric '{self.name}' takes labels "
                f"{sorted(self.labelnames)}, got {sorted(labels)}")
        return tuple(str(labels[name]) for name in self.labelnames)

    def _get_or_create(self, key: tuple, factory: Callable):
        """Caller holds ``self._lock``. Returns None when the series was
        dropped by the cardinality bound in drop mode."""
        series = self._series.get(key)
        if series is None:
            if len(self._series) >= self.max_label_sets:
                if self.overflow == "drop":
                    self.dropped += 1
                    return None
                raise CardinalityError(
                    f"metric '{self.name}' exceeded its label-set bound "
                    f"({self.max_label_sets}); refusing to create series "
                    f"for labels {dict(zip(self.labelnames, key))}")
            series = factory()
            self._series[key] = series
        return series

    def remove(self, **labels):
        """Drop one series (engines remove their gauges on stop so a
        process churning short-lived engines doesn't pin stale series)."""
        key = self._key(labels)
        with self._lock:
            self._series.pop(key, None)

    def clear(self):
        with self._lock:
            self._series.clear()
            self.dropped = 0

    def _labels_suffix(self, key: tuple, extra: str = "") -> str:
        parts = [f'{name}="{_escape_label(value)}"'
                 for name, value in zip(self.labelnames, key)]
        if extra:
            parts.append(extra)
        return "{" + ",".join(parts) + "}" if parts else ""

    def render(self) -> list[str]:
        raise NotImplementedError


class Counter(_Metric):
    """Monotone counter. ``inc`` adds; ``set_total`` syncs to an absolute
    monotone total (for collectors mirroring an existing cumulative stat,
    e.g. an engine's ``prefix_hits``) and never moves backwards."""

    type_name = "counter"

    def inc(self, value: float = 1.0, **labels):
        if value < 0:
            raise MetricError(
                f"counter '{self.name}' cannot decrease (inc {value})")
        key = self._key(labels)
        with self._lock:
            if self._get_or_create(key, float) is not None:
                self._series[key] += value

    def set_total(self, value: float, **labels):
        key = self._key(labels)
        with self._lock:
            current = self._get_or_create(key, float)
            if current is not None and value > current:
                self._series[key] = float(value)

    def value(self, **labels) -> float:
        key = self._key(labels)
        with self._lock:
            return float(self._series.get(key, 0.0))

    def render(self) -> list[str]:
        with self._lock:
            items = sorted(self._series.items())
        return [f"{self.name}{self._labels_suffix(key)} {_fmt(value)}"
                for key, value in items]


class Gauge(_Metric):
    """Point-in-time value (queue depth, free-page fraction, breaker
    state)."""

    type_name = "gauge"

    def set(self, value: float, **labels):
        key = self._key(labels)
        with self._lock:
            if self._get_or_create(key, float) is not None:
                self._series[key] = float(value)

    def inc(self, value: float = 1.0, **labels):
        key = self._key(labels)
        with self._lock:
            if self._get_or_create(key, float) is not None:
                self._series[key] += value

    def dec(self, value: float = 1.0, **labels):
        self.inc(-value, **labels)

    def value(self, **labels) -> float:
        key = self._key(labels)
        with self._lock:
            return float(self._series.get(key, 0.0))

    def render(self) -> list[str]:
        with self._lock:
            items = sorted(self._series.items())
        return [f"{self.name}{self._labels_suffix(key)} {_fmt(value)}"
                for key, value in items]


class _HistogramSeries:
    __slots__ = ("counts", "sum", "count")

    def __init__(self, n_buckets: int):
        self.counts = [0] * n_buckets  # per-bucket (non-cumulative)
        self.sum = 0.0
        self.count = 0


class Histogram(_Metric):
    """Fixed-bound histogram; exposition emits cumulative ``_bucket``
    series (with the implicit ``+Inf``), ``_sum`` and ``_count``."""

    type_name = "histogram"

    def __init__(self, name: str, help: str = "",
                 labels: Iterable[str] = (),
                 buckets: Iterable[float] | None = None,
                 max_label_sets: int | None = None,
                 overflow: str = "raise"):
        super().__init__(name, help, labels, max_label_sets, overflow)
        bounds = tuple(sorted(float(b) for b in (buckets or DEFAULT_BUCKETS)))
        if not bounds:
            raise MetricError(f"histogram '{name}' needs >= 1 bucket bound")
        self.buckets = bounds

    def observe(self, value: float, **labels):
        key = self._key(labels)
        with self._lock:
            series = self._get_or_create(
                key, lambda: _HistogramSeries(len(self.buckets)))
            if series is None:
                return
            for index, bound in enumerate(self.buckets):
                if value <= bound:
                    series.counts[index] += 1
                    break
            series.sum += value
            series.count += 1

    def value(self, **labels) -> dict:
        key = self._key(labels)
        with self._lock:
            series = self._series.get(key)
            if series is None:
                return {"count": 0, "sum": 0.0}
            return {"count": series.count, "sum": series.sum}

    def render(self) -> list[str]:
        with self._lock:
            items = sorted(
                (key, list(series.counts), series.sum, series.count)
                for key, series in self._series.items())
        lines = []
        for key, counts, total, count in items:
            cumulative = 0
            for bound, bucket_count in zip(self.buckets, counts):
                cumulative += bucket_count
                le = 'le="' + _fmt(bound) + '"'
                lines.append(f"{self.name}_bucket"
                             f"{self._labels_suffix(key, le)} {cumulative}")
            le_inf = 'le="+Inf"'
            lines.append(f"{self.name}_bucket"
                         f"{self._labels_suffix(key, le_inf)} {count}")
            lines.append(
                f"{self.name}_sum{self._labels_suffix(key)} {_fmt(total)}")
            lines.append(
                f"{self.name}_count{self._labels_suffix(key)} {count}")
        return lines


class MetricsRegistry:
    """Process-wide metric families + scrape-time collectors.

    ``counter``/``gauge``/``histogram`` are get-or-create: re-declaring
    the same name with the same type returns the existing family (so
    module reloads and multiple importers agree); a type clash is a
    :class:`MetricError`.

    Collectors are callables invoked at scrape time, for state that is
    cheaper to read on demand than to push per-event (engine queue
    depth, breaker states). A collector returning ``False`` is removed —
    the weakref-friendly retirement contract for collectors bound to
    short-lived objects.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, _Metric] = {}
        self._collectors: list[Callable] = []

    def _declare(self, cls, name: str, help: str, **kwargs) -> _Metric:
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if not isinstance(existing, cls):
                    raise MetricError(
                        f"metric '{name}' already registered as "
                        f"{existing.type_name}, not {cls.type_name}")
                return existing
            metric = cls(name, help, **kwargs)
            self._metrics[name] = metric
            return metric

    def counter(self, name: str, help: str = "", **kwargs) -> Counter:
        return self._declare(Counter, name, help, **kwargs)

    def gauge(self, name: str, help: str = "", **kwargs) -> Gauge:
        return self._declare(Gauge, name, help, **kwargs)

    def histogram(self, name: str, help: str = "", **kwargs) -> Histogram:
        return self._declare(Histogram, name, help, **kwargs)

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def add_collector(self, collector: Callable) -> Callable:
        with self._lock:
            self._collectors.append(collector)
        return collector

    def remove_collector(self, collector: Callable):
        with self._lock:
            if collector in self._collectors:
                self._collectors.remove(collector)

    def collect(self):
        """Run scrape-time collectors; retire the ones reporting False
        (their backing object is gone)."""
        with self._lock:
            collectors = list(self._collectors)
        retired = []
        for collector in collectors:
            try:
                if collector() is False:
                    retired.append(collector)
            except Exception:  # noqa: BLE001 - one bad collector must not
                # take the whole scrape down
                retired.append(collector)
        for collector in retired:
            self.remove_collector(collector)

    def render(self) -> str:
        """Prometheus text exposition (format version 0.0.4)."""
        self.collect()
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        lines = []
        for metric in metrics:
            lines.append(f"# HELP {metric.name} "
                         f"{_escape_help(metric.help or metric.name)}")
            lines.append(f"# TYPE {metric.name} {metric.type_name}")
            lines.extend(metric.render())
        return "\n".join(lines) + "\n"

    def reset(self):
        """Zero every series (tests); families and collectors survive."""
        with self._lock:
            metrics = list(self._metrics.values())
        for metric in metrics:
            metric.clear()


# the process-wide registry /metrics renders
REGISTRY = MetricsRegistry()

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"
