"""Declarative SLOs with fast+slow multi-window burn-rate evaluation
(docs/observability.md "SLOs & burn rates").

An objective declares what "good" means (p95 TTFT under a target,
dispatch error rate under a budget, availability over a floor); the
evaluator turns the federated time-series store into burn rates — how
fast the error budget is being consumed relative to steady-state — over
a FAST window (catches a sharp regression in seconds) and a SLOW window
(confirms it isn't a blip). An alert fires only when BOTH windows burn
over their thresholds (the SRE-workbook multi-window pattern: fast-only
is noise, slow-only is a stale incident), and it fires through the
existing ``service/alerts.process_event`` machinery — alert configs,
silencing windows, and notification fan-out work unchanged.

Burn-rate definitions (budget = allowed bad fraction):

- ``latency``: objective "q-quantile of ``family`` ≤ ``target``
  seconds". Budget is ``1 - q`` (a p95 objective tolerates 5% of
  requests over target); the observed bad fraction is the windowed
  fraction of histogram observations above ``target``.
- ``error_rate``: objective "``bad`` events / ``total`` events ≤
  ``target``". Budget is ``target`` itself.
- ``availability``: objective "good / total ≥ ``target``" — an
  error-rate objective with budget ``1 - target``.
- ``goodput``: objective "goodput fraction ≥ ``target``" over the
  run-lifecycle accounting families (``obs/goodput.py``): bad defaults
  to ``mlt_badput_seconds_total`` and total to
  ``mlt_goodput_wall_seconds_total``, budget is ``1 - target`` (a 0.9
  goodput floor tolerates 10% badput seconds). ``run=`` narrows the
  objective to one run's series; ``bad_labels={"bucket": ...}``
  narrows to one badput class (e.g. alert on preemption downtime
  alone). Evaluation rides the same windowed-increase path as
  ``error_rate`` — nothing below this constructor changes.
- ``quality_delta``: objective "a gauge statistic under
  ``canary_labels`` must not degrade more than ``target`` against the
  same statistic under ``labels``" — the canary-vs-stable comparison
  behind the continuous-tuning loop (docs/continuous_tuning.md), over
  the per-adapter ``mlt_drift_stat`` series by default. Budget is 1.0
  so ``burn == windowed degradation / target``: burn 1.0 means the
  canary is worse by exactly the allowed delta; burn 0 means at least
  as good. ``direction`` says which way is worse for the statistic
  (``"higher_worse"`` — e.g. a drift score — or ``"lower_worse"`` —
  e.g. a confidence/quality mean). Either side's window being empty is
  "no signal", never a verdict.

``burn = bad_fraction / budget``; burn 1.0 = exactly on budget.

Stdlib-only at module level (``from_mlconf`` / ``process`` lazy-import
config and the service alert machinery).
"""

from __future__ import annotations

import threading
from typing import Callable, Iterable, Optional

from .flight import record as flight_record
from .metrics import REGISTRY


def registry_exemplars(family: str, labels: Optional[dict],
                       threshold: float, k: int) -> list[dict]:
    """Default breach-forensics lookup: the top-``k`` exemplars by value
    from the in-process registry histogram's offending buckets (value
    over ``threshold``, label-subset filtered) — the trace ids an SLO
    breach names (docs/observability.md "Request attribution, exemplars
    & trace assembly"). A federated evaluator passes
    ``MetricsAggregator.exemplars``-backed lookup instead."""
    metric = REGISTRY.get(family)
    exemplar_read = getattr(metric, "exemplars", None)
    if exemplar_read is None:
        return []
    found = exemplar_read(match=labels)
    over = [e for e in found if e["value"] > threshold]
    return sorted(over, key=lambda e: -e["value"])[:max(0, int(k))]

SLO_BURN_RATE = REGISTRY.gauge(
    "mlt_slo_burn_rate",
    "Error-budget burn rate per objective and window (1.0 = consuming "
    "budget exactly at the allowed steady-state rate)",
    labels=("slo", "window"), overflow="drop")
SLO_STATUS = REGISTRY.gauge(
    "mlt_slo_status",
    "Objective state: 0 ok, 1 fast-window burning (unconfirmed), "
    "2 breach (fast AND slow windows over threshold)",
    labels=("slo",), overflow="drop")
SLO_BREACHES = REGISTRY.counter(
    "mlt_slo_breaches_total",
    "Multi-window burn-rate breaches emitted to the alert machinery",
    labels=("slo",), overflow="drop")

_KINDS = ("latency", "error_rate", "availability", "goodput",
          "quality_delta")

# default event kind SLO breaches are emitted under — alert configs list
# it in trigger_events (see service/alerts.ALERT_TEMPLATES["SLOBurnRate"])
SLO_EVENT_KIND = "slo_burn_rate"


class SLO:
    """One declarative objective. ``family``/``bad``/``total`` name
    metric families in the time-series store; ``labels`` narrows the
    series the objective evaluates over (e.g. one engine)."""

    def __init__(self, name: str, kind: str, target: float,
                 family: str = "mlt_llm_ttft_seconds", q: float = 0.95,
                 bad: str = "mlt_fleet_dispatches_total",
                 bad_labels: Optional[dict] = None,
                 total: str = "mlt_fleet_dispatches_total",
                 total_labels: Optional[dict] = None,
                 labels: Optional[dict] = None,
                 severity: str = "high",
                 adapter: Optional[str] = None,
                 run: Optional[str] = None,
                 canary_labels: Optional[dict] = None,
                 direction: str = "higher_worse"):
        if kind not in _KINDS:
            raise ValueError(f"unknown SLO kind '{kind}' (one of {_KINDS})")
        if kind == "quality_delta":
            # like the goodput sugar: swap the latency-family default
            # for the drift-stat gauges the comparison is documented
            # over — an explicit family= still wins
            if family == "mlt_llm_ttft_seconds":
                family = "mlt_drift_stat"
            if not canary_labels:
                raise ValueError(
                    "quality_delta SLO needs canary_labels (the series "
                    "compared against the stable-side labels)")
            if dict(canary_labels) == dict(labels or {}):
                raise ValueError(
                    "quality_delta SLO canary_labels must differ from "
                    "labels — identical sides always read delta 0")
            if direction not in ("higher_worse", "lower_worse"):
                raise ValueError(
                    f"quality_delta direction must be 'higher_worse' or "
                    f"'lower_worse', got '{direction}'")
            if target <= 0:
                raise ValueError(
                    "quality_delta SLO target (allowed degradation) "
                    "must be > 0")
        elif canary_labels is not None:
            raise ValueError(
                "canary_labels is quality_delta-only sugar")
        if kind == "goodput":
            # goodput sugar: swap the serving-path default counters for
            # the run-lifecycle accounting families and fold a run=
            # filter into both sides; from here down the objective is an
            # ordinary windowed-increase ratio (the error_rate path)
            if bad == "mlt_fleet_dispatches_total":
                bad = "mlt_badput_seconds_total"
            if total == "mlt_fleet_dispatches_total":
                total = "mlt_goodput_wall_seconds_total"
            if run is not None:
                bad_labels = {**(bad_labels or {}), "run": run}
                total_labels = {**(total_labels or {}), "run": run}
        elif run is not None:
            raise ValueError(
                "run= is goodput-only sugar; other kinds take explicit "
                "bad_labels/total_labels")
        if adapter is not None:
            # per-tenant objective sugar (docs/observability.md "SLOs &
            # burn rates"): fold the adapter id into the latency-family
            # label filter so the windows evaluate ONE tenant's series —
            # a breaching tenant pages without painting its neighbors
            # red. Latency-only: the TTFT/ITL families carry the
            # adapter label; the default error-rate/availability
            # counters (fleet dispatches) do NOT, and silently matching
            # zero series would disable the objective — counter kinds
            # must put the adapter into bad_labels/total_labels against
            # a family that actually carries it.
            if kind != "latency":
                raise ValueError(
                    f"adapter= is latency-only sugar; a per-tenant "
                    f"{kind} SLO needs explicit bad_labels/total_labels "
                    f"over adapter-labeled families")
            labels = {**(labels or {}), "adapter": adapter}
        if kind == "latency":
            if not 0 < q < 1:
                raise ValueError(f"latency SLO needs 0 < q < 1, got {q}")
            if target <= 0:
                raise ValueError("latency SLO target must be > 0 seconds")
        elif kind != "quality_delta" and not 0 < target < 1:
            raise ValueError(
                f"{kind} SLO target must be a fraction in (0, 1)")
        if kind not in ("latency", "quality_delta") and bad == total \
                and dict(bad_labels or {}) == dict(total_labels or {}):
            # bad/total over the identical series is always 1.0 — a
            # constant max-burn false breach, never a real objective
            raise ValueError(
                f"{kind} SLO needs bad_labels (or a distinct bad "
                f"family) to tell bad events apart from the total")
        self.name = name
        self.kind = kind
        self.target = float(target)
        self.family = family
        self.q = float(q)
        self.bad = bad
        self.bad_labels = dict(bad_labels or {})
        self.total = total
        self.total_labels = dict(total_labels or {})
        self.labels = dict(labels or {})
        self.severity = severity
        self.adapter = adapter
        self.run = run
        self.canary_labels = dict(canary_labels or {})
        self.direction = direction

    @classmethod
    def from_config(cls, config: dict) -> "SLO":
        known = ("name", "kind", "target", "family", "q", "bad",
                 "bad_labels", "total", "total_labels", "labels",
                 "severity", "adapter", "run", "canary_labels",
                 "direction")
        unknown = set(config) - set(known)
        if unknown:
            raise ValueError(
                f"unknown SLO objective keys: {sorted(unknown)}")
        return cls(**config)

    @property
    def budget(self) -> float:
        """Allowed bad fraction."""
        if self.kind == "latency":
            return 1.0 - self.q
        if self.kind in ("availability", "goodput"):
            return 1.0 - self.target
        if self.kind == "quality_delta":
            # burn == bad_fraction == degradation / target directly:
            # burn 1.0 = the canary is worse by exactly the allowed delta
            return 1.0
        return self.target

    def _window_mean(self, store, window: float, at: float,
                     labels: dict) -> Optional[float]:
        """Mean of one side's windowed gauge points (bucket-avg, then
        time-avg) — None when the window carries no points."""
        pts = store.points(self.family, at - window, at,
                           labels=labels or None, agg="avg")
        if not pts:
            return None
        return sum(v for _, v in pts) / len(pts)

    def bad_fraction(self, store, window: float,
                     at: float) -> Optional[float]:
        """Observed bad fraction over ``window`` — None when the window
        carries no signal (an empty window neither burns nor clears)."""
        if self.kind == "latency":
            return store.fraction_over(self.family, self.target, window,
                                       at, labels=self.labels or None)
        if self.kind == "quality_delta":
            stable = self._window_mean(store, window, at, self.labels)
            canary = self._window_mean(store, window, at,
                                       self.canary_labels)
            if stable is None or canary is None:
                return None
            delta = canary - stable
            if self.direction == "lower_worse":
                delta = -delta
            # deliberately NOT clamped to 1.0: burn must be able to
            # exceed the evaluator's thresholds (the global evaluator
            # runs fast_burn 14.4 / slow_burn 6.0 — a capped burn could
            # never breach there no matter how bad the canary got)
            return max(0.0, delta / self.target)
        total = store.increase(self.total, window, at,
                               labels=self.total_labels or None)
        if total <= 0:
            return None
        bad = store.increase(self.bad, window, at,
                             labels=self.bad_labels or None)
        return max(0.0, min(1.0, bad / total))

    def describe(self) -> dict:
        out = {"name": self.name, "kind": self.kind, "target": self.target,
               "budget": self.budget, "severity": self.severity}
        if self.adapter is not None:
            out["adapter"] = self.adapter
        if self.run is not None:
            out["run"] = self.run
        if self.kind == "latency":
            out.update(family=self.family, q=self.q)
        elif self.kind == "quality_delta":
            out.update(family=self.family, direction=self.direction,
                       canary_labels=self.canary_labels)
        else:
            out.update(bad=self.bad, total=self.total)
        return out


class SLOStatus(dict):
    """Evaluation result — a plain dict (JSON-friendly for the status
    endpoints) with attribute sugar for the hot keys."""

    @property
    def breaching(self) -> bool:
        return bool(self["breaching"])

    @property
    def burn_fast(self) -> Optional[float]:
        return self["burn"]["fast"]

    @property
    def burn_slow(self) -> Optional[float]:
        return self["burn"]["slow"]


class SLOEvaluator:
    """Evaluates objectives against a :class:`TimeSeriesStore` and
    pushes confirmed breaches through the alert machinery."""

    def __init__(self, store, slos: Iterable[SLO] = (),
                 fast_window: float = 60.0, slow_window: float = 300.0,
                 fast_burn: float = 14.4, slow_burn: float = 6.0,
                 refire_after: float = 0.0, project: str = "",
                 exemplar_lookup: Optional[Callable] = None,
                 exemplar_k: int = 3):
        if fast_window <= 0 or slow_window <= fast_window:
            raise ValueError("need 0 < fast_window < slow_window")
        self.store = store
        self.slos = list(slos)
        self.fast_window = float(fast_window)
        self.slow_window = float(slow_window)
        self.fast_burn = float(fast_burn)
        self.slow_burn = float(slow_burn)
        self.refire_after = float(refire_after)
        self.project = project
        # (family, labels, threshold, k) -> worst-offender exemplars; a
        # confirmed breach attaches these so the alert names trace ids
        self.exemplar_lookup = exemplar_lookup or registry_exemplars
        self.exemplar_k = int(exemplar_k)
        self._lock = threading.Lock()
        self._last: list[SLOStatus] = []
        self._fired_at: dict[str, float] = {}  # slo name -> last fire t

    @classmethod
    def from_mlconf(cls, store, slos: Iterable[SLO] = None,
                    project: str = "",
                    exemplar_lookup: Optional[Callable] = None
                    ) -> "SLOEvaluator":
        from ..config import mlconf

        conf = mlconf.observability.slo
        if slos is None:
            slos = [SLO.from_config(dict(obj))
                    for obj in (conf.objectives or [])]
        return cls(store, slos,
                   fast_window=float(conf.fast_window_s),
                   slow_window=float(conf.slow_window_s),
                   fast_burn=float(conf.fast_burn),
                   slow_burn=float(conf.slow_burn),
                   refire_after=float(conf.refire_after_s),
                   project=project,
                   exemplar_lookup=exemplar_lookup)

    def evaluate(self, at: float) -> list[SLOStatus]:
        """Burn rates for every objective at ``at``. Breach = fast AND
        slow windows over their thresholds; fast-only = "burning"
        (unconfirmed, surfaced but not alerted)."""
        out = []
        for slo in self.slos:
            burns = {}
            for window_name, window, threshold in (
                    ("fast", self.fast_window, self.fast_burn),
                    ("slow", self.slow_window, self.slow_burn)):
                frac = slo.bad_fraction(self.store, window, at)
                burn = (frac / slo.budget) if frac is not None else None
                burns[window_name] = burn
                # an empty window exports 0, not the last value — a
                # stale breach-level gauge after traffic stops would
                # contradict mlt_slo_status forever
                SLO_BURN_RATE.set(burn if burn is not None else 0.0,
                                  slo=slo.name, window=window_name)
            fast_over = (burns["fast"] is not None
                         and burns["fast"] >= self.fast_burn)
            slow_over = (burns["slow"] is not None
                         and burns["slow"] >= self.slow_burn)
            breaching = fast_over and slow_over
            status = SLOStatus(slo.describe())
            status.update(
                burn=burns, burning=fast_over, breaching=breaching,
                thresholds={"fast": self.fast_burn,
                            "slow": self.slow_burn},
                windows={"fast": self.fast_window,
                         "slow": self.slow_window},
                at=at)
            SLO_STATUS.set(2 if breaching else 1 if fast_over else 0,
                           slo=slo.name)
            out.append(status)
        with self._lock:
            self._last = out
        return out

    def status(self) -> list[SLOStatus]:
        """Last evaluation (the cheap read the smoke/status endpoints
        use; empty before the first evaluate())."""
        with self._lock:
            return list(self._last)

    def process(self, db, at: float, project: str = None) -> list:
        """Evaluate and push each confirmed breach through
        ``service/alerts.process_event`` — the event is also persisted
        via ``db.emit_event`` first so count-over-period criteria see
        it. Returns the names of alert configs that fired (an active
        silence window keeps a breach out of this list — silencing is
        ``process_event``'s job, not re-implemented here). A SUSTAINED
        breach re-fires only every ``refire_after`` seconds (0 = every
        call): the service loop evaluates every few seconds, and one
        long incident must not page once per tick. Recovery resets the
        damper, so a fresh incident fires immediately."""
        from ..service.alerts import process_event

        project = self.project if project is None else project
        slos_by_name = {slo.name: slo for slo in self.slos}
        fired = []
        for status in self.evaluate(at):
            if not status.breaching:
                self._fired_at.pop(status["name"], None)
                continue
            last = self._fired_at.get(status["name"])
            if last is not None and self.refire_after > 0 \
                    and at - last < self.refire_after:
                continue
            self._fired_at[status["name"]] = at
            SLO_BREACHES.inc(slo=status["name"])
            event = {"entity_id": status["name"],
                     "slo": status["name"], "kind": status["kind"],
                     "severity": status["severity"],
                     "burn_fast": status.burn_fast,
                     "burn_slow": status.burn_slow,
                     "target": status["target"]}
            exemplar_ids: list[str] = []
            slo = slos_by_name.get(status["name"])
            if slo is not None and slo.kind == "latency":
                # the breach window's worst offenders, lifted off the
                # offending histogram buckets: the alert payload and the
                # flight-recorder entry now NAME trace ids a
                # `/debug/trace/<id>` fetch turns into a waterfall
                try:
                    worst = self.exemplar_lookup(
                        slo.family, slo.labels or None, slo.target,
                        self.exemplar_k)
                except Exception:  # noqa: BLE001 - forensics must not
                    worst = []     # block the alert itself
                if worst:
                    event["exemplars"] = [
                        {"value": e["value"], **e["labels"]}
                        for e in worst]
                    exemplar_ids = [e["labels"].get("trace_id")
                                    for e in worst
                                    if e["labels"].get("trace_id")]
            flight_record("slo.breach", slo=status["name"],
                          slo_kind=status["kind"],
                          burn_fast=status.burn_fast,
                          burn_slow=status.burn_slow,
                          exemplar_trace_ids=exemplar_ids)
            db.emit_event(SLO_EVENT_KIND, event, project)
            fired.extend(process_event(db, project, SLO_EVENT_KIND, event))
        return fired
