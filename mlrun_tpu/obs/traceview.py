"""Cross-replica trace assembly + blocking critical path
(docs/observability.md "Request attribution, exemplars & trace assembly").

``GET /debug/trace/<trace_id>`` turns one trace id — typically lifted
off a histogram exemplar attached to an SLO breach — into a single
waterfall: the local span ring's spans for that trace, merged with spans
fanned in from fleet replicas' rings (in-process replicas share the
process tracer; process replicas answer the same endpoint over HTTP with
a per-replica timeout, and a dead replica degrades the waterfall with a
partial-result marker instead of 504ing it). On the assembled tree this
module computes the **blocking critical path** — the longest chain of
non-overlapping child spans under the root, with gap time attributed to
the parent span's phase — and per-phase totals that reconcile against
the request's phase ledger (``obs/reqledger.py``; asserted in tests).

Stdlib only (the ``obs/`` bottom-layer rule); spans are the plain dicts
``Span.to_dict`` produces, so HTTP-fetched and local spans merge
uniformly.
"""

from __future__ import annotations

# span-name → ledger phase for critical-path segments; a parent's gap
# time lands on the PARENT's phase (a gap under server.run is time the
# request was in the server but in no child span — queue/dispatch wait)
_SPAN_PHASE = {
    "llm.prefill": "prefill",
    "llm.decode": "decode_active",
    "server.run": "queue_wait",
}


def span_phase(name: str) -> str:
    if name in _SPAN_PHASE:
        return _SPAN_PHASE[name]
    if name.startswith("remote."):
        return "network"
    if name.startswith(("step.", "server.")):
        return "queue_wait"
    return "other"


def merge_spans(*span_lists) -> list[dict]:
    """Merge span dicts from several rings, deduplicating by span_id
    (the local ring and an in-process replica's ring are the same ring;
    a re-fetched remote span must not double its duration)."""
    seen: set = set()
    merged: list[dict] = []
    for spans in span_lists:
        for span in spans or ():
            span_id = span.get("span_id")
            if span_id in seen:
                continue
            seen.add(span_id)
            merged.append(span)
    merged.sort(key=lambda s: (s.get("start") or 0.0, s.get("span_id")))
    return merged


def _finished(spans: list[dict]) -> list[dict]:
    return [s for s in spans if s.get("end") is not None]


def find_root(spans: list[dict]):
    """The waterfall root: the longest finished span whose parent is not
    in the assembled set (a header-joined trace may reference a parent
    span id that lives in an unreachable caller's ring)."""
    finished = _finished(spans)
    if not finished:
        return None
    ids = {s["span_id"] for s in finished}
    orphans = [s for s in finished
               if not s.get("parent_id") or s["parent_id"] not in ids]
    pool = orphans or finished
    return max(pool, key=lambda s: s["end"] - s["start"])


def critical_path(spans: list[dict]) -> list[dict]:
    """Blocking critical path through the span tree, as a flat list of
    segments ordered by start time.

    For each span on the path, the chain of its non-overlapping children
    that reaches furthest back from the span's end is followed
    recursively; the intervals no chosen child covers are the span's own
    blocking time (``kind="self"`` segments — for a parent that is a
    scheduler/server span this is the queue/dispatch gap the ledger
    calls ``queue_wait``). Segment durations partition the root span's
    duration exactly, so ``sum(self_s) == root wall`` by construction.
    """
    finished = _finished(spans)
    root = find_root(finished)
    if root is None:
        return []
    children: dict[str, list[dict]] = {}
    for span in finished:
        parent = span.get("parent_id")
        if parent:
            children.setdefault(parent, []).append(span)

    segments: list[dict] = []

    def seg(span: dict, start: float, end: float, kind: str):
        if end - start <= 0:
            return
        segments.append({
            "name": span["name"], "span_id": span["span_id"],
            "start": start, "end": end,
            "self_s": end - start, "kind": kind,
            "phase": span_phase(span["name"]),
            "replica": (span.get("attrs") or {}).get("replica", ""),
        })

    def walk(span: dict):
        start = max(span["start"], root["start"])
        end = min(span["end"], root["end"])
        # pick the blocking chain: from the span's end walk backwards,
        # each step taking the child with the latest end that finishes
        # before the current cursor (ties/overlaps skipped — they are
        # concurrent, not blocking)
        kids = sorted(
            (c for c in children.get(span["span_id"], ())
             if c["end"] > start and c["start"] < end),
            key=lambda c: c["end"], reverse=True)
        chain: list[dict] = []
        cursor = end
        for child in kids:
            if child["end"] <= cursor:
                chain.append(child)
                cursor = max(child["start"], start)
        chain.reverse()
        # emit: alternating parent-gap and child segments, left to right
        pos = start
        for child in chain:
            child_start = max(child["start"], start)
            child_end = min(child["end"], end)
            seg(span, pos, child_start, "self")
            walk(child)
            pos = child_end
        seg(span, pos, end, "self")

    walk(root)
    segments.sort(key=lambda s: s["start"])
    return segments


def phase_totals(segments: list[dict]) -> dict[str, float]:
    """Per-phase wall totals over the critical path. For an
    ``llm.decode`` segment whose span carried the request's ledger
    breakdown this is refined by the ledger's decode split in
    :func:`assemble`; here it is the raw segment mapping."""
    totals: dict[str, float] = {}
    for segment in segments:
        phase = segment["phase"]
        totals[phase] = totals.get(phase, 0.0) + segment["self_s"]
    return {k: v for k, v in sorted(totals.items()) if v > 0}


def assemble(trace_id: str, spans: list[dict]) -> dict:
    """One waterfall payload for ``trace_id``: the merged spans (start
    order), the blocking critical path, per-phase totals, and — when an
    engine span carried the request's phase ledger (``attrs.timing``) —
    the ledger view plus a reconciliation block comparing the two
    attributions (they must agree on the wall; tests assert it)."""
    spans = [s for s in spans if s.get("trace_id") == trace_id]
    segments = critical_path(spans)
    totals = phase_totals(segments)
    root = find_root(spans)
    out = {
        "trace_id": trace_id,
        "spans": spans,
        "span_count": len(spans),
        "replicas": sorted({(s.get("attrs") or {}).get("replica")
                            for s in spans
                            if (s.get("attrs") or {}).get("replica")}),
        "root": root["name"] if root else None,
        "critical_path": segments,
        "phase_totals": totals,
        "critical_path_s": sum(s["self_s"] for s in segments),
    }
    # the request ledger rides the llm.decode span (engine _finish); a
    # disaggregated request has one per hop — merge them
    ledgers = [s["attrs"]["timing"] for s in spans
               if isinstance((s.get("attrs") or {}).get("timing"), dict)]
    if ledgers:
        phases: dict[str, float] = {}
        wall = 0.0
        for timing in ledgers:
            for phase, seconds in (timing.get("phases") or {}).items():
                phases[phase] = phases.get(phase, 0.0) + seconds
            wall += timing.get("wall_s", 0.0)
        out["ledger"] = {"phases": phases, "wall_s": wall}
        out["reconciliation"] = {
            "critical_path_s": out["critical_path_s"],
            "ledger_wall_s": wall,
            "delta_s": out["critical_path_s"] - wall,
        }
    return out
