"""Unified telemetry: the metrics registry + tracer behind ``/metrics``
and ``X-MLT-Trace`` (docs/observability.md).

This package owns the canonical metric families so every ``/metrics``
render — serving gateway or service API — exposes the same schema even
before a sample lands. Producers import the family objects from here;
consumers render ``REGISTRY``.

Naming: ``mlt_<area>_<what>[_total|_seconds]``, labels snake_case.
"""

import threading as _threading

from .metrics import (  # noqa: F401
    CONTENT_TYPE,
    DEFAULT_BUCKETS,
    OPENMETRICS_CONTENT_TYPE,
    CardinalityError,
    Counter,
    Gauge,
    Histogram,
    MetricError,
    MetricsRegistry,
    REGISTRY,
    wants_openmetrics,
)
from .federation import (  # noqa: F401
    MetricsAggregator,
    PromParseError,
    check_histogram_consistency,
    parse_exposition,
    parse_prometheus,
)
from .reqledger import (  # noqa: F401
    REQUEST_PHASE_SECONDS,
    RequestLedger,
    export_phases,
    ledger_enabled,
    merge_timing,
    retire_adapter_phases,
)
from .flight import (  # noqa: F401
    FlightRecorder,
    get_flight_recorder,
)
from .flight import record as flight_record  # noqa: F401
from .goodput import (  # noqa: F401
    BADPUT_BUCKETS,
    BADPUT_SECONDS,
    GOODPUT_FRACTION,
    GOODPUT_SECONDS,
    WALL_SECONDS,
    GoodputLedger,
    record_badput,
)
from .stats import nearest_rank  # noqa: F401
from .slo import (  # noqa: F401
    SLO,
    SLO_EVENT_KIND,
    SLOEvaluator,
    SLOStatus,
)
from .timeseries import (  # noqa: F401
    TimeSeriesStore,
    get_store,
    grafana_query,
    parse_target,
    set_store,
)
from .tracing import (  # noqa: F401
    TRACE_HEADER,
    Span,
    Tracer,
    format_trace_header,
    get_tracer,
    new_trace_id,
    parse_trace_header,
    trace_id_for,
    tracer,
)
from .tracing import configure_from_mlconf as _configure_tracing
from .flight import configure_from_mlconf as _configure_flight


def configure_from_mlconf():
    """Apply ``mlconf.observability`` to the process tracer AND flight
    recorder (one call at every entrypoint: gateway, service, smoke)."""
    _configure_flight()
    return _configure_tracing()

# -- serving path ------------------------------------------------------------
REQUEST_LATENCY = REGISTRY.histogram(
    "mlt_request_latency_seconds",
    "End-to-end GraphServer.run latency per event")
STEP_LATENCY = REGISTRY.histogram(
    "mlt_step_latency_seconds",
    "Per-step execution latency in the serving graph",
    labels=("step",), overflow="drop")
SERVING_EVENTS = REGISTRY.counter(
    "mlt_serving_events_total",
    "Serving-path events mirrored from context.metrics (breaker trips, "
    "admission rejects, sheds, deadline expiries, drain rejections)",
    labels=("event",), overflow="drop")
PROBE_REQUESTS = REGISTRY.counter(
    "mlt_probe_requests_total",
    "Probe/scrape endpoint hits (healthz/readyz/stats/metrics) — counted "
    "here, excluded from request telemetry and never traced",
    labels=("path",), overflow="drop")
BREAKER_STATE = REGISTRY.gauge(
    "mlt_breaker_state",
    "Circuit breaker state per step (0 closed, 1 half-open, 2 open)",
    labels=("step",), overflow="drop")
SERVER_INFLIGHT = REGISTRY.gauge(
    "mlt_server_inflight", "In-flight events on the graph server")

# -- LLM engines -------------------------------------------------------------
# every family carries a ``replica`` label (empty for standalone engines)
# so a fleet's per-replica series are tellable apart; the TTFT/ITL/queue
# families additionally carry a bounded ``adapter`` label ("" = base
# model) so per-tenant SLOs and the autoscaler see tenants, not just
# replicas (docs/serving.md "Multi-tenant LoRA"). Cardinality is
# bounded: fleet replicas retire a stale tenant's series at scrape time
# and remove all their own series on stop (scale-down must not leak
# series — serving/fleet.py); standalone engines share the replica=""
# series, where max_label_sets + overflow="drop" is the backstop
LLM_TTFT = REGISTRY.histogram(
    "mlt_llm_ttft_seconds", "Time to first token (continuous batching)",
    labels=("replica", "adapter"), max_label_sets=256, overflow="drop")
LLM_ITL = REGISTRY.histogram(
    "mlt_llm_itl_seconds",
    "Inter-token latency: whole scheduler iterations that produced a "
    "decode step (observed once per adapter active in the tick)",
    labels=("replica", "adapter"), max_label_sets=256, overflow="drop",
    buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
             0.5, 1.0, 2.5))
LLM_DECODE_TICK = REGISTRY.histogram(
    "mlt_llm_decode_tick_seconds",
    "One decode dispatch (host-observed, admission prefill excluded) — "
    "the attention-dominated device step the paged/flash kernels target",
    labels=("replica",), max_label_sets=128, overflow="drop",
    buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
             0.5, 1.0, 2.5))
LLM_QUEUE_DEPTH = REGISTRY.gauge(
    "mlt_llm_queue_depth",
    "Queued + pending admissions per engine, split by adapter (the "
    "adapter=\"\" series carries the base/untenanted remainder, so the "
    "sum over adapter label values is the engine's total depth)",
    labels=("engine", "replica", "adapter"), max_label_sets=512,
    overflow="drop")
LLM_FREE_PAGE_FRAC = REGISTRY.gauge(
    "mlt_llm_free_page_frac",
    "Free (incl. reclaimable prefix) KV-page fraction, paged engines",
    labels=("engine", "replica"), overflow="drop")
LLM_EVENTS = REGISTRY.counter(
    "mlt_llm_events_total",
    "Cumulative engine events mirrored from stats() (requests, completed, "
    "shed, expired, prefix_hits, prefix_evictions, ...)",
    labels=("engine", "replica", "event"), max_label_sets=1024,
    overflow="drop")
# in-engine speculative decoding (docs/serving.md "Speculative
# decoding"): fed from engine stats at scrape time, removed on engine
# stop like the rest of the per-replica families
LLM_SPEC_ROUNDS = REGISTRY.counter(
    "mlt_llm_spec_rounds_total",
    "Speculative verify rounds (one multi-token verify dispatch covers "
    "every speculating row in the tick; each speculating row counts one "
    "round)",
    labels=("engine", "replica"), max_label_sets=512, overflow="drop")
LLM_SPEC_TOKENS = REGISTRY.counter(
    "mlt_llm_spec_tokens_total",
    "Draft tokens by verify outcome: accepted (matched the target "
    "argmax) vs rejected (rolled back on the KV by pos-rewind) — "
    "accepted/(accepted+rejected) is the fleet acceptance rate",
    labels=("engine", "replica", "outcome"), max_label_sets=512,
    overflow="drop")
# hierarchical KV cache (serving/kv_tier.py, docs/serving.md
# "Hierarchical KV"): fed event-side from the paged engine, removed on
# engine stop like the rest of the per-replica families
KV_TIER_BYTES = REGISTRY.gauge(
    "mlt_kv_tier_bytes",
    "Host-KV-tier bytes resident (demoted int8 pages + scales) per "
    "paged engine",
    labels=("engine", "replica"), overflow="drop")
KV_TIER_HITS = REGISTRY.counter(
    "mlt_kv_tier_hits_total",
    "Prefix-block admissions served by cache tier: device (page-pool "
    "radix hit), host (promote from the host tier), remote "
    "(cross-replica page fetch)",
    labels=("engine", "replica", "tier"), max_label_sets=512,
    overflow="drop")
KV_TIER_EVENTS = REGISTRY.counter(
    "mlt_kv_tier_events_total",
    "Hierarchical-KV movement by op (demote / promote / fetch) and "
    "outcome (ok / miss / fallback / error) — error and fallback "
    "outcomes degrade to plain token prefill, never a client error",
    labels=("engine", "replica", "op", "outcome"), max_label_sets=512,
    overflow="drop")

# -- multi-tenant adapters (serving/adapters.py) -----------------------------
ADAPTER_LIVE = REGISTRY.gauge(
    "mlt_adapter_live",
    "LoRA adapters currently resident in the engine's device bank "
    "(working set, base slot excluded)",
    labels=("engine", "replica"), overflow="drop")
ADAPTER_LOADS = REGISTRY.counter(
    "mlt_adapter_loads_total",
    "Adapter registry outcomes: ok (device load), evict (LRU "
    "displacement), error (failed artifact load), capacity (429 "
    "working-set full), unknown (404 bad tenant id), rate_limited "
    "(per-tenant fairness shed)",
    labels=("engine", "replica", "outcome"), max_label_sets=512,
    overflow="drop")

# -- engine fleet (serving/fleet.py) -----------------------------------------
FLEET_DISPATCHES = REGISTRY.counter(
    "mlt_fleet_dispatches_total",
    "Fleet routing outcomes per replica (ok / redispatch / failed / "
    "no_replica)",
    labels=("replica", "outcome"), max_label_sets=512, overflow="drop")
FLEET_HANDOFF_BYTES = REGISTRY.counter(
    "mlt_fleet_handoff_bytes_total",
    "KV bytes moved prefill-replica -> decode-replica (the batch=1 "
    "slot-cache serialization boundary)")
FLEET_HANDOFF_LATENCY = REGISTRY.histogram(
    "mlt_fleet_handoff_seconds",
    "Prefill-complete -> decode-slot-active latency for disaggregated "
    "requests (decode-side import + queueing)",
    buckets=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
             1.0, 2.5))
FLEET_REPLICAS = REGISTRY.gauge(
    "mlt_fleet_replicas", "Live fleet replicas by role",
    labels=("role",), overflow="drop")
FLEET_POD_EVENTS = REGISTRY.counter(
    "mlt_fleet_pod_events_total",
    "Serving-pod lifecycle transitions (serving/podfleet.py): scale_up /"
    " prewarm / ready / join / kill / redispatch / drain / delete",
    labels=("pod", "event"), max_label_sets=512, overflow="drop")
FLEET_POD_PHASE = REGISTRY.gauge(
    "mlt_fleet_pod_phase",
    "Serving-pod state-machine phase (0 pending, 1 warming, 2 ready, "
    "3 joined, 4 draining; the series is retired on delete)",
    labels=("pod",), max_label_sets=512, overflow="drop")
FLEET_POD_PREWARM_SECONDS = REGISTRY.histogram(
    "mlt_fleet_pod_prewarm_seconds",
    "Pod pre-warm wall (adapter working set + engine warmup + "
    "reassigned-prefix KV replay) before the ring join",
    buckets=(0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
             30.0))
REPLICA_HEALTH_SCORE = REGISTRY.gauge(
    "mlt_replica_health_score",
    "EWMA-smoothed peer-relative badness (robust z over the fleet "
    "median; obs/health.py ReplicaHealthScorer) — 0 is median-healthy, "
    "above suspect_z the replica is a fail-slow outlier",
    labels=("replica",), max_label_sets=512, overflow="drop")
REPLICA_HEALTH_STATE = REGISTRY.gauge(
    "mlt_replica_health_state",
    "Replica health state machine position (0 healthy, 1 suspect, "
    "2 probation; retired with the replica's other series on stop)",
    labels=("replica",), max_label_sets=512, overflow="drop")
HEALTH_TRANSITIONS = REGISTRY.counter(
    "mlt_health_transitions_total",
    "Health state-machine transitions per replica, labeled by the state "
    "entered (suspect / probation / healthy)",
    labels=("replica", "to"), max_label_sets=512, overflow="drop")

# -- control-plane crash recovery (common/journal.py + per-controller
# reconcile — docs/fault_tolerance.md "Control-plane crash recovery") --------
RECONCILE_ACTIONS = REGISTRY.counter(
    "mlt_reconcile_actions_total",
    "Intent-vs-world convergence actions taken by a restarted controller"
    " (podfleet: adopt / resume_drain / orphan_deleted / orphan_vanished"
    " / skip_unknown; autoscaler: cooldown_armed / adopt_drain; canary: "
    "adopt_split / adopt_retrain)",
    labels=("controller", "action"), max_label_sets=64, overflow="drop")
RECONCILE_SECONDS = REGISTRY.histogram(
    "mlt_reconcile_seconds",
    "Wall time of one reconcile() pass (journal replay + world listing "
    "+ convergence) on controller restart",
    buckets=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
             1.0, 2.5, 5.0))
JOURNAL_WRITES = REGISTRY.counter(
    "mlt_journal_writes_total",
    "Intent-journal appends by outcome (ok / failed — a failed append "
    "degrades recovery fidelity, never the control loop)",
    labels=("journal", "outcome"), max_label_sets=64, overflow="drop")

# -- model monitoring / continuous tuning (model_monitoring/,
# serving/canary.py — docs/continuous_tuning.md) -----------------------------
DRIFT_STAT = REGISTRY.gauge(
    "mlt_drift_stat",
    "Windowed per-adapter traffic statistics from the serving-side "
    "sample analyzer (stat = token_psi | token_kld | length_psi | "
    "quality_mean | ttft_mean_s | sample_count); the quality_delta SLO "
    "kind compares these canary-vs-stable",
    labels=("adapter", "stat"), max_label_sets=512, overflow="drop")
DRIFT_EVENTS = REGISTRY.counter(
    "mlt_drift_events_total",
    "Drift state-machine transitions per adapter (detected | confirmed "
    "| retrain_submitted | retrain_failed)",
    labels=("adapter", "event"), max_label_sets=512, overflow="drop")
CANARY_REQUESTS = REGISTRY.counter(
    "mlt_canary_requests_total",
    "Requests resolved through the canary hash split, by side (the "
    "adapter label is the TENANT id, not the versioned adapter id)",
    labels=("adapter", "side"), max_label_sets=512, overflow="drop")
CANARY_STATE = REGISTRY.gauge(
    "mlt_canary_state",
    "Canary lifecycle per tenant: 0 none, 1 canary serving a split, "
    "2 last canary promoted, -1 last canary rolled back",
    labels=("adapter",), max_label_sets=256, overflow="drop")
CANARY_DECISIONS = REGISTRY.counter(
    "mlt_canary_decisions_total",
    "Closed-loop decisions per tenant (start | promote | rollback)",
    labels=("adapter", "decision"), max_label_sets=512, overflow="drop")

# -- run lifecycle -----------------------------------------------------------
RUN_SUBMITS = REGISTRY.counter(
    "mlt_run_submits_total", "Runs launched via the server-side launcher",
    labels=("kind",), overflow="drop")
RUN_RETRIES = REGISTRY.counter(
    "mlt_run_retries_total",
    "Failed resources resubmitted by the monitor, by failure class",
    labels=("failure_class",), overflow="drop")
RUN_STALL_ABORTS = REGISTRY.counter(
    "mlt_run_stall_aborts_total",
    "Runs aborted by the heartbeat-stall watchdog")

# -- autoscaler (service/autoscaler.py) --------------------------------------
AUTOSCALER_RECOMMENDATIONS = REGISTRY.counter(
    "mlt_autoscaler_recommendations_total",
    "Scale recommendations the signal evaluation produced (recorded in "
    "dry-run too — the act/observe seam)",
    labels=("action", "reason"), overflow="drop")
AUTOSCALER_ACTIONS = REGISTRY.counter(
    "mlt_autoscaler_actions_total",
    "Scale actions actually applied to the fleet (add / drain / remove)",
    labels=("action",), overflow="drop")
AUTOSCALER_DESIRED = REGISTRY.gauge(
    "mlt_autoscaler_desired_replicas",
    "Worker-replica count the autoscaler currently wants")

# -- chaos / training --------------------------------------------------------
CHAOS_FIRED = REGISTRY.counter(
    "mlt_chaos_fired_total",
    "Armed fault injections whose effect actually fired, by point",
    labels=("point",), overflow="drop")
TRAIN_MFU = REGISTRY.gauge(
    "mlt_training_mfu", "Last computed model FLOPs utilization")
TRAIN_STEP_TIME = REGISTRY.gauge(
    "mlt_train_step_seconds", "Last step wall time per StepTimer",
    labels=("timer",), overflow="drop")
TRAIN_INPUT_WAIT = REGISTRY.counter(
    "mlt_train_input_wait_seconds",
    "Cumulative seconds the training loop spent blocked waiting on the "
    "input pipeline (next(data_iter)) — a growing rate proves the run is "
    "input-bound, not FLOPs-bound")
TRAIN_H2D_BYTES = REGISTRY.counter(
    "mlt_train_h2d_bytes_total",
    "Host->device batch bytes issued by the training input path "
    "(device prefetch stage or inline shard_batch)")
TRAIN_COMPILE_SECONDS = REGISTRY.gauge(
    "mlt_train_compile_seconds",
    "Wall seconds of the last train-step XLA compile (Trainer.warmup or "
    "the first fit step) — near-zero after a persistent-cache hit")
TRAIN_LOADER_OCCUPANCY = REGISTRY.gauge(
    "mlt_train_loader_ring_occupancy",
    "Staged batches currently in the native TokenShardLoader ring buffer "
    "(0 with consumer waits climbing = input-bound)",
    labels=("loader",), overflow="drop")
TRAIN_LOADER_EVENTS = REGISTRY.counter(
    "mlt_train_loader_events_total",
    "Cumulative TokenShardLoader counters mirrored from stats() "
    "(batches, consumer_waits, producer_waits, epochs)",
    labels=("loader", "event"), max_label_sets=512, overflow="drop")

# -- memory (utils/profiler.memory_sample, scrape-time) ----------------------
DEVICE_MEM = REGISTRY.gauge(
    "mlt_device_mem_bytes",
    "Device memory snapshot per accelerator (kind = in_use | peak | "
    "limit), read at scrape time by the weakref collector trainers and "
    "LLM engines register (register_memory_collector)",
    labels=("device", "kind"), max_label_sets=512, overflow="drop")
HOST_RSS = REGISTRY.gauge(
    "mlt_host_rss_bytes",
    "Resident set size of this process (VmRSS), scrape-time")


# owners (trainers, engines) that asked for memory exposition; ONE shared
# scrape-time collector serves them all — the sample is process-wide, so
# a trainer and two engines registering must not triple the device reads
_memory_lock = _threading.Lock()
_memory_refs: set = set()
_memory_active = [False]


def register_memory_collector(owner) -> None:
    """Publish ``mlt_device_mem_bytes{device,kind}`` + host RSS while
    ``owner`` is alive (weakref; the collector retires itself when every
    registered owner is gone — the standard scrape-collector contract)."""
    import weakref

    with _memory_lock:
        try:
            _memory_refs.add(weakref.ref(owner))
        except TypeError:  # non-weakrefable owner: nothing to key
            return         # liveness on — skip rather than pin it forever
        if _memory_active[0]:
            return
        _memory_active[0] = True

    def _collect():
        with _memory_lock:
            for ref in list(_memory_refs):
                if ref() is None:
                    _memory_refs.discard(ref)
            if not _memory_refs:
                _memory_active[0] = False
                # the scrape-collector contract: retire the series WITH
                # the collector, or every later scrape exports a frozen
                # memory snapshot that looks live
                DEVICE_MEM.clear()
                HOST_RSS.clear()
                return False
        from ..utils.profiler import memory_sample

        sample = memory_sample()
        for device, kinds in sample.get("devices", {}).items():
            for kind, value in kinds.items():
                if value is not None:
                    DEVICE_MEM.set(value, device=device, kind=kind)
        rss = sample.get("host_rss_bytes")
        if rss is not None:
            HOST_RSS.set(rss)
        return True

    REGISTRY.add_collector(_collect)


def _install_chaos_observer():
    """Count fired injections AND land them on the flight recorder
    without giving chaos/registry (a bottom layer that must not import
    mlrun_tpu) any dependency: the hook is pushed in from above."""
    from ..chaos.registry import set_fire_observer

    def _observe(point):
        CHAOS_FIRED.inc(point=point)
        flight_record("chaos.fire", point=point)

    set_fire_observer(_observe)


_install_chaos_observer()
