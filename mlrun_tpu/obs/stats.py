"""Tiny shared statistics helpers for the observability layer.

One canonical nearest-rank percentile: the engine latency rings
(``serving/llm_batch._percentile``) and the trainer's
``utils/profiler.StepTimer.summary`` both quote p50/p95, and two
hand-rolled index formulas drifted apart — ``int(n * q)`` picks the
order statistic ONE RANK HIGH of the nearest-rank definition whenever
``q * n`` is an integer (p95 of 100 samples must be the 95th smallest,
``ceil(0.95 * 100) = 95`` → index 94, not index 95). Stdlib only, same
bottom-layer rule as the rest of ``obs/``.
"""

from __future__ import annotations

import math


def nearest_rank(sorted_samples, q: float) -> float:
    """Nearest-rank percentile: the ``ceil(q * n)``-th order statistic of
    an already-sorted, non-empty sample sequence (0 < q <= 1)."""
    n = len(sorted_samples)
    if n == 0:
        raise ValueError("nearest_rank needs at least one sample")
    idx = max(0, math.ceil(q * n) - 1)
    return sorted_samples[min(idx, n - 1)]
