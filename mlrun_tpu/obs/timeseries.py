"""Bounded fixed-resolution time-series store over aggregated metrics
(docs/observability.md "Federation" → "Time series").

The federation layer (``obs/federation.py``) answers "what is the fleet
doing NOW"; SLO burn rates and the autoscaler need "what happened over
the last N minutes". This store is the smallest structure that answers
both windowed questions deterministically:

- one bounded ring per series at a fixed resolution (a sample lands in
  bucket ``floor(ts / resolution)``; last write within a bucket wins;
  the ring holds ``capacity`` buckets, so retention =
  ``resolution * capacity`` with O(1) memory per series);
- counter-aware ``rate()``/``increase()`` (sums positive deltas, treats
  a reset as the post-reset value — the Prometheus convention);
- histogram-cumulative → quantile: ``quantile()`` computes windowed
  bucket increases, merges them across label sets (the fleet-wide p95
  over every replica's TTFT histogram), and linearly interpolates inside
  the winning bucket;
- the grafana simpleJSON contract (``/search`` + ``/query`` in
  ``service/api/monitoring.py``) via :func:`parse_target` /
  :func:`grafana_query`.

Stdlib only at module level; ``from_mlconf`` lazy-imports the config.
"""

from __future__ import annotations

import math
import re
import threading
from typing import Optional

_TARGET_RE = re.compile(
    r"^(?:(?P<fn>rate|p50|p90|p95|p99)\()?"
    r"(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>[^}]*)\})?"
    r"(?(fn)\))"
    r"(?:\[(?P<window>[0-9]+(?:\.[0-9]+)?)\])?$")
_TARGET_LABEL_RE = re.compile(
    r'([a-zA-Z_][a-zA-Z0-9_]*)="([^"]*)"')

_QUANTILE_FNS = {"p50": 0.50, "p90": 0.90, "p95": 0.95, "p99": 0.99}


class _Ring:
    """Fixed-resolution circular buffer: slot i holds the value for
    bucket index ``head - (capacity - 1 - offset)``; ``None`` = no
    sample landed in that bucket."""

    __slots__ = ("values", "head", "kind")

    def __init__(self, capacity: int, kind: str):
        self.values: list = [None] * capacity
        self.head: Optional[int] = None  # newest bucket index
        self.kind = kind

    def put(self, bucket: int, value: float):
        capacity = len(self.values)
        if self.head is None:
            self.head = bucket
        elif bucket > self.head:
            # clear the buckets we skipped so stale values from a prior
            # lap never masquerade as fresh samples
            for skipped in range(self.head + 1, min(bucket,
                                                    self.head + capacity)):
                self.values[skipped % capacity] = None
            if bucket - self.head >= capacity:
                self.values = [None] * capacity
            self.head = bucket
        elif self.head - bucket >= capacity:
            return  # older than the ring's retention — drop
        self.values[bucket % capacity] = value

    def points(self, start_bucket: int, end_bucket: int):
        """``[(bucket, value)]`` for non-empty buckets in range."""
        if self.head is None:
            return []
        capacity = len(self.values)
        lo = max(start_bucket, self.head - capacity + 1)
        hi = min(end_bucket, self.head)
        out = []
        for bucket in range(lo, hi + 1):
            value = self.values[bucket % capacity]
            if value is not None:
                out.append((bucket, value))
        return out


class TimeSeriesStore:
    """Bounded store of ``(family, labels) → ring``; all reads take an
    explicit ``at`` so windows are deterministic in tests."""

    def __init__(self, resolution_s: float = 5.0, capacity: int = 720,
                 max_series: int = 2048):
        if resolution_s <= 0 or capacity <= 0 or max_series <= 0:
            raise ValueError("resolution_s, capacity, max_series must "
                             "be > 0")
        self.resolution_s = float(resolution_s)
        self.capacity = int(capacity)
        self.max_series = int(max_series)
        self.dropped_series = 0
        self._lock = threading.Lock()
        self._series: dict[tuple, _Ring] = {}

    @classmethod
    def from_mlconf(cls, **overrides) -> "TimeSeriesStore":
        from ..config import mlconf

        ts = mlconf.observability.timeseries
        kwargs = {"resolution_s": float(ts.resolution_s),
                  "capacity": int(ts.capacity),
                  "max_series": int(ts.max_series)}
        kwargs.update(overrides)
        return cls(**kwargs)

    # -- writes --------------------------------------------------------------
    def _bucket(self, ts: float) -> int:
        return int(ts // self.resolution_s)

    def record(self, name: str, value: float, at: float,
               labels: Optional[dict] = None, kind: str = "gauge"):
        key = (name, frozenset(
            (k, str(v)) for k, v in (labels or {}).items()))
        with self._lock:
            ring = self._series.get(key)
            if ring is None:
                if len(self._series) >= self.max_series:
                    self.dropped_series += 1
                    return
                ring = _Ring(self.capacity, kind)
                self._series[key] = ring
            ring.put(self._bucket(at), float(value))

    def drop_series(self, name: Optional[str] = None,
                    labels: Optional[dict] = None):
        """Remove series matching name (+ label subset); ``name=None``
        matches every family — the scale-down path retires a removed
        replica's series across all of them so a churning fleet cannot
        fill ``max_series`` with dead rings."""
        match = set(((k, str(v)) for k, v in (labels or {}).items()))
        with self._lock:
            for key in [k for k in self._series
                        if (name is None or k[0] == name)
                        and match <= set(k[1])]:
                del self._series[key]

    # -- reads ---------------------------------------------------------------
    def _select(self, name: str, labels: Optional[dict] = None):
        match = set(((k, str(v)) for k, v in (labels or {}).items()))
        with self._lock:
            return [(key[1], ring) for key, ring in self._series.items()
                    if key[0] == name and match <= set(key[1])]

    def series(self) -> list[dict]:
        with self._lock:
            return [{"name": name, "labels": dict(labels)}
                    for name, labels in sorted(
                        self._series, key=lambda k: (k[0], sorted(k[1])))]

    def search(self, query: str = "") -> list[str]:
        """Series descriptors (``name{k="v",...}``) matching a substring
        — the grafana ``/search`` payload."""
        out = []
        for entry in self.series():
            labels = ",".join(f'{k}="{v}"' for k, v in
                              sorted(entry["labels"].items()))
            desc = entry["name"] + (f"{{{labels}}}" if labels else "")
            if query.lower() in desc.lower():
                out.append(desc)
        return out

    def points(self, name: str, start: float, end: float,
               labels: Optional[dict] = None, agg: str = "sum"):
        """Bucket-aligned ``[(ts, value)]`` over matching series,
        aggregated per bucket (``sum``/``max``/``avg``)."""
        per_bucket: dict[int, list] = {}
        for _, ring in self._select(name, labels):
            for bucket, value in ring.points(self._bucket(start),
                                             self._bucket(end)):
                per_bucket.setdefault(bucket, []).append(value)
        out = []
        for bucket in sorted(per_bucket):
            values = per_bucket[bucket]
            if agg == "max":
                value = max(values)
            elif agg == "avg":
                value = sum(values) / len(values)
            else:
                value = sum(values)
            out.append((bucket * self.resolution_s, value))
        return out

    def latest(self, name: str, at: float,
               labels: Optional[dict] = None,
               agg: str = "sum") -> Optional[float]:
        pts = self.points(name, at - self.capacity * self.resolution_s,
                          at, labels=labels, agg=agg)
        return pts[-1][1] if pts else None

    @staticmethod
    def _ring_increase(ring, start_bucket: int,
                       end_bucket: int) -> Optional[float]:
        """Reset-aware counter increase over one ring's window: sums
        positive deltas; a drop to a smaller value contributes the
        post-reset value, never a negative delta. None = no points."""
        pts = ring.points(start_bucket, end_bucket)
        if not pts:
            return None
        prev = pts[0][1]
        inc = 0.0
        for _, value in pts[1:]:
            inc += value - prev if value >= prev else value
            prev = value
        return inc

    def increase(self, name: str, window: float, at: float,
                 labels: Optional[dict] = None) -> float:
        """Windowed counter increase summed across matching series."""
        total = 0.0
        start_bucket = self._bucket(at - window)
        end_bucket = self._bucket(at)
        for _, ring in self._select(name, labels):
            inc = self._ring_increase(ring, start_bucket, end_bucket)
            if inc is not None:
                total += inc
        return total

    def rate(self, name: str, window: float, at: float,
             labels: Optional[dict] = None) -> float:
        return self.increase(name, window, at, labels) / window \
            if window > 0 else 0.0

    # -- histogram queries ---------------------------------------------------
    def _bucket_increases(self, family: str, window: float, at: float,
                          labels: Optional[dict] = None) -> list:
        """Windowed increase per ``le`` bound, summed across every other
        label dimension (fleet-wide): ``[(bound, increase)]`` sorted."""
        per_le: dict[float, float] = {}
        start_bucket = self._bucket(at - window)
        end_bucket = self._bucket(at)
        for series_labels, ring in self._select(family + "_bucket", labels):
            le = dict(series_labels).get("le")
            if le is None:
                continue
            bound = math.inf if le == "+Inf" else float(le)
            inc = self._ring_increase(ring, start_bucket, end_bucket)
            if inc is None:
                continue
            per_le[bound] = per_le.get(bound, 0.0) + inc
        return sorted(per_le.items())

    def quantile(self, family: str, q: float, window: float, at: float,
                 labels: Optional[dict] = None) -> Optional[float]:
        """Windowed quantile from cumulative bucket counters (Prometheus
        ``histogram_quantile`` semantics: linear interpolation inside the
        winning bucket; the +Inf bucket answers with the highest finite
        bound). None when the window saw no observations."""
        buckets = self._bucket_increases(family, window, at, labels)
        if not buckets:
            return None
        total = buckets[-1][1]
        if total <= 0:
            return None
        target = q * total
        prev_bound, prev_cum = 0.0, 0.0
        for bound, cum in buckets:
            if cum >= target:
                if math.isinf(bound):
                    return prev_bound
                if cum == prev_cum:
                    return bound
                frac = (target - prev_cum) / (cum - prev_cum)
                return prev_bound + frac * (bound - prev_bound)
            prev_bound, prev_cum = bound, cum
        return prev_bound

    def fraction_over(self, family: str, threshold: float, window: float,
                      at: float,
                      labels: Optional[dict] = None) -> Optional[float]:
        """Fraction of windowed observations above ``threshold`` — the
        latency-SLO "bad events" ratio, interpolated within the bucket
        the threshold falls into. None when the window saw nothing."""
        buckets = self._bucket_increases(family, window, at, labels)
        if not buckets:
            return None
        total = buckets[-1][1]
        if total <= 0:
            return None
        prev_bound, prev_cum = 0.0, 0.0
        under = total
        for bound, cum in buckets:
            if threshold <= bound:
                if math.isinf(bound):
                    # the threshold is past the highest finite bound:
                    # where +Inf-bucket observations fall relative to it
                    # is unknown — count them as OVER (a total-outage
                    # histogram must not read as 0.0 bad fraction just
                    # because its buckets top out below the target)
                    under = prev_cum
                elif bound == prev_bound:
                    under = cum
                else:
                    frac = (threshold - prev_bound) / (bound - prev_bound)
                    under = prev_cum + frac * (cum - prev_cum)
                break
            prev_bound, prev_cum = bound, cum
        return max(0.0, min(1.0, (total - under) / total))


# -- grafana simpleJSON contract ---------------------------------------------
def parse_target(spec: str):
    """Parse a simpleJSON target: ``name``, ``name{k="v",...}``,
    ``rate(name{...})[window]``, ``p95(family)[window]``. Returns
    ``(fn, name, labels, window)``; fn None = raw series."""
    match = _TARGET_RE.match(spec.strip())
    if not match:
        raise ValueError(f"bad target: {spec!r}")
    labels = dict(_TARGET_LABEL_RE.findall(match.group("labels") or ""))
    window = float(match.group("window")) if match.group("window") else 60.0
    return match.group("fn"), match.group("name"), labels, window


# function targets evaluate per bucket — cap the response (and the CPU
# spent in the executor) for arbitrarily wide dashboard ranges by
# striding, grafana maxDataPoints-style
GRAFANA_MAX_POINTS = 2000


def grafana_query(store: TimeSeriesStore, spec: str, start: float,
                  end: float) -> dict:
    """One simpleJSON ``timeserie`` response entry for ``spec``:
    ``{"target", "datapoints": [[value, ts_millis], ...]}``. Function
    targets (rate/pXX) evaluate per bucket over their trailing window,
    strided down to at most :data:`GRAFANA_MAX_POINTS` points."""
    if end < start:
        raise ValueError(f"range end {end} before start {start}")
    fn, name, labels, window = parse_target(spec)
    datapoints = []
    if fn is None:
        for ts, value in store.points(name, start, end, labels=labels):
            datapoints.append([value, ts * 1000.0])
    elif fn != "rate" or store._select(name, labels):
        # (a rate over a series the store has never seen returns 0.0,
        # not None — skip it entirely so "no data" stays distinguishable
        # from "zero traffic" on the panel)
        step = store.resolution_s
        steps = int((end - start) // step) + 1
        stride = step * max(1, math.ceil(steps / GRAFANA_MAX_POINTS))
        ts = math.ceil(start / step) * step
        while ts <= end:
            if fn == "rate":
                value = store.rate(name, window, ts, labels=labels)
            else:
                value = store.quantile(name, _QUANTILE_FNS[fn], window,
                                       ts, labels=labels)
            if value is not None:
                datapoints.append([value, ts * 1000.0])
            ts += stride
    return {"target": spec, "datapoints": datapoints}


# -- process-global store -----------------------------------------------------
# the service API's grafana proxy and the SLO/autoscaler loops share one
# store per process (tests swap it with set_store)
_STORE: Optional[TimeSeriesStore] = None
_STORE_LOCK = threading.Lock()


def get_store() -> TimeSeriesStore:
    global _STORE
    with _STORE_LOCK:
        if _STORE is None:
            _STORE = TimeSeriesStore.from_mlconf()
        return _STORE


def set_store(store: Optional[TimeSeriesStore]):
    global _STORE
    with _STORE_LOCK:
        _STORE = store
