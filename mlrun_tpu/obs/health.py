"""Peer-relative replica health scoring: fail-slow detection
(docs/observability.md "Replica health & fail-slow detection").

At fleet scale the failures that hurt p95 are not crashes but *fail-slow*
replicas — a throttled, contended, or link-degraded pod answers every
request correctly, just 3–10x slower, and the error-path machinery
(circuit breaker, ``redispatchable()`` re-routing) never sees it: nothing
errors. Worse, affinity routing keeps pinning hot prefixes to the slow
replica. This module closes that blind spot with a control loop over
signals the fleet already produces:

- **peer-relative, not absolute.** Thresholds on absolute latency break
  on every model/hardware change; a replica is sick when it is an
  *outlier against its peers right now*. Each signal is scored as a
  robust z against the fleet median, with MAD (median absolute
  deviation) as the scale — both are immune to the outlier dragging the
  baseline toward itself, which is exactly what mean/stddev get wrong.
- **EWMA + hysteresis.** The per-replica badness score is EWMA-smoothed
  and state transitions require consecutive-tick streaks, so one slow
  GC pause or compile stall never probates a healthy replica.
- **graduated actuation.** healthy → suspect (observe only) → probation:
  the fleet de-weights the replica's ring vnodes
  (``EngineFleet.set_replica_weight``) so traffic shifts gradually with
  minimal key movement — a slow-but-correct replica deserves less
  traffic, not death. Only *persistent* probation makes it a replacement
  candidate (``pop_replace_due``), which the autoscaler executes through
  the normal drain → delete → below-min-repair lifecycle.

Time is an explicit ``now`` argument to :meth:`tick` (MLT003,
analysis/clock.py): every detection drill runs on a fake clock with zero
sleeps. The module never reads a wall clock.
"""

from __future__ import annotations

from ..config import mlconf
from ..utils import logger
from . import HEALTH_TRANSITIONS, REPLICA_HEALTH_SCORE, REPLICA_HEALTH_STATE
from .flight import record as flight_record

# (signal key in EngineFleet.stats per_replica, MAD floor). The floor
# bounds the z denominator from below so a near-uniform fleet (MAD ~ 0)
# cannot turn measurement noise into huge z-scores: a replica must
# exceed the median by a *materially meaningful* margin, not a
# statistically tiny one. Floors are in the signal's own units.
SIGNALS = (
    ("ttft_p95_s", 0.005),
    ("decode_tick_p95_s", 0.002),
    ("queue_depth", 2.0),
    ("dispatch_failure_rate", 0.05),
    ("fetch_fallback_rate", 0.10),
)

# robust z-scores are capped so a single grotesque outlier saturates
# instead of poisoning the EWMA for many recovery ticks
_Z_CAP = 16.0

_STATE_VALUES = {"healthy": 0, "suspect": 1, "probation": 2}


def _median(values: list[float]) -> float:
    ordered = sorted(values)
    mid = len(ordered) // 2
    if len(ordered) % 2:
        return ordered[mid]
    return (ordered[mid - 1] + ordered[mid]) / 2.0


class _ReplicaState:
    """Per-replica scorer memory across ticks."""

    __slots__ = ("score", "state", "bad", "good", "probation_age",
                 "replace_flagged")

    def __init__(self):
        self.score = None        # EWMA-smoothed badness (None = no tick)
        self.state = "healthy"
        self.bad = 0             # consecutive ticks at/above suspect_z
        self.good = 0            # consecutive ticks below recover_z
        self.probation_age = 0   # ticks spent in probation (cumulative)
        self.replace_flagged = False


class ReplicaHealthScorer:
    """One scorer per :class:`~mlrun_tpu.serving.fleet.EngineFleet`.

    ``store`` (an ``obs.TimeSeriesStore``) fills the TTFT signal for
    process replicas whose engine stats don't travel in
    ``fleet.stats`` — the federated ``mlt_llm_ttft_seconds{replica}``
    windowed quantile. Optional: an in-process fleet needs no federation
    plumbing.

    Knobs read ``mlconf.serving.health`` and accept keyword overrides
    (the autoscaler convention); unknown overrides raise.
    """

    def __init__(self, fleet, store=None, ttft_window: float = 60.0,
                 **overrides):
        conf = mlconf.serving.health

        def knob(name, cast=float):
            if name in overrides:
                return cast(overrides.pop(name))
            return cast(getattr(conf, name))

        self.fleet = fleet
        self.store = store
        self.ttft_window = float(ttft_window)
        self.enabled = knob("enabled", bool)
        self.ewma_alpha = knob("ewma_alpha")
        self.suspect_z = knob("suspect_z")
        self.recover_z = knob("recover_z")
        self.suspect_ticks = knob("suspect_ticks", int)
        self.probation_ticks = knob("probation_ticks", int)
        self.recover_ticks = knob("recover_ticks", int)
        self.probation_weight = knob("probation_weight")
        self.replace_after_ticks = knob("replace_after_ticks", int)
        self.min_peers = knob("min_peers", int)
        if overrides:
            raise ValueError(
                f"unknown health scorer knobs: {sorted(overrides)}")
        if not 0.0 < self.ewma_alpha <= 1.0:
            raise ValueError(
                f"need 0 < ewma_alpha <= 1, got {self.ewma_alpha}")
        if not 0.0 <= self.recover_z <= self.suspect_z:
            raise ValueError(
                f"need 0 <= recover_z <= suspect_z, got "
                f"{self.recover_z}..{self.suspect_z}")
        if not 0.0 < self.probation_weight <= 1.0:
            raise ValueError(
                f"need 0 < probation_weight <= 1, got "
                f"{self.probation_weight}")
        self._states: dict[str, _ReplicaState] = {}
        self._replace_due: list[str] = []

    # -- introspection -------------------------------------------------------
    def state(self, replica_id: str) -> str:
        entry = self._states.get(replica_id)
        return entry.state if entry is not None else "healthy"

    def score(self, replica_id: str) -> float:
        entry = self._states.get(replica_id)
        return entry.score if entry is not None and \
            entry.score is not None else 0.0

    def pop_replace_due(self):
        """One persistently-probated replica id, or None. The consumer
        (autoscaler) executes the replacement; popping is destructive so
        a replica is handed out exactly once."""
        return self._replace_due.pop(0) if self._replace_due else None

    # -- signal plane --------------------------------------------------------
    def _candidate_rows(self, now: float) -> dict[str, dict]:
        """Scoring population: non-draining, non-joining replicas from
        ``fleet.stats`` ``per_replica``. A draining victim or a warming
        joiner is *expected* to look unlike its peers — scoring it would
        both smear the baseline and flag lifecycle as sickness."""
        per = (self.fleet.stats.get("per_replica") or {})
        rows = {rid: dict(stats) for rid, stats in per.items()
                if not stats.get("draining") and not stats.get("joining")}
        if self.store is not None:
            # process replicas: the pod client's stats dict carries no
            # engine latency — fall back to the federated quantile
            for rid, row in rows.items():
                if row.get("ttft_p95_s") is None:
                    row["ttft_p95_s"] = self.store.quantile(
                        "mlt_llm_ttft_seconds", 0.95, self.ttft_window,
                        now, labels={"replica": rid})
        return rows

    def _raw_scores(self, rows: dict[str, dict]) -> dict[str, float]:
        """Max-over-signals robust z per replica. A signal participates
        only when >= min_peers replicas report it — a 2-replica fleet
        has no meaningful median, and a signal only one engine exports
        must not condemn that engine for being observable."""
        raw = {rid: 0.0 for rid in rows}
        for key, floor in SIGNALS:
            values = {rid: float(row[key]) for rid, row in rows.items()
                      if row.get(key) is not None}
            if len(values) < self.min_peers:
                continue
            med = _median(list(values.values()))
            mad = _median([abs(v - med) for v in values.values()])
            scale = max(1.4826 * mad, floor)
            for rid, value in values.items():
                z = min(max((value - med) / scale, 0.0), _Z_CAP)
                if z > raw[rid]:
                    raw[rid] = z
        return raw

    # -- state machine -------------------------------------------------------
    def _transition(self, rid: str, entry: _ReplicaState, to: str,
                    now: float):
        entry.state = to
        HEALTH_TRANSITIONS.inc(replica=rid, to=to)
        for replica in self.fleet.replicas:
            if replica.id == rid:
                replica.health_state = to
                break

    def _actuate_weight(self, rid: str, weight: float):
        setter = getattr(self.fleet, "set_replica_weight", None)
        if setter is None:
            return
        try:
            setter(rid, weight)
        except KeyError:
            pass  # removed between stats snapshot and actuation

    def tick(self, now: float) -> dict:
        """One scoring pass at ``now``: window the signals, score each
        replica peer-relative, advance the state machines, actuate ring
        weights, and publish gauges. Deterministic — no internal clock
        reads, no sleeps."""
        if not self.enabled:
            return {}
        rows = self._candidate_rows(now)
        raw = self._raw_scores(rows)
        snapshot: dict[str, dict] = {}
        for rid, raw_score in raw.items():
            entry = self._states.setdefault(rid, _ReplicaState())
            if entry.score is None:
                entry.score = raw_score
            else:
                entry.score = (self.ewma_alpha * raw_score
                               + (1.0 - self.ewma_alpha) * entry.score)
            if entry.score >= self.suspect_z:
                entry.bad += 1
                entry.good = 0
            elif entry.score < self.recover_z:
                entry.good += 1
                entry.bad = 0
            else:
                # hysteresis band: not sick enough to advance, not well
                # enough to recover — freeze the bad streak, reset good
                entry.good = 0
            if entry.state == "healthy" \
                    and entry.bad >= self.suspect_ticks:
                self._transition(rid, entry, "suspect", now)
                flight_record("health.suspect", replica=rid,
                              score=round(entry.score, 3), at=now)
                logger.warning("replica health: suspect", replica=rid,
                               score=entry.score)
            if entry.state == "suspect" and entry.bad >= \
                    self.suspect_ticks + self.probation_ticks:
                self._transition(rid, entry, "probation", now)
                self._actuate_weight(rid, self.probation_weight)
                flight_record("health.probation", replica=rid,
                              score=round(entry.score, 3),
                              weight=self.probation_weight, at=now)
                logger.warning("replica health: probation", replica=rid,
                               score=entry.score,
                               weight=self.probation_weight)
            if entry.state == "probation":
                entry.probation_age += 1
                if entry.probation_age >= self.replace_after_ticks \
                        and not entry.replace_flagged:
                    # persistently sick: hand it to the autoscaler as a
                    # replacement candidate exactly once
                    entry.replace_flagged = True
                    self._replace_due.append(rid)
            if entry.state in ("suspect", "probation") \
                    and entry.good >= self.recover_ticks:
                was_probation = entry.state == "probation"
                self._transition(rid, entry, "healthy", now)
                if was_probation:
                    self._actuate_weight(rid, 1.0)
                entry.bad = 0
                entry.probation_age = 0
                entry.replace_flagged = False
                if rid in self._replace_due:
                    self._replace_due.remove(rid)
                flight_record("health.recovered", replica=rid,
                              score=round(entry.score, 3), at=now)
                logger.info("replica health: recovered", replica=rid,
                            score=entry.score)
            REPLICA_HEALTH_SCORE.set(entry.score, replica=rid)
            REPLICA_HEALTH_STATE.set(_STATE_VALUES[entry.state],
                                     replica=rid)
            snapshot[rid] = {"score": entry.score, "state": entry.state}
        # forget replicas that left the population (drained, removed):
        # their registry series are retired by remove_replica; dropping
        # scorer memory here keeps a churning fleet's state bounded and
        # re-admits a rejoining id with a clean slate
        for rid in [r for r in self._states if r not in raw]:
            self._states.pop(rid)
            if rid in self._replace_due:
                self._replace_due.remove(rid)
            REPLICA_HEALTH_SCORE.remove(replica=rid)
            REPLICA_HEALTH_STATE.remove(replica=rid)
        return snapshot
