from .base import BaseLauncher  # noqa: F401
from .factory import LauncherFactory  # noqa: F401
from .local import ClientLocalLauncher  # noqa: F401
from .remote import ClientRemoteLauncher  # noqa: F401
