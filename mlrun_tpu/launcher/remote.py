"""Client-side remote launcher (reference analog: mlrun/launcher/remote.py:34
ClientRemoteLauncher.launch, :123 _submit_job)."""

from __future__ import annotations

from ..common.runtimes_constants import RunStates
from ..model import RunObject
from ..utils import logger
from .base import BaseLauncher


class ClientRemoteLauncher(BaseLauncher):
    """Stores the function in the service and POSTs the run to /submit_job."""

    def launch(self, runtime, task: RunObject, schedule=None, watch=True,
               auto_build=False, **kwargs) -> RunObject:
        self.enrich_runtime(runtime)
        run = self._enrich_run(runtime, task)
        self._validate_run(run)
        db = runtime._get_db()

        if auto_build and not runtime.is_deployed:
            deploy = getattr(runtime, "deploy", None)
            if deploy:
                deploy()

        # store the function so the server launcher can rebuild it
        self._store_function(runtime, run, db)
        return self._submit_job(runtime, run, db, schedule, watch)

    @staticmethod
    def _store_function(runtime, run: RunObject, db):
        hash_key = db.store_function(
            runtime.to_dict(), runtime.metadata.name,
            run.metadata.project, tag=runtime.metadata.tag or "latest",
            versioned=True)
        runtime.metadata.hash = hash_key
        run.spec.function = runtime.uri

    def _submit_job(self, runtime, run: RunObject, db, schedule,
                    watch: bool) -> RunObject:
        body = run.to_dict()
        body["task"] = {"spec": body.get("spec", {}),
                        "metadata": body.get("metadata", {})}
        body["function"] = runtime.to_dict()
        if schedule:
            body["schedule"] = schedule
        resp = db.submit_job(body, schedule=schedule)
        if schedule:
            logger.info("task scheduled", schedule=schedule)
            run.status.state = "scheduled"
            return run
        uid = resp.get("data", resp).get("metadata", {}).get("uid") or \
            run.metadata.uid
        run.metadata.uid = uid
        run._db = db
        if watch:
            state, _ = db.watch_log(uid, run.metadata.project, watch=True)
            run.refresh()
            self._push_notifications(run)
            if run.status.state == RunStates.error:
                raise RuntimeError(
                    f"run {run.metadata.name} failed: {run.status.error}")
        else:
            run.refresh()
        return run
