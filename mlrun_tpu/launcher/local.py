"""Client-side local launcher (reference analog: mlrun/launcher/local.py:44
ClientLocalLauncher.launch, :133 _execute)."""

from __future__ import annotations

import socket

from ..common.runtimes_constants import RunStates
from ..execution import MLClientCtx
from ..model import RunObject
from ..utils import logger
from .base import BaseLauncher


class ClientLocalLauncher(BaseLauncher):
    """Runs a task in-process through the runtime's ``_run``."""

    def __init__(self, local: bool = True):
        self._is_local = local

    def launch(self, runtime, task: RunObject, schedule=None, watch=True,
               auto_build=False, **kwargs) -> RunObject:
        if schedule:
            raise ValueError(
                "schedules require the remote service (set MLT_DBPATH)")
        self.enrich_runtime(runtime)
        run = self._enrich_run(runtime, task)
        self._validate_run(run)

        # local=True forces in-process execution of any kind's handler;
        # otherwise client-driven kinds (dask/spark/databricks) keep their
        # own _run, which talks to their execution substrate directly
        if runtime.kind not in ("local", "handler", "") and self._is_local:
            runtime = self._convert_to_local(runtime)

        execution = MLClientCtx.from_dict(
            run.to_dict(), host=socket.gethostname())
        runtime._pre_run(run, execution)
        try:
            if run.spec.is_hyper_job():
                result = self._run_with_hyperparams(runtime, run, execution)
            else:
                result = runtime._run(run, execution)
        except Exception as exc:  # noqa: BLE001 - surface on the run object
            execution.set_state(error=str(exc))
            result = execution.to_dict()
        runtime._post_run(result, execution)
        run = self._log_track_results(runtime, result, run)
        self._push_notifications(run)
        return run

    @staticmethod
    def _convert_to_local(runtime):
        """Clone a remote-kind function into a LocalRuntime that executes the
        same code in-process (reference local.py run local flow)."""
        from ..runtimes.local import LocalRuntime

        local = LocalRuntime.from_dict(runtime.to_dict())
        local.kind = "local"
        local.metadata = runtime.metadata
        local.spec = runtime.spec
        local._handler = getattr(runtime, "_handler", None)
        return local
