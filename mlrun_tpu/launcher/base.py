"""Launcher base (reference analog: mlrun/launcher/base.py:35 BaseLauncher,
:225 run enrichment). A launcher is the strategy object that takes a
(runtime, task) pair and executes it — locally in-process, or remotely via the
service. The hyper-param fan-out lives here so every execution path shares it.
"""

from __future__ import annotations

import socket
from abc import ABC, abstractmethod
from typing import Optional

from ..common.runtimes_constants import RunStates
from ..config import mlconf
from ..execution import MLClientCtx
from ..model import RunObject
from ..runtimes.generators import get_generator, select_best_iteration
from ..utils import generate_uid, get_in, logger, now_iso, template_artifact_path


class BaseLauncher(ABC):
    @abstractmethod
    def launch(self, runtime, task: RunObject, schedule=None, watch=True,
               auto_build=False, **kwargs) -> RunObject:
        ...

    # -- enrichment --------------------------------------------------------
    def enrich_runtime(self, runtime, project_name: str = ""):
        runtime.metadata.project = (
            runtime.metadata.project or project_name or mlconf.default_project)
        runtime.metadata.name = runtime.metadata.name or "handler"

    def _enrich_run(self, runtime, run: RunObject) -> RunObject:
        run.metadata.uid = run.metadata.uid or generate_uid()
        run.metadata.project = (
            run.metadata.project or runtime.metadata.project
            or mlconf.default_project)
        run.spec.function = runtime.uri
        if not run.spec.output_path:
            run.spec.output_path = mlconf.resolve_artifact_path(
                run.metadata.project)
        run.spec.output_path = template_artifact_path(
            run.spec.output_path, run.metadata.project, run.metadata.uid)
        handler = run.spec.handler
        if handler and not callable(handler):
            run.spec.handler = str(handler)
        if not run.spec.handler and runtime.spec.default_handler:
            run.spec.handler = runtime.spec.default_handler
        return run

    @staticmethod
    def _validate_run(run: RunObject):
        if run.spec.hyperparams and run.spec.hyper_param_options and \
                run.spec.hyper_param_options.strategy == "list":
            lengths = {len(v) for v in run.spec.hyperparams.values()}
            if len(lengths) > 1:
                raise ValueError("list hyper-param strategy requires equal lists")

    # -- hyper-param orchestration ----------------------------------------
    def _run_with_hyperparams(self, runtime, run: RunObject,
                              execution: MLClientCtx) -> dict:
        """Fan out iterations, collect a summary, select + link the best
        (reference: BaseRuntime._run_many runtimes/base.py:508)."""
        if run.spec.hyper_param_options and \
                run.spec.hyper_param_options.param_file:
            from ..runtimes.generators import load_params_file

            loaded = load_params_file(run)
            merged = dict(run.spec.hyperparams or {})
            merged.update(loaded)
            run.spec.hyperparams = merged
        generator = get_generator(run.spec, execution)
        iteration_results = []
        errors = 0

        def run_one(task):
            child_ctx = MLClientCtx.from_dict(
                task.to_dict(), rundb=execution._db,
                host=socket.gethostname())
            try:
                result = runtime._run(task, child_ctx)
            except Exception as exc:  # noqa: BLE001 - iteration failure tolerated
                child_ctx.set_state(error=str(exc))
                result = child_ctx.to_dict()
            return task, result

        def record(task, result) -> bool:
            """Append an iteration row; True → abort the sweep."""
            nonlocal errors
            state = get_in(result, "status.state")
            results = get_in(result, "status.results", {}) or {}
            iteration_results.append({
                "iter": task.metadata.iteration,
                "state": state,
                "results": results,
                "parameters": task.spec.parameters,
            })
            if state == RunStates.error:
                errors += 1
                if errors >= generator.max_errors:
                    execution.set_state(
                        error=f"{errors} iterations failed — aborting sweep")
                    return True
            if generator.eval_stop_condition(results):
                logger.info("stop condition met",
                            iteration=task.metadata.iteration)
                return True
            return False

        if generator.use_parallel():
            # N iterations as concurrent resources with a max-parallel cap
            # (reference parallelizes via dask/process pools,
            # mlrun/runtimes/local.py:74); early stop cancels queued
            # iterations instead of draining them
            from concurrent.futures import ThreadPoolExecutor, as_completed

            workers = int(generator.options.parallel_runs)
            pool = ThreadPoolExecutor(max_workers=workers)
            try:
                futures = [pool.submit(run_one, task)
                           for task in generator.generate(run)]
                for future in as_completed(futures):
                    if record(*future.result()):
                        break
            finally:
                pool.shutdown(wait=True, cancel_futures=True)
        else:
            for task in generator.generate(run):
                if record(*run_one(task)):
                    break

        selector = (run.spec.hyper_param_options.selector
                    if run.spec.hyper_param_options else None)
        best = select_best_iteration(iteration_results, selector or "")
        if best:
            best_row = next(
                r for r in iteration_results if r["iter"] == best)
            execution.log_results(best_row["results"])
            for key in (get_in(best_row, "results", {}) or {}):
                pass
            # link parent artifacts to the best child iteration
            execution._artifacts_manager.link_artifact(
                execution._producer(), "best_iteration", best)
        execution.log_iteration_results(best, iteration_results, run.to_dict())
        execution.commit(completed=errors < generator.max_errors)
        return execution.to_dict()

    # -- notifications -----------------------------------------------------
    @staticmethod
    def _push_notifications(run: RunObject):
        notifications = run.spec.notifications or []
        if not notifications:
            return
        from ..utils.notifications import NotificationPusher

        try:
            run_dict = run.to_dict()
            NotificationPusher([run_dict]).push()
            # persist sent/error statuses so the server-side monitor does
            # not push the same notifications again on resource retirement
            specs = run_dict.get("spec", {}).get("notifications")
            from ..db import get_run_db

            get_run_db().update_run(
                {"spec.notifications": specs},
                run.metadata.uid, run.metadata.project)
        except Exception as exc:  # noqa: BLE001
            logger.warning("notification push failed", error=str(exc))

    @staticmethod
    def _log_track_results(runtime, result: dict, run: RunObject) -> RunObject:
        run.status = run.status.__class__.from_dict(
            result.get("status", {}))
        state = run.status.state
        if state == RunStates.completed:
            logger.info("run completed", name=run.metadata.name,
                        uid=run.metadata.uid, results=run.status.results)
        elif state == RunStates.error:
            logger.error("run failed", name=run.metadata.name,
                         uid=run.metadata.uid, error=run.status.error)
        return run
