"""Launcher DI factory (reference analog: mlrun/launcher/factory.py:24)."""

from __future__ import annotations

from ..config import mlconf
from .base import BaseLauncher
from .local import ClientLocalLauncher
from .remote import ClientRemoteLauncher


class LauncherFactory:
    _server_side_cls = None  # the service injects ServerSideLauncher here

    @classmethod
    def set_server_side(cls, launcher_cls):
        cls._server_side_cls = launcher_cls

    @classmethod
    def create_launcher(cls, is_remote: bool = False, local: bool = False,
                        is_api: bool = False, **kwargs) -> BaseLauncher:
        if is_api and cls._server_side_cls is not None:
            return cls._server_side_cls(**kwargs)
        if local:
            return ClientLocalLauncher(local=True)
        if is_remote:
            if not mlconf.is_remote:
                raise RuntimeError(
                    "remote runtime kinds need the service — set MLT_DBPATH "
                    "to the api url, or pass local=True to run in-process")
            return ClientRemoteLauncher()
        return ClientLocalLauncher(local=False)
