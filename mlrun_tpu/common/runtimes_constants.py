"""Runtime/run-state constants (reference analog: mlrun/common/runtimes/constants.py).

The reference's MPIJob CRD constants are replaced by TPU JobSet constants.
"""

from __future__ import annotations


# checkpoint-resume env contract for resubmitted runs: the service monitor
# writes these into the replacement resource (service/runtime_handlers.py)
# and training/checkpoint.py resume_directive reads them — one definition
# so the two sides cannot drift
RESUME_CHECKPOINT_ENV = "MLT_RESUME_FROM_CHECKPOINT"
RESUME_STEP_ENV = "MLT_RESUME_STEP"

# persistent XLA compilation-cache dir, threaded into (re)submitted TPU
# JobSets by TpuJobHandler. The name is the mlconf env mapping for
# ``mlconf.training.compile_cache_dir`` on purpose: the in-pod trainer
# picks it up through the ordinary config layer
# (utils/compile_cache.configure_from_mlconf) with no extra plumbing.
COMPILE_CACHE_ENV = "MLT_TRAINING__COMPILE_CACHE_DIR"


class RunStates:
    created = "created"
    pending = "pending"
    running = "running"
    completed = "completed"
    error = "error"
    aborting = "aborting"
    aborted = "aborted"
    skipped = "skipped"
    unknown = "unknown"

    @staticmethod
    def all() -> list[str]:
        return [
            RunStates.created, RunStates.pending, RunStates.running,
            RunStates.completed, RunStates.error, RunStates.aborting,
            RunStates.aborted, RunStates.skipped, RunStates.unknown,
        ]

    @staticmethod
    def terminal_states() -> list[str]:
        return [RunStates.completed, RunStates.error, RunStates.aborted,
                RunStates.skipped]

    @staticmethod
    def error_states() -> list[str]:
        return [RunStates.error, RunStates.aborted]

    @staticmethod
    def abortable_states() -> list[str]:
        return [RunStates.created, RunStates.pending, RunStates.running,
                RunStates.unknown]


class RuntimeKinds:
    local = "local"
    handler = "handler"
    job = "job"
    tpujob = "tpujob"
    dask = "dask"
    spark = "spark"
    databricks = "databricks"
    serving = "serving"
    remote = "remote"  # generic http-triggered function (nuclio analog)
    application = "application"

    @staticmethod
    def all() -> list[str]:
        return [
            RuntimeKinds.local, RuntimeKinds.handler, RuntimeKinds.job,
            RuntimeKinds.tpujob, RuntimeKinds.dask, RuntimeKinds.spark,
            RuntimeKinds.databricks, RuntimeKinds.serving,
            RuntimeKinds.remote, RuntimeKinds.application,
        ]

    @staticmethod
    def handled_kinds() -> list[str]:
        """Kinds with a server-side runtime handler (resource recovery)."""
        return [RuntimeKinds.job, RuntimeKinds.tpujob, RuntimeKinds.dask,
                RuntimeKinds.spark]

    @staticmethod
    def remote_kinds() -> list[str]:
        return [RuntimeKinds.job, RuntimeKinds.tpujob, RuntimeKinds.dask,
                RuntimeKinds.spark, RuntimeKinds.serving,
                RuntimeKinds.remote, RuntimeKinds.application]

    @staticmethod
    def pod_creating_kinds() -> list[str]:
        return [RuntimeKinds.job, RuntimeKinds.tpujob, RuntimeKinds.dask]


class PodPhases:
    pending = "Pending"
    running = "Running"
    succeeded = "Succeeded"
    failed = "Failed"
    unknown = "Unknown"

    @staticmethod
    def to_run_state(phase: str) -> str:
        return {
            PodPhases.pending: RunStates.pending,
            PodPhases.running: RunStates.running,
            PodPhases.succeeded: RunStates.completed,
            PodPhases.failed: RunStates.error,
        }.get(phase, RunStates.unknown)


class JobSetConditions:
    """GKE JobSet condition types the tpujob handler reconciles
    (replacing the reference's MPIJob CRD condition mapping,
    server/api/runtime_handlers/mpijob/v1.py:244-287)."""

    startup_policy_completed = "StartupPolicyCompleted"
    completed = "Completed"
    failed = "Failed"
    suspended = "Suspended"

    @staticmethod
    def to_run_state(conditions: list[dict]) -> str:
        by_type = {
            c.get("type"): c for c in conditions or []
            if c.get("status") in (True, "True")
        }
        if JobSetConditions.completed in by_type:
            return RunStates.completed
        if JobSetConditions.failed in by_type:
            return RunStates.error
        if JobSetConditions.suspended in by_type:
            return RunStates.pending
        return RunStates.running


class ThresholdStates:
    pending_scheduled = "pending_scheduled"
    pending_not_scheduled = "pending_not_scheduled"
    image_pull_backoff = "image_pull_backoff"
    executing = "executing"
