"""Project API schemas (reference analog: mlrun/common/schemas/project.py)."""

from __future__ import annotations

import enum
from typing import Optional

import pydantic


class ProjectState(str, enum.Enum):
    unknown = "unknown"
    creating = "creating"
    online = "online"
    offline = "offline"
    archived = "archived"
    deleting = "deleting"


class ProjectRecord(pydantic.BaseModel):
    kind: str = "project"
    metadata: dict = pydantic.Field(default_factory=dict)
    spec: dict = pydantic.Field(default_factory=dict)
    status: dict = pydantic.Field(default_factory=dict)

    model_config = pydantic.ConfigDict(extra="allow")


class ProjectOut(pydantic.BaseModel):
    name: str
    state: ProjectState = ProjectState.online
    description: Optional[str] = None
