"""Notification schemas (reference analog:
mlrun/common/schemas/notification.py)."""

from __future__ import annotations

import enum
from typing import Optional

import pydantic


class NotificationKind(str, enum.Enum):
    console = "console"
    slack = "slack"
    webhook = "webhook"
    mail = "mail"


class NotificationSeverity(str, enum.Enum):
    info = "info"
    warning = "warning"
    error = "error"


class NotificationStatus(str, enum.Enum):
    pending = "pending"
    sent = "sent"
    error = "error"


class Notification(pydantic.BaseModel):
    kind: NotificationKind = NotificationKind.console
    name: str = ""
    message: str = ""
    severity: NotificationSeverity = NotificationSeverity.info
    when: list[str] = ["completed", "error"]
    condition: str = ""
    # either inline params or a {"secret": <key>} reference after
    # server-side masking
    params: dict = {}
    status: Optional[NotificationStatus] = None
    sent_time: Optional[str] = None
