"""Runtime resource schemas (reference analog:
mlrun/common/schemas/runtime_resource.py)."""

from __future__ import annotations

from typing import Optional

import pydantic


class RuntimeResource(pydantic.BaseModel):
    """One tracked execution resource (pod / JobSet / local process) —
    the durable row behind service restart recovery."""

    project: str
    uid: str
    kind: Optional[str] = None
    resource_id: Optional[str] = None
    started: Optional[float] = None


class RuntimeResourcesOutput(pydantic.BaseModel):
    resources: list[RuntimeResource] = []
