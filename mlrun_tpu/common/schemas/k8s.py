"""Kubernetes resource schemas (reference analog:
mlrun/common/schemas/k8s.py — reduced to the TPU JobSet/pod surface)."""

from __future__ import annotations

from typing import Optional

import pydantic


class Resources(pydantic.BaseModel):
    """Container resources; the accelerator resource is google.com/tpu
    (replacing nvidia.com/gpu)."""

    cpu: Optional[str] = None
    memory: Optional[str] = None
    tpu: Optional[int] = None

    def to_k8s(self) -> dict:
        out: dict = {}
        if self.cpu:
            out["cpu"] = self.cpu
        if self.memory:
            out["memory"] = self.memory
        if self.tpu:
            out["google.com/tpu"] = self.tpu
        return out


class NodeSelector(pydantic.BaseModel):
    """TPU pod-slice placement (accelerator type + topology)."""

    accelerator: Optional[str] = None  # e.g. tpu-v5-lite-podslice
    topology: Optional[str] = None     # e.g. 4x4

    def to_k8s(self) -> dict:
        out = {}
        if self.accelerator:
            out["cloud.google.com/gke-tpu-accelerator"] = self.accelerator
        if self.topology:
            out["cloud.google.com/gke-tpu-topology"] = self.topology
        return out
