"""Secret schemas (reference analog: mlrun/common/schemas/secret.py)."""

from __future__ import annotations

import enum
from typing import Optional

import pydantic


class SecretProviderName(str, enum.Enum):
    kubernetes = "kubernetes"
    vault = "vault"


class SecretsData(pydantic.BaseModel):
    provider: SecretProviderName = SecretProviderName.kubernetes
    secrets: dict[str, str] = {}


class SecretKeysData(pydantic.BaseModel):
    provider: SecretProviderName = SecretProviderName.kubernetes
    secret_keys: list[str] = []


class AuthSecretData(pydantic.BaseModel):
    provider: SecretProviderName = SecretProviderName.kubernetes
    username: Optional[str] = None
    access_key: Optional[str] = None
