"""Pagination schemas (reference analog: the pagination_cache model in
server/api/db/sqldb/models.py + paginated responses)."""

from __future__ import annotations

from typing import Optional

import pydantic


class PaginationInfo(pydantic.BaseModel):
    page_token: Optional[str] = None
    page_size: Optional[int] = None


class PaginatedResponse(pydantic.BaseModel):
    items: list = []
    pagination: PaginationInfo = PaginationInfo()
