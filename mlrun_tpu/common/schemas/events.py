"""Event schemas (reference analog: mlrun/common/schemas/events.py +
alert trigger event kinds)."""

from __future__ import annotations

import enum
from typing import Optional

import pydantic


class EventKind(str, enum.Enum):
    run_failed = "run-failed"
    run_completed = "run-completed"
    drift_detected = "drift-detected"
    drift_suspected = "drift-suspected"
    endpoint_failed = "endpoint-failed"
    custom = "custom"


class Event(pydantic.BaseModel):
    kind: EventKind = EventKind.custom
    project: Optional[str] = None
    entity: Optional[str] = None
    value: Optional[float] = None
    created: Optional[str] = None
    body: dict = {}
