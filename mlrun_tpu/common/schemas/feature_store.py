"""Feature-store schemas (reference analog:
mlrun/common/schemas/feature_store.py)."""

from __future__ import annotations

from typing import Optional

import pydantic

from .base import ObjectMetadata


class Entity(pydantic.BaseModel):
    name: str
    value_type: Optional[str] = None
    labels: dict = {}


class Feature(pydantic.BaseModel):
    name: str
    value_type: Optional[str] = None
    labels: dict = {}


class FeatureSetSpec(pydantic.BaseModel):
    entities: list[Entity] = []
    features: list[Feature] = []
    engine: str = "pandas"
    timestamp_key: Optional[str] = None
    targets: list = []


class FeatureSetRecord(pydantic.BaseModel):
    metadata: ObjectMetadata
    spec: FeatureSetSpec = FeatureSetSpec()
    status: dict = {}


class FeatureVectorSpec(pydantic.BaseModel):
    features: list[str] = []
    label_feature: Optional[str] = None
    with_indexes: bool = False


class FeatureVectorRecord(pydantic.BaseModel):
    metadata: ObjectMetadata
    spec: FeatureVectorSpec = FeatureVectorSpec()
    status: dict = {}
