"""Background task schemas (reference analog: mlrun/common/schemas/background_task.py)."""

from __future__ import annotations

import enum
from typing import Optional

import pydantic


class BackgroundTaskState(str, enum.Enum):
    created = "created"
    running = "running"
    succeeded = "succeeded"
    failed = "failed"

    @staticmethod
    def terminal_states():
        return [BackgroundTaskState.succeeded, BackgroundTaskState.failed]


class BackgroundTask(pydantic.BaseModel):
    name: str
    project: Optional[str] = None
    state: BackgroundTaskState = BackgroundTaskState.created
    created: Optional[str] = None
    updated: Optional[str] = None
    timeout: Optional[int] = None
    error: Optional[str] = None
