"""Model-endpoint schemas for monitoring
(reference analog: mlrun/common/schemas/model_monitoring/model_endpoints.py)."""

from __future__ import annotations

from typing import Optional

import pydantic


class ModelEndpoint(pydantic.BaseModel):
    uid: Optional[str] = None
    project: str = ""
    name: str = ""
    function_uri: str = ""
    model_uri: str = ""
    model_class: str = ""
    state: str = "ready"
    feature_names: list = pydantic.Field(default_factory=list)
    label_names: list = pydantic.Field(default_factory=list)
    metrics: dict = pydantic.Field(default_factory=dict)
    first_request: Optional[str] = None
    last_request: Optional[str] = None
    error_count: int = 0
    drift_status: str = ""

    model_config = pydantic.ConfigDict(extra="allow")
