"""Run API schemas (reference analog: mlrun/common/schemas/runs.py)."""

from __future__ import annotations

from typing import Optional

import pydantic


class RunIdentifier(pydantic.BaseModel):
    kind: str = "run"
    uid: Optional[str] = None
    iter: Optional[int] = None


class RunRecord(pydantic.BaseModel):
    kind: str = "run"
    metadata: dict = pydantic.Field(default_factory=dict)
    spec: dict = pydantic.Field(default_factory=dict)
    status: dict = pydantic.Field(default_factory=dict)

    model_config = pydantic.ConfigDict(extra="allow")
