"""Run API schemas (reference analog: mlrun/common/schemas/runs.py)."""

from __future__ import annotations

from typing import Optional

import pydantic


class RunIdentifier(pydantic.BaseModel):
    kind: str = "run"
    uid: Optional[str] = None
    iter: Optional[int] = None


class RetryPolicy(pydantic.BaseModel):
    """Run-level fault-tolerance policy carried on ``spec.retry_policy``.

    The reference has nothing here — an MPIJob worker failure fails the
    run (SURVEY §5.3). On preemptible TPU pod-slices eviction is the
    common case, so runs declare how the service should respond: how many
    resubmissions, exponential backoff shape, which failure classes are
    worth retrying (see ``common/retry.py FailureClass``), and what to do
    with a heartbeat-silent (stalled) run. Service-side enforcement lives
    in ``service/runtime_handlers.py``.
    """

    max_retries: int = pydantic.Field(0, ge=0)
    backoff: float = pydantic.Field(5.0, ge=0)
    backoff_factor: float = pydantic.Field(2.0, ge=1.0)
    backoff_max: float = pydantic.Field(300.0, ge=0)
    jitter: float = pydantic.Field(0.1, ge=0, le=1.0)
    # failure classes to retry; empty/None = every retryable infra class
    retry_on: Optional[list[str]] = None
    # heartbeat-silence threshold in seconds; <= 0 disables the watchdog
    stall_timeout: float = -1.0
    on_stall: str = "abort"  # "abort" | "resubmit"

    # a typo'd key would otherwise silently disarm the policy (the raw
    # dict reaches resolve_retry_policy, which keeps known keys only)
    model_config = pydantic.ConfigDict(extra="forbid")

    @pydantic.field_validator("on_stall")
    @classmethod
    def _check_on_stall(cls, value: str) -> str:
        if value not in ("abort", "resubmit"):
            raise ValueError("on_stall must be 'abort' or 'resubmit'")
        return value

    @pydantic.field_validator("retry_on")
    @classmethod
    def _check_retry_on(cls, value):
        # a typo'd class name would otherwise silently disable retries —
        # the classifier's output would never match it
        if value is None:
            return value
        from ..retry import FailureClass

        unknown = set(value) - set(FailureClass.retryable())
        if unknown:
            raise ValueError(
                f"unknown retry_on failure classes {sorted(unknown)}; "
                f"valid: {FailureClass.retryable()}")
        return value


class RunRecord(pydantic.BaseModel):
    kind: str = "run"
    metadata: dict = pydantic.Field(default_factory=dict)
    spec: dict = pydantic.Field(default_factory=dict)
    status: dict = pydantic.Field(default_factory=dict)

    model_config = pydantic.ConfigDict(extra="allow")
