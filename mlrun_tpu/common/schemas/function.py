"""Function API schemas (reference analog: mlrun/common/schemas/function.py)."""

from __future__ import annotations

import enum

import pydantic


class FunctionState(str, enum.Enum):
    unknown = "unknown"
    ready = "ready"
    error = "error"
    deploying = "deploying"
    running = "running"
    pending = "pending"
    build = "build"


class FunctionRecord(pydantic.BaseModel):
    kind: str = ""
    metadata: dict = pydantic.Field(default_factory=dict)
    spec: dict = pydantic.Field(default_factory=dict)
    status: dict = pydantic.Field(default_factory=dict)

    model_config = pydantic.ConfigDict(extra="allow")
