"""Shared pydantic bases for the client/server API contract
(reference analog: mlrun/common/schemas/object.py)."""

from __future__ import annotations

from datetime import datetime
from typing import Optional

import pydantic


class ObjectMetadata(pydantic.BaseModel):
    name: str
    project: Optional[str] = None
    tag: Optional[str] = None
    uid: Optional[str] = None
    labels: dict = pydantic.Field(default_factory=dict)
    annotations: dict = pydantic.Field(default_factory=dict)
    created: Optional[datetime] = None
    updated: Optional[datetime] = None

    model_config = pydantic.ConfigDict(extra="allow")


class ObjectSpec(pydantic.BaseModel):
    model_config = pydantic.ConfigDict(extra="allow")


class ObjectStatus(pydantic.BaseModel):
    state: Optional[str] = None
    model_config = pydantic.ConfigDict(extra="allow")


class ObjectKind(pydantic.BaseModel):
    kind: str = ""
    metadata: ObjectMetadata
    spec: ObjectSpec = pydantic.Field(default_factory=ObjectSpec)
    status: ObjectStatus = pydantic.Field(default_factory=ObjectStatus)
