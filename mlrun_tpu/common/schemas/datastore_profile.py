"""Datastore profile schemas (reference analog:
mlrun/common/schemas/datastore_profile.py)."""

from __future__ import annotations

from typing import Optional

import pydantic


class DatastoreProfile(pydantic.BaseModel):
    """Public (non-secret) half of a profile; the private half rides the
    project secret store (datastore/profiles.py)."""

    name: str
    type: str = "basic"
    fields: dict = {}
    project: Optional[str] = None


class DatastoreProfileCreate(pydantic.BaseModel):
    profile: DatastoreProfile
    private: Optional[dict] = None
