"""Client-spec pushed from server to SDK on connect
(reference analog: mlrun/common/schemas/client_spec.py,
server/api/api/endpoints/client_spec.py)."""

from __future__ import annotations

from typing import Optional

import pydantic


class ClientSpec(pydantic.BaseModel):
    version: Optional[str] = None
    namespace: Optional[str] = None
    default_project: Optional[str] = None
    artifact_path: Optional[str] = None
    default_image: Optional[str] = None
    tpu_defaults: dict = pydantic.Field(default_factory=dict)
    config_overrides: dict = pydantic.Field(default_factory=dict)
