"""Schedule API schemas (reference analog: mlrun/common/schemas/schedule.py)."""

from __future__ import annotations

import enum
from typing import Optional

import pydantic


class ScheduleKinds(str, enum.Enum):
    job = "job"
    pipeline = "pipeline"


class ScheduleRecord(pydantic.BaseModel):
    name: str
    project: str
    kind: ScheduleKinds = ScheduleKinds.job
    cron_trigger: str  # standard 5-field cron
    scheduled_object: dict = pydantic.Field(default_factory=dict)
    labels: dict = pydantic.Field(default_factory=dict)
    creation_time: Optional[str] = None
    last_run_uri: Optional[str] = None
    next_run_time: Optional[str] = None
    concurrency_limit: int = 1

    model_config = pydantic.ConfigDict(extra="allow")
