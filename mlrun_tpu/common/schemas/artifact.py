"""Artifact API schemas (reference analog: mlrun/common/schemas/artifact.py)."""

from __future__ import annotations

import pydantic


class ArtifactRecord(pydantic.BaseModel):
    kind: str = "artifact"
    metadata: dict = pydantic.Field(default_factory=dict)
    spec: dict = pydantic.Field(default_factory=dict)
    status: dict = pydantic.Field(default_factory=dict)

    model_config = pydantic.ConfigDict(extra="allow")
