from .base import ObjectKind, ObjectMetadata, ObjectSpec, ObjectStatus  # noqa: F401
from .background_task import BackgroundTask, BackgroundTaskState  # noqa: F401
from .client_spec import ClientSpec  # noqa: F401
from .function import FunctionRecord, FunctionState  # noqa: F401
from .project import ProjectOut, ProjectRecord, ProjectState  # noqa: F401
from .run import RunIdentifier, RunRecord  # noqa: F401
from .schedule import ScheduleKinds, ScheduleRecord  # noqa: F401
from .artifact import ArtifactRecord  # noqa: F401
from .model_endpoint import ModelEndpoint  # noqa: F401
from .alert import AlertConfigRecord, AlertSeverity, AlertState  # noqa: F401
