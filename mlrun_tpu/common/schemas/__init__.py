from .base import ObjectKind, ObjectMetadata, ObjectSpec, ObjectStatus  # noqa: F401
from .background_task import BackgroundTask, BackgroundTaskState  # noqa: F401
from .client_spec import ClientSpec  # noqa: F401
from .function import FunctionRecord, FunctionState  # noqa: F401
from .project import ProjectOut, ProjectRecord, ProjectState  # noqa: F401
from .run import RetryPolicy, RunIdentifier, RunRecord  # noqa: F401
from .schedule import ScheduleKinds, ScheduleRecord  # noqa: F401
from .artifact import ArtifactRecord  # noqa: F401
from .model_endpoint import ModelEndpoint  # noqa: F401
from .alert import AlertConfigRecord, AlertSeverity, AlertState  # noqa: F401
from .datastore_profile import (  # noqa: F401
    DatastoreProfile,
    DatastoreProfileCreate,
)
from .events import Event, EventKind  # noqa: F401
from .feature_store import (  # noqa: F401
    Entity,
    Feature,
    FeatureSetRecord,
    FeatureSetSpec,
    FeatureVectorRecord,
    FeatureVectorSpec,
)
from .k8s import NodeSelector, Resources  # noqa: F401
from .notification import (  # noqa: F401
    Notification,
    NotificationKind,
    NotificationSeverity,
    NotificationStatus,
)
from .pagination import PaginatedResponse, PaginationInfo  # noqa: F401
from .runtime_resource import (  # noqa: F401
    RuntimeResource,
    RuntimeResourcesOutput,
)
from .secret import (  # noqa: F401
    AuthSecretData,
    SecretKeysData,
    SecretProviderName,
    SecretsData,
)
from .workflow import (  # noqa: F401
    WorkflowSpec,
    WorkflowState,
    WorkflowStatusOut,
)
