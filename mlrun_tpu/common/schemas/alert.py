"""Alert schemas (reference analog: mlrun/common/schemas/alert.py)."""

from __future__ import annotations

import enum
from typing import Optional

import pydantic


class AlertSeverity(str, enum.Enum):
    low = "low"
    medium = "medium"
    high = "high"


class AlertState(str, enum.Enum):
    inactive = "inactive"
    active = "active"


class AlertConfigRecord(pydantic.BaseModel):
    name: str
    project: str = ""
    summary: str = ""
    severity: AlertSeverity = AlertSeverity.medium
    entity_kind: str = "job"  # job | model-endpoint
    entity_id: str = "*"
    trigger_events: list = pydantic.Field(default_factory=list)
    criteria: dict = pydantic.Field(default_factory=dict)  # {count, period_seconds}
    notifications: list = pydantic.Field(default_factory=list)
    reset_policy: str = "auto"  # auto | manual
    state: AlertState = AlertState.inactive
    count: int = 0
    # silencing window: while now < silence_until (ISO timestamp) the alert
    # evaluates but does NOT fire or notify (maintenance windows, known
    # incidents). Cleared by writing an empty string.
    silence_until: str = ""

    model_config = pydantic.ConfigDict(extra="allow")
