"""Workflow schemas (reference analog: mlrun/common/schemas/workflow.py)."""

from __future__ import annotations

import enum
from typing import Optional

import pydantic


class WorkflowState(str, enum.Enum):
    running = "running"
    completed = "completed"
    error = "error"


class WorkflowSpec(pydantic.BaseModel):
    name: str = ""
    code: Optional[str] = None
    path: Optional[str] = None
    handler: Optional[str] = None
    engine: str = "local"
    arguments: dict = {}


class WorkflowStatusOut(pydantic.BaseModel):
    workflow_id: str
    state: WorkflowState = WorkflowState.running
    error: Optional[str] = None
