"""Run-level retry policy engine + failure classifier.

The reference has no retry policy at all — ``monitor_runs`` marks a run
failed and stops (SURVEY §5.3). On preemptible TPU pod-slices eviction is
the common case, so the service needs to answer three questions for every
failed resource: *was this the user's fault or the infrastructure's*,
*should we try again*, and *how long to wait*. This module answers all
three deterministically; the service-side resubmission itself lives in
``service/runtime_handlers.py``.
"""

from __future__ import annotations

import dataclasses
import random
import re

from ..config import mlconf


class FailureClass:
    """Coarse failure taxonomy recorded on ``status.failure_class``."""

    # retryable infra faults
    preemption = "preemption"                  # spot/preemptible eviction
    # ONE pod-slice of a multi-slice job evicted while the job itself is
    # alive — the elastic case: survivors reshard and keep training, the
    # monitor submits only a replacement slice (not a full resubmit).
    # Distinguished from ``preemption`` (whole job dead) by the provider's
    # slice_status probe / slice-scoped failure text.
    slice_preempted = "slice_preempted"
    image_pull_backoff = "image_pull_backoff"  # registry flake
    node_drain = "node_drain"                  # node shutdown / drain
    http_5xx = "http_5xx"                      # control-plane 5xx
    resource_vanished = "resource_vanished"    # GC'd / deleted out-of-band
    infra = "infra"                            # generic infra failure
    stalled = "stalled"                        # heartbeat-silent run
    # permanent
    user_code = "user_code"                    # handler raised / exit != 0

    @staticmethod
    def retryable() -> list[str]:
        return [
            FailureClass.preemption, FailureClass.slice_preempted,
            FailureClass.image_pull_backoff,
            FailureClass.node_drain, FailureClass.http_5xx,
            FailureClass.resource_vanished, FailureClass.infra,
            FailureClass.stalled,
        ]


# keyword → class, checked in order (first hit wins). Sources: GKE pod
# reasons (Evicted/Preempted/NodeShutdown), kubelet waiting reasons
# (ImagePullBackOff/ErrImagePull), and control-plane error text.
_PATTERNS: list[tuple[str, str]] = [
    # slice-scoped text must outrank the generic preemption pattern
    # ("slice 1 preempted" contains "preempt") — first hit wins
    (r"slice[\s_-]*\d*[\s_-]*(preempt|fail|evict)|slicefailed|failedslice",
     FailureClass.slice_preempted),
    (r"preempt|evict|spot|gke-spot", FailureClass.preemption),
    (r"imagepullbackoff|errimagepull|image\s*pull", FailureClass.image_pull_backoff),
    (r"node\s*drain|nodeshutdown|node\s*shutdown|unschedulable|"
     r"deletiontimestamp", FailureClass.node_drain),
    (r"\b50[0-9]\b|http\s*5xx|server\s+error|bad\s+gateway|"
     r"service\s+unavailable", FailureClass.http_5xx),
]


def classify_failure(probe_error: str | None = None,
                     run_error: str | None = None,
                     reason: str | None = None,
                     run_reported_terminal: bool = False) -> str:
    """Classify a failed/vanished resource.

    The load-bearing signal is ``run_reported_terminal``: the in-run
    process writes a terminal error state (with traceback) when *user
    code* raises, so a failed resource whose run doc already reached a
    terminal state is a permanent user-code failure. A resource that died
    while its run doc still says running/pending never got to report —
    that is infrastructure (preemption, OOM-kill of the node, GC), and it
    is retryable. Text patterns then refine the infra class.
    """
    if run_reported_terminal:
        return FailureClass.user_code
    text = " ".join(t for t in (probe_error, reason, run_error) if t).lower()
    for pattern, cls in _PATTERNS:
        if re.search(pattern, text):
            return cls
    if probe_error:
        # state probe itself failed → the resource is gone (404 after GC,
        # dead pid, deleted JobSet)
        return FailureClass.resource_vanished
    return FailureClass.infra


@dataclasses.dataclass
class RetryPolicy:
    """Resolved run-level retry/stall policy (spec overlaid on config
    defaults — see ``resolve_retry_policy``)."""

    max_retries: int = 0
    backoff: float = 5.0          # first-retry delay, seconds
    backoff_factor: float = 2.0   # exponential growth per attempt
    backoff_max: float = 300.0    # delay ceiling
    jitter: float = 0.1           # ± fraction of the delay
    retry_on: tuple = ()          # failure classes worth retrying
    stall_timeout: float = -1.0   # heartbeat-silence threshold; <=0 off
    on_stall: str = "abort"       # "abort" | "resubmit"

    def retries_left(self, retry_count: int) -> bool:
        return int(retry_count) < int(self.max_retries)


def resolve_retry_policy(spec: dict | None = None) -> RetryPolicy:
    """Overlay a run's ``spec.retry_policy`` dict on the service defaults
    (``mlconf.runs.retries`` + ``mlconf.runs.heartbeat``)."""
    defaults = _config_defaults()
    spec = dict(spec or {})
    fields = {f.name for f in dataclasses.fields(RetryPolicy)}
    merged = {k: v for k, v in {**defaults, **spec}.items()
              if k in fields and v is not None}
    if "retry_on" in merged:
        merged["retry_on"] = tuple(merged["retry_on"])
    policy = RetryPolicy(**merged)
    if not policy.retry_on:
        policy.retry_on = tuple(FailureClass.retryable())
    return policy


def _config_defaults() -> dict:
    out: dict = {}
    retries = getattr(mlconf.runs, "retries", None)
    if retries is not None and hasattr(retries, "to_dict"):
        out.update(retries.to_dict())
    heartbeat = getattr(mlconf.runs, "heartbeat", None)
    if heartbeat is not None and hasattr(heartbeat, "to_dict"):
        hb = heartbeat.to_dict()
        out.setdefault("stall_timeout", hb.get("stall_timeout"))
        out.setdefault("on_stall", hb.get("on_stall"))
    return out


def compute_backoff(attempt: int, policy: RetryPolicy, seed: str = "") -> float:
    """Exponential backoff with *deterministic* jitter: the jitter draw is
    keyed on (seed, attempt) so a given run's retry timeline is
    reproducible — chaos tests and postmortems see the same schedule.
    ``attempt`` is 0-based (0 → first retry)."""
    if policy.backoff <= 0:
        return 0.0
    delay = min(policy.backoff * (policy.backoff_factor ** attempt),
                policy.backoff_max)
    if policy.jitter > 0:
        rng = random.Random(f"{seed}:{attempt}")
        delay *= 1.0 + rng.uniform(-policy.jitter, policy.jitter)
    return max(0.0, delay)
