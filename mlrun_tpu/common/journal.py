"""Durable intent journal for the serving control plane.

The pod fleet, autoscaler, and continuous-tuning controller keep their
orchestration state (submitted JobSets, drain progress, live canary
splits, in-flight retrains) in process memory. A service restart would
orphan all of it. The :class:`IntentJournal` is the write-ahead record
that makes restart a non-event: every intent transition is appended as
one JSONL line *before* the side effect it describes, and a restarted
controller replays the journal, lists the observed world, and converges
the two level-triggered (docs/fault_tolerance.md "Control-plane crash
recovery").

Design constraints, in order:

1. **Never poison the control loop.** A journal write failure degrades
   (logged, counted in ``stats``) — it never raises into ``tick()``.
   Losing a journal line costs recovery fidelity after a *later* crash;
   raising costs availability *now*.
2. **Torn tails are expected.** A crash mid-write leaves a partial last
   line. ``replay()`` drops an unparseable final line silently (counted)
   and skips+logs corrupt lines mid-file; recovery always proceeds with
   whatever prefix is intact.
3. **Bounded size.** Appends are compacted away: ``compact()`` rewrites
   the file atomically (tmp + rename) from a snapshot of live records,
   and auto-compaction triggers via the ``snapshot`` callback once the
   append count since the last compaction crosses ``compact_threshold``.
4. **Deterministic fault injection.** Chaos point ``journal.write``
   fires per append with a mutable box — an action may truncate the
   serialized line (torn write on demand), an error models a failed
   write.

Off by default: ``open_journal()`` returns ``None`` unless
``mlconf.serving.fleet.journal_dir`` is set, and every caller treats a
``None`` journal as "journaling disabled" — zero behavior change.
"""

from __future__ import annotations

import io
import json
import os
import threading
from typing import Callable, Iterator, Optional

from ..chaos import FaultPoints, fire
from ..utils import logger


class IntentJournal:
    """Append-only JSONL intent journal with fsync batching, atomic
    compaction, and torn-tail-tolerant replay."""

    def __init__(self, path: str, *, fsync_every: int = 8,
                 compact_threshold: int = 256,
                 snapshot: Optional[Callable[[], list[dict]]] = None):
        self.path = path
        self.fsync_every = max(1, int(fsync_every))
        self.compact_threshold = max(1, int(compact_threshold))
        self._snapshot = snapshot
        self._lock = threading.Lock()
        self._fp: Optional[io.TextIOWrapper] = None
        self._since_fsync = 0
        self._since_compact = 0
        self.stats = {
            "appends": 0,
            "write_failures": 0,
            "torn_tail_dropped": 0,
            "corrupt_skipped": 0,
            "compactions": 0,
        }
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)

    # -- write path ----------------------------------------------------------
    def append(self, kind: str, **fields) -> bool:
        """Append one intent record. Returns False (and degrades) on any
        failure — callers in control loops must not need a try/except."""
        record = {"kind": kind}
        record.update(fields)
        try:
            line = json.dumps(record, sort_keys=True,
                              separators=(",", ":")) + "\n"
        except (TypeError, ValueError) as exc:
            logger.warning("journal record not serializable",
                           path=self.path, kind=kind, error=str(exc))
            self.stats["write_failures"] += 1
            return False
        box = {"line": line, "kind": kind}
        with self._lock:
            try:
                # an action() may truncate box["line"] (torn write), an
                # error models the write itself failing
                fire(FaultPoints.journal_write, box=box, path=self.path)
                fp = self._open_locked()
                fp.write(box["line"])
                fp.flush()
                self._since_fsync += 1
                if self._since_fsync >= self.fsync_every:
                    os.fsync(fp.fileno())
                    self._since_fsync = 0
            except Exception as exc:  # noqa: BLE001 - degrade, never
                # raise into the control loop (design constraint 1)
                logger.warning("journal append failed",
                               path=self.path, kind=kind, error=str(exc))
                self.stats["write_failures"] += 1
                self._reset_fp_locked()
                return False
            self.stats["appends"] += 1
            self._since_compact += 1
            auto = (self._snapshot is not None
                    and self._since_compact >= self.compact_threshold)
        if auto:
            self.compact(self._snapshot())
        return True

    def _open_locked(self) -> io.TextIOWrapper:
        if self._fp is None or self._fp.closed:
            # heal a torn tail before appending: a crash mid-write can
            # leave the file without a trailing newline, and appending
            # straight after it would weld the new record onto the torn
            # fragment — losing BOTH lines at the next replay
            try:
                with open(self.path, "rb") as fp:
                    fp.seek(-1, os.SEEK_END)
                    torn = fp.read(1) != b"\n"
            except (OSError, ValueError):
                torn = False  # missing/empty file: nothing to heal
            self._fp = open(self.path, "a", encoding="utf-8")
            if torn:
                self._fp.write("\n")
        return self._fp

    def _reset_fp_locked(self) -> None:
        if self._fp is not None:
            try:
                self._fp.close()
            except Exception:  # noqa: BLE001 - already degraded
                pass
            self._fp = None

    # -- read path -----------------------------------------------------------
    def replay(self) -> list[dict]:
        """All intact records, in append order. A partial final line
        (torn tail) is dropped; corrupt mid-file lines are skipped and
        logged — recovery proceeds with the intact prefix."""
        return list(self._iter_records())

    def _iter_records(self) -> Iterator[dict]:
        try:
            with open(self.path, encoding="utf-8") as fp:
                lines = fp.readlines()
        except FileNotFoundError:
            return
        except OSError as exc:
            logger.warning("journal unreadable — recovering cold",
                           path=self.path, error=str(exc))
            return
        last = len(lines) - 1
        for idx, line in enumerate(lines):
            stripped = line.strip()
            if not stripped:
                continue
            try:
                record = json.loads(stripped)
                if not isinstance(record, dict):
                    raise ValueError("record is not an object")
            except (ValueError, TypeError):
                if idx == last:
                    # torn tail: the crash interrupted the final write —
                    # expected, silent by design (constraint 2)
                    self.stats["torn_tail_dropped"] += 1
                else:
                    logger.warning("journal line corrupt — skipped",
                                   path=self.path, line_no=idx + 1)
                    self.stats["corrupt_skipped"] += 1
                continue
            yield record

    # -- compaction ----------------------------------------------------------
    def compact(self, records: list[dict]) -> None:
        """Atomically rewrite the journal to exactly ``records`` (each a
        dict with a ``kind`` key): tmp-write + fsync + rename, so a crash
        mid-compaction leaves either the old or the new file, never a
        mix."""
        tmp = self.path + ".tmp"
        with self._lock:
            self._reset_fp_locked()
            try:
                with open(tmp, "w", encoding="utf-8") as fp:
                    for record in records:
                        fp.write(json.dumps(record, sort_keys=True,
                                            separators=(",", ":")) + "\n")
                    fp.flush()
                    os.fsync(fp.fileno())
                os.replace(tmp, self.path)
            except Exception as exc:  # noqa: BLE001 - degrade (1): the
                # un-compacted journal is still valid
                logger.warning("journal compaction failed",
                               path=self.path, error=str(exc))
                self.stats["write_failures"] += 1
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                return
            self.stats["compactions"] += 1
            self._since_compact = 0

    def close(self) -> None:
        with self._lock:
            if self._fp is not None and not self._fp.closed:
                try:
                    os.fsync(self._fp.fileno())
                except (OSError, ValueError):
                    pass
            self._reset_fp_locked()


def open_journal(name: str, *,
                 snapshot: Optional[Callable[[], list[dict]]] = None,
                 ) -> Optional[IntentJournal]:
    """Journal ``<journal_dir>/<name>.jsonl``, or ``None`` when
    ``mlconf.serving.fleet.journal_dir`` is unset (journaling off — the
    default; every caller treats None as disabled)."""
    from ..config import mlconf

    journal_dir = str(getattr(mlconf.serving.fleet, "journal_dir", "")
                      or "").strip()
    if not journal_dir:
        return None
    return IntentJournal(os.path.join(journal_dir, f"{name}.jsonl"),
                         snapshot=snapshot)
