"""Run helpers (reference analog: mlrun/run.py — get_or_create_ctx :198,
new_function :425, code_to_function :581, import_function :330)."""

from __future__ import annotations

import base64
import inspect
import json
import os
import socket
from typing import Callable, Optional, Union

from .common.runtimes_constants import RuntimeKinds
from .config import mlconf
from .execution import MLClientCtx
from .model import RunObject, RunTemplate
from .runtimes import get_runtime_class
from .runtimes.base import BaseRuntime
from .utils import logger, normalize_name, update_in


def get_or_create_ctx(name: str, uid: str = "", event=None, spec=None,
                      with_env: bool = True, rundb=None, project: str = "",
                      upload_artifacts: bool = False) -> MLClientCtx:
    """Entry point inside user scripts: returns the active context if running
    under the framework, or creates a fresh one (reference run.py:198)."""
    newspec = {}
    config = os.environ.get(mlconf.exec_config_env) if with_env else None
    if spec:
        newspec = spec.to_dict() if hasattr(spec, "to_dict") else dict(spec)
    elif config:
        newspec = json.loads(config)
    update_in(newspec, "metadata.name", name, replace=False)
    if uid:
        update_in(newspec, "metadata.uid", uid)
    if project:
        update_in(newspec, "metadata.project", project)
    if not newspec.get("spec", {}).get("output_path"):
        update_in(newspec, "spec.output_path",
                  mlconf.resolve_artifact_path(
                      newspec.get("metadata", {}).get("project", "")))
    ctx = MLClientCtx.from_dict(newspec, rundb=rundb,
                                host=socket.gethostname(),
                                autocommit=bool(config))
    return ctx


def new_function(name: str = "", project: str = "", tag: str = "",
                 kind: str = "", command: str = "", image: str = "",
                 args: list | None = None, mode: str = "",
                 handler: Callable | None = None, source: str = "",
                 requirements: list | None = None,
                 kfp: bool | None = None) -> BaseRuntime:
    """Create a runtime object of the given kind (reference run.py:425)."""
    kind = kind or RuntimeKinds.local
    runtime = get_runtime_class(kind)()
    runtime.kind = kind
    name = name or (handler.__name__ if handler else "") or \
        (os.path.splitext(os.path.basename(command))[0] if command else "handler")
    runtime.metadata.name = normalize_name(name)
    runtime.metadata.project = project or mlconf.default_project
    runtime.metadata.tag = tag or "latest"
    runtime.spec.command = command
    runtime.spec.image = image
    runtime.spec.args = args or []
    runtime.spec.mode = mode
    if handler is not None:
        if callable(handler):
            runtime.spec.default_handler = handler.__name__
            # kept for in-process execution (local kinds and local=True
            # conversions of remote kinds)
            runtime._handler = handler
        else:
            runtime.spec.default_handler = handler
    if source:
        runtime.spec.build.source = source
    if requirements:
        runtime.with_requirements(requirements)
    return runtime


def code_to_function(name: str = "", project: str = "", tag: str = "",
                     filename: str = "", handler: str = "", kind: str = "",
                     image: str = "", code_output: str = "",
                     embed_code: bool = True, description: str = "",
                     requirements: list | None = None,
                     categories: list | None = None, labels: dict | None = None,
                     with_doc: bool = True,
                     ignored_tags=None) -> BaseRuntime:
    """Turn a python file / notebook / function object into a runtime with
    embedded code (reference run.py:581)."""
    filename = filename or _calling_filename()
    if not filename or not os.path.isfile(filename):
        raise ValueError(
            f"cannot embed code: file '{filename}' not found "
            "(pass filename= explicitly)")
    with open(filename) as fp:
        source_code = fp.read()

    kind = kind or RuntimeKinds.job
    runtime = new_function(name=name or os.path.splitext(
        os.path.basename(filename))[0], project=project, tag=tag, kind=kind,
        image=image)
    if embed_code:
        runtime.spec.build.with_source(source_code)
        runtime.spec.build.origin_filename = filename
        runtime.spec.build.code_origin = filename
    else:
        runtime.spec.command = filename
    runtime.spec.default_handler = handler
    runtime.spec.description = description
    if requirements:
        runtime.with_requirements(requirements)
    if labels:
        for key, value in labels.items():
            runtime.set_label(key, value)
    runtime.metadata.categories = categories or []
    if with_doc:
        runtime.spec.entry_points = _extract_entry_points(source_code)
    return runtime


def import_function(url: str = "", project: str = "", new_name: str = "",
                    secrets: dict | None = None) -> BaseRuntime:
    """Load a function object from yaml/json/db/hub
    (reference run.py:330)."""
    if url.startswith("db://"):
        body = url[len("db://"):]
        project_part, _, name_part = body.partition("/")
        tag = ""
        if ":" in name_part:
            name_part, tag = name_part.split(":", 1)
        from .db import get_run_db

        struct = get_run_db().get_function(name_part, project_part, tag)
    elif url.startswith("hub://"):
        from .hub import get_hub_function

        struct = get_hub_function(url)
    else:
        from .datastore import store_manager

        item = store_manager.object(url=url, secrets=secrets)
        text = item.get(encoding="utf-8")
        import yaml

        struct = yaml.safe_load(text)
    kind = struct.get("kind", RuntimeKinds.job)
    runtime = get_runtime_class(kind).from_dict(struct)
    runtime.kind = kind
    if new_name:
        runtime.metadata.name = normalize_name(new_name)
    if project:
        runtime.metadata.project = project
    return runtime


def function_to_module(code: str = "", workdir: str = "", secrets=None):
    """Import a function file as a module (reference run.py function_to_module)."""
    import importlib.util
    import sys

    path = os.path.join(workdir or "", code)
    module_name = os.path.splitext(os.path.basename(path))[0]
    spec = importlib.util.spec_from_file_location(module_name, path)
    module = importlib.util.module_from_spec(spec)
    sys.modules[module_name] = module
    spec.loader.exec_module(module)
    return module


def run_local(task=None, command: str = "", name: str = "",
              handler: Callable | None = None, params: dict | None = None,
              inputs: dict | None = None, artifact_path: str = "",
              project: str = "") -> RunObject:
    """One-shot local run helper (reference run.py run_local)."""
    fn = new_function(name=name, project=project, kind=RuntimeKinds.local,
                      command=command, handler=handler)
    return fn.run(task, handler=handler, name=name, params=params,
                  inputs=inputs, artifact_path=artifact_path, local=True)


def wait_for_pipeline_completion(run_id, timeout: float = 3600,
                                 expected_statuses: list | None = None,
                                 project: str = ""):
    """Wait for a workflow run to finish (reference run.py:909)."""
    from .projects.pipelines import wait_for_run_completion

    return wait_for_run_completion(run_id, timeout=timeout, project=project,
                                   expected_statuses=expected_statuses)


def _calling_filename() -> str:
    for frame in inspect.stack()[2:]:
        fname = frame.filename
        if "mlrun_tpu" not in fname and not fname.startswith("<"):
            return fname
    return ""


def _extract_entry_points(source_code: str) -> dict:
    """Parse top-level defs with docstrings for fn.doc()
    (reference funcdoc analog)."""
    import ast

    out = {}
    try:
        tree = ast.parse(source_code)
    except SyntaxError:
        return out
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            params = []
            for arg in node.args.args:
                annotation = ""
                if arg.annotation is not None:
                    annotation = ast.unparse(arg.annotation)
                params.append({"name": arg.arg, "type": annotation})
            out[node.name] = {
                "name": node.name,
                "doc": ast.get_docstring(node) or "",
                "parameters": params,
            }
    return out
