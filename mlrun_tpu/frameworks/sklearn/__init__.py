"""sklearn auto-logger (reference analog: mlrun/frameworks/sklearn/ —
``apply_mlrun`` patches fit/predict to log params/metrics/model).
"""

from __future__ import annotations

import functools
import pickle
import tempfile
from typing import Any, Optional

from ...execution import MLClientCtx
from ...utils import logger


def apply_mlrun(model: Any = None, context: MLClientCtx | None = None,
                model_name: str = "model", tag: str = "",
                x_test=None, y_test=None, sample_set=None,
                label_column: str | None = None, log_model: bool = True,
                **kwargs):
    """Patch a sklearn-API estimator so fit() auto-logs to the context."""
    if context is None:
        import mlrun_tpu

        context = mlrun_tpu.get_or_create_ctx("sklearn")

    handler = SKLearnModelHandler(model, context, model_name, tag,
                                  x_test=x_test, y_test=y_test,
                                  sample_set=sample_set,
                                  label_column=label_column,
                                  log_model=log_model)
    if model is not None:
        handler.patch()
    return handler


class SKLearnModelHandler:
    def __init__(self, model, context, model_name="model", tag="",
                 x_test=None, y_test=None, sample_set=None,
                 label_column=None, log_model=True):
        self.model = model
        self.context = context
        self.model_name = model_name
        self.tag = tag
        self.x_test = x_test
        self.y_test = y_test
        self.sample_set = sample_set
        self.label_column = label_column
        self._log_model = log_model

    def patch(self):
        original_fit = self.model.fit

        @functools.wraps(original_fit)
        def wrapped_fit(*args, **kwargs):
            result = original_fit(*args, **kwargs)
            self._post_fit(args, kwargs)
            return result

        self.model.fit = wrapped_fit
        return self.model

    def _post_fit(self, fit_args, fit_kwargs):
        context = self.context
        try:
            params = {
                key: value for key, value in self.model.get_params().items()
                if isinstance(value, (int, float, str, bool))
            }
            context.parameters.update(params)
            context.set_label("model_class", type(self.model).__name__)
        except Exception:  # noqa: BLE001
            pass
        predictions = None
        if self.x_test is not None and self.y_test is not None:
            try:
                predictions = self.model.predict(self.x_test)
            except Exception as exc:  # noqa: BLE001
                logger.warning("test-set prediction failed",
                               error=str(exc))
        metrics = self._compute_metrics(predictions)
        if metrics:
            context.log_results(metrics)
        if predictions is not None:
            # evaluation artifact plans (confusion matrix / roc /
            # calibration / feature importance / residuals) — reuse the
            # predictions computed for the metrics
            from .._common import produce_artifacts

            try:
                produce_artifacts(context, self.model, self.x_test,
                                  self.y_test, y_pred=predictions)
            except Exception as exc:  # noqa: BLE001 - plots are best-effort
                logger.warning("artifact plans failed", error=str(exc))
        if self._log_model:
            self.log_model(metrics)

    def _compute_metrics(self, predictions=None) -> dict:
        if self.x_test is None or self.y_test is None:
            return {}
        import numpy as np

        from .._common.plans import _is_classifier

        metrics: dict = {}
        try:
            if predictions is None:
                predictions = self.model.predict(self.x_test)
            y = np.asarray(self.y_test).reshape(-1)
            p = np.asarray(predictions).reshape(-1)
            if _is_classifier(self.model, p):
                from sklearn.metrics import accuracy_score, f1_score

                metrics["accuracy"] = float(accuracy_score(y, p))
                try:
                    metrics["f1_score"] = float(
                        f1_score(y, p, average="macro"))
                except ValueError:
                    pass
            else:
                from sklearn.metrics import mean_squared_error, r2_score

                metrics["mse"] = float(mean_squared_error(y, p))
                metrics["r2"] = float(r2_score(y, p))
        except Exception as exc:  # noqa: BLE001
            logger.warning("metric computation failed", error=str(exc))
        return metrics

    def log_model(self, metrics: dict | None = None):
        # drop the instance-level fit patch so the estimator pickles clean
        patched_fit = self.model.__dict__.pop("fit", None)
        tmp = tempfile.NamedTemporaryFile(suffix=".pkl", delete=False)
        try:
            with open(tmp.name, "wb") as fp:
                pickle.dump(self.model, fp)
        finally:
            if patched_fit is not None:
                self.model.fit = patched_fit
        inputs = None
        outputs = None
        if self.sample_set is not None and self.label_column:
            inputs = [
                {"name": c, "value_type": str(self.sample_set[c].dtype)}
                for c in self.sample_set.columns if c != self.label_column
            ]
            outputs = [{"name": self.label_column}]
        return self.context.log_model(
            self.model_name, model_file=tmp.name, framework="sklearn",
            algorithm=type(self.model).__name__, metrics=metrics or {},
            tag=self.tag, inputs=inputs, outputs=outputs,
            training_set=self.sample_set, label_column=self.label_column)


class SKLearnModelServer:
    """V2ModelServer for pickled sklearn models (reference analog:
    mlrun/frameworks/sklearn model server via V2ModelServer)."""

    def __new__(cls, *args, **kwargs):
        # defined here to avoid a hard serving dependency at import time
        from ...serving.v2_serving import V2ModelServer

        class _Server(V2ModelServer):
            def load(self):
                model_file, extra = self.get_model(".pkl")
                with open(model_file, "rb") as fp:
                    self.model = pickle.load(fp)

            def predict(self, request):
                import numpy as np

                inputs = np.asarray(request["inputs"])
                return self.model.predict(inputs).tolist()

        return _Server(*args, **kwargs)
