"""HuggingFace adapter (reference analog: mlrun/frameworks/huggingface/
model_server.py:24 HuggingFaceModelServer).

TPU twist: ``load_hf_weights_into_llama`` maps HF Llama checkpoints into the
stacked-parameter pytree the TPU model uses, so fine-tunes start from real
weights; the model server runs tokenization on host and the generate loop on
TPU via mlrun_tpu.serving.llm.
"""

from __future__ import annotations

import numpy as np

from ...utils import logger


def load_hf_weights_into_llama(model_name_or_path: str, config=None,
                               dtype=None):
    """Load an HF Llama-family torch checkpoint into (LlamaConfig, params).

    Weights come via transformers (torch CPU) and are re-laid-out into the
    stacked [n_layers, ...] tree. Big models stream layer by layer.
    """
    import jax.numpy as jnp
    import torch
    from transformers import AutoConfig, AutoModelForCausalLM

    from ...models.llama import LlamaConfig

    hf_config = AutoConfig.from_pretrained(model_name_or_path)
    config = config or LlamaConfig(
        vocab_size=hf_config.vocab_size,
        n_layers=hf_config.num_hidden_layers,
        embed_dim=hf_config.hidden_size,
        n_heads=hf_config.num_attention_heads,
        n_kv_heads=getattr(hf_config, "num_key_value_heads",
                           hf_config.num_attention_heads),
        head_dim=getattr(hf_config, "head_dim",
                         hf_config.hidden_size
                         // hf_config.num_attention_heads),
        mlp_dim=hf_config.intermediate_size,
        rope_theta=getattr(hf_config, "rope_theta", 500000.0),
        norm_eps=hf_config.rms_norm_eps,
        tie_embeddings=getattr(hf_config, "tie_word_embeddings", False),
    )
    dtype = dtype or config.dtype

    model = AutoModelForCausalLM.from_pretrained(
        model_name_or_path, torch_dtype=torch.float32)
    sd = model.state_dict()

    def get(name):
        return np.asarray(sd[name].numpy())

    def stack(fmt, transpose=True):
        mats = [get(fmt.format(i)) for i in range(config.n_layers)]
        arr = np.stack(mats)
        if transpose:
            arr = arr.transpose(0, 2, 1)  # torch [out,in] -> ours [in,out]
        return jnp.asarray(arr, dtype)

    params = {
        "embedding": jnp.asarray(get("model.embed_tokens.weight"), dtype),
        "layers": {
            "attn_norm_scale": jnp.asarray(np.stack(
                [get(f"model.layers.{i}.input_layernorm.weight")
                 for i in range(config.n_layers)]), dtype),
            "wq": stack("model.layers.{}.self_attn.q_proj.weight"),
            "wk": stack("model.layers.{}.self_attn.k_proj.weight"),
            "wv": stack("model.layers.{}.self_attn.v_proj.weight"),
            "wo": stack("model.layers.{}.self_attn.o_proj.weight"),
            "mlp_norm_scale": jnp.asarray(np.stack(
                [get(f"model.layers.{i}.post_attention_layernorm.weight")
                 for i in range(config.n_layers)]), dtype),
            "w_gate": stack("model.layers.{}.mlp.gate_proj.weight"),
            "w_up": stack("model.layers.{}.mlp.up_proj.weight"),
            "w_down": stack("model.layers.{}.mlp.down_proj.weight"),
        },
        "final_norm_scale": jnp.asarray(get("model.norm.weight"), dtype),
    }
    if not config.tie_embeddings:
        params["lm_head"] = jnp.asarray(
            get("lm_head.weight").transpose(1, 0), dtype)
    return config, params


class HuggingFaceModelServer:
    """Serving-graph step wrapping an HF pipeline on host CPU (parity with
    reference huggingface/model_server.py) — use LLMModelServer from
    mlrun_tpu.serving.llm for TPU-compiled generation."""

    def __new__(cls, *args, **kwargs):
        from ...serving.v2_serving import V2ModelServer

        class _Server(V2ModelServer):
            def __init__(self, *a, task: str = "text-classification",
                         model_name: str | None = None, **kw):
                super().__init__(*a, **kw)
                self.task = task
                self.hf_model_name = model_name

            def load(self):
                from transformers import pipeline

                self.model = pipeline(
                    self.task, model=self.hf_model_name or None)

            def predict(self, request):
                return [self.model(item) for item in request["inputs"]]

        return _Server(*args, **kwargs)
