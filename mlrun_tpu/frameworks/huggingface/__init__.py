"""HuggingFace adapter (reference analog: mlrun/frameworks/huggingface/
model_server.py:24 HuggingFaceModelServer).

TPU twist: ``load_hf_weights_into_llama`` maps HF Llama checkpoints into the
stacked-parameter pytree the TPU model uses, so fine-tunes start from real
weights; the model server runs tokenization on host and the generate loop on
TPU via mlrun_tpu.serving.llm.
"""

from __future__ import annotations

import json
import os

import numpy as np

from ...utils import logger


class _CheckpointReader:
    """Tensor-by-tensor access to an HF checkpoint directory WITHOUT
    instantiating the torch model: safetensors (single or sharded via
    model.safetensors.index.json) are opened lazily per file; pytorch .bin
    falls back to a torch mmap load. Peak host memory is one tensor at a
    time, which is what lets 8B-class weights load inside a container."""

    def __init__(self, directory: str):
        self.directory = directory
        self._file_of: dict[str, str] = {}
        self._handles: dict = {}
        self._bin_state: dict = {}

        st_index = os.path.join(directory, "model.safetensors.index.json")
        st_single = os.path.join(directory, "model.safetensors")
        bin_index = os.path.join(directory, "pytorch_model.bin.index.json")
        bin_single = os.path.join(directory, "pytorch_model.bin")
        if os.path.exists(st_index):
            with open(st_index) as fp:
                weight_map = json.load(fp)["weight_map"]
            self._file_of = dict(weight_map)
            self._kind = "safetensors"
        elif os.path.exists(st_single):
            from safetensors import safe_open

            with safe_open(st_single, framework="np") as f:
                self._file_of = {k: "model.safetensors" for k in f.keys()}
            self._kind = "safetensors"
        elif os.path.exists(bin_index):
            with open(bin_index) as fp:
                self._file_of = dict(json.load(fp)["weight_map"])
            self._kind = "bin"
        elif os.path.exists(bin_single):
            self._file_of = {}
            self._kind = "bin_single"
        else:
            raise FileNotFoundError(
                f"no model.safetensors[.index.json] or pytorch_model.bin"
                f"[.index.json] under {directory}")

    def get(self, name: str) -> np.ndarray:
        if self._kind == "safetensors":
            from safetensors import safe_open

            fname = self._file_of[name]
            handle = self._handles.get(fname)
            if handle is None:
                handle = safe_open(os.path.join(self.directory, fname),
                                   framework="np")
                self._handles[fname] = handle
            return handle.get_tensor(name)
        # torch .bin path: mmap keeps tensors on disk until accessed
        import torch

        fname = self._file_of.get(name, "pytorch_model.bin")
        state = self._bin_state.get(fname)
        if state is None:
            state = torch.load(os.path.join(self.directory, fname),
                               map_location="cpu", mmap=True,
                               weights_only=True)
            self._bin_state[fname] = state
        return np.asarray(state[name].float().numpy())

    def close(self):
        self._handles.clear()
        self._bin_state.clear()


def _resolve_checkpoint_dir(model_name_or_path: str) -> str:
    if os.path.isdir(model_name_or_path):
        return model_name_or_path
    from huggingface_hub import snapshot_download

    # only the serving checkpoint + configs — a bare snapshot would also
    # pull duplicate original/*.pth weights, doubling disk in-container
    return snapshot_download(
        model_name_or_path,
        allow_patterns=["*.safetensors", "*.safetensors.index.json",
                        "*.bin", "*.bin.index.json", "*.json", "*.model",
                        "tokenizer*"],
        ignore_patterns=["original/*", "*.pth", "*.gguf"])


def load_hf_weights_into_llama(model_name_or_path: str, config=None,
                               dtype=None, shardings=None):
    """Load an HF Llama-family checkpoint into (LlamaConfig, params).

    Streams the checkpoint shard-by-shard (never instantiates the torch
    model): each stacked leaf of the target tree is assembled tensor by
    tensor in the target dtype and placed on device immediately, so host
    peak memory is one leaf + one source tensor — 8B-class weights load
    inside a 16GB container. ``shardings`` may be a pytree of
    NamedShardings matching the param tree to place leaves sharded across
    a mesh directly.
    """
    import jax
    import jax.numpy as jnp
    from transformers import AutoConfig

    from ...models.llama import LlamaConfig

    hf_config = AutoConfig.from_pretrained(model_name_or_path)
    config = config or LlamaConfig(
        vocab_size=hf_config.vocab_size,
        n_layers=hf_config.num_hidden_layers,
        embed_dim=hf_config.hidden_size,
        n_heads=hf_config.num_attention_heads,
        n_kv_heads=getattr(hf_config, "num_key_value_heads",
                           hf_config.num_attention_heads),
        head_dim=getattr(hf_config, "head_dim",
                         hf_config.hidden_size
                         // hf_config.num_attention_heads),
        mlp_dim=hf_config.intermediate_size,
        rope_theta=getattr(hf_config, "rope_theta", 500000.0),
        norm_eps=hf_config.rms_norm_eps,
        tie_embeddings=getattr(hf_config, "tie_word_embeddings", False),
    )
    dtype = dtype or config.dtype
    if jnp.dtype(dtype).name == "bfloat16":
        import ml_dtypes

        np_dtype = ml_dtypes.bfloat16
    else:
        np_dtype = np.dtype(jnp.dtype(dtype).name)

    reader = _CheckpointReader(
        _resolve_checkpoint_dir(model_name_or_path))

    def place(array, path: tuple):
        sharding = None
        if shardings is not None:
            node = shardings
            for key in path:
                node = node[key]
            sharding = node
        if sharding is not None:
            return jax.device_put(array, sharding)
        return jnp.asarray(array)

    def leaf(name: str, path: tuple, transpose=False):
        tensor = reader.get(name)
        if transpose:
            tensor = tensor.transpose(1, 0)
        return place(np.asarray(tensor, np_dtype), path)

    def stacked(fmt: str, path: tuple, transpose=True):
        """Assemble [n_layers, ...] leaf one layer-tensor at a time in the
        TARGET dtype (the fp32 source tensor is freed per layer)."""
        first = reader.get(fmt.format(0))
        if transpose:
            first = first.transpose(1, 0)  # torch [out,in] -> ours [in,out]
        out = np.empty((config.n_layers,) + first.shape, np_dtype)
        out[0] = first.astype(np_dtype)
        del first
        for i in range(1, config.n_layers):
            tensor = reader.get(fmt.format(i))
            if transpose:
                tensor = tensor.transpose(1, 0)
            out[i] = tensor.astype(np_dtype)
            del tensor
        return place(out, path)

    layers_path = ("layers",)
    params = {
        "embedding": leaf("model.embed_tokens.weight", ("embedding",)),
        "layers": {
            "attn_norm_scale": stacked(
                "model.layers.{}.input_layernorm.weight",
                layers_path + ("attn_norm_scale",), transpose=False),
            "wq": stacked("model.layers.{}.self_attn.q_proj.weight",
                          layers_path + ("wq",)),
            "wk": stacked("model.layers.{}.self_attn.k_proj.weight",
                          layers_path + ("wk",)),
            "wv": stacked("model.layers.{}.self_attn.v_proj.weight",
                          layers_path + ("wv",)),
            "wo": stacked("model.layers.{}.self_attn.o_proj.weight",
                          layers_path + ("wo",)),
            "mlp_norm_scale": stacked(
                "model.layers.{}.post_attention_layernorm.weight",
                layers_path + ("mlp_norm_scale",), transpose=False),
            "w_gate": stacked("model.layers.{}.mlp.gate_proj.weight",
                              layers_path + ("w_gate",)),
            "w_up": stacked("model.layers.{}.mlp.up_proj.weight",
                            layers_path + ("w_up",)),
            "w_down": stacked("model.layers.{}.mlp.down_proj.weight",
                              layers_path + ("w_down",)),
        },
        "final_norm_scale": leaf("model.norm.weight",
                                 ("final_norm_scale",)),
    }
    if not config.tie_embeddings:
        params["lm_head"] = leaf("lm_head.weight", ("lm_head",),
                                 transpose=True)
    reader.close()
    logger.info("streamed HF checkpoint", model=model_name_or_path,
                layers=config.n_layers)
    return config, params


class HuggingFaceModelServer:
    """Serving-graph step wrapping an HF pipeline on host CPU (parity with
    reference huggingface/model_server.py) — use LLMModelServer from
    mlrun_tpu.serving.llm for TPU-compiled generation."""

    def __new__(cls, *args, **kwargs):
        from ...serving.v2_serving import V2ModelServer

        class _Server(V2ModelServer):
            def __init__(self, *a, task: str = "text-classification",
                         model_name: str | None = None, **kw):
                super().__init__(*a, **kw)
                self.task = task
                self.hf_model_name = model_name

            def load(self):
                from transformers import pipeline

                self.model = pipeline(
                    self.task, model=self.hf_model_name or None)

            def predict(self, request):
                return [self.model(item) for item in request["inputs"]]

        return _Server(*args, **kwargs)
