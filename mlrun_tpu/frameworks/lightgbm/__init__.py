"""LightGBM MLRun interface (reference analog: mlrun/frameworks/lgbm/ —
its own MLRunInterface with training callbacks, not a sklearn alias).

- sklearn-API estimators (``LGBMClassifier``/``LGBMRegressor``): the
  sklearn fit-patch carries metric logging, plus a lightgbm-specific
  split/gain feature-importance artifact post-fit.
- native ``lightgbm.train`` workflows: ``mlrun_callback`` follows the
  lightgbm callback contract (a callable invoked each iteration with a
  ``CallbackEnv`` carrying ``iteration`` and ``evaluation_result_list``)
  and ``log_booster`` logs the trained booster.

Booster logic is duck-typed and testable without the lightgbm package;
only ``apply_mlrun`` on a real estimator requires the import.
"""

from __future__ import annotations

from .._common.boosters import (
    estimator_importance_scores,
    log_booster_model,
    log_importance_artifact,
    wrap_post_fit,
)


def _importance_artifact(context, booster, model_name: str) -> dict:
    """split/gain importances for Booster objects,
    ``feature_importances_`` for sklearn-API estimators."""
    scores: dict = {}
    importance = getattr(booster, "feature_importance", None)
    if importance is None:  # sklearn-API estimator
        scores = estimator_importance_scores(booster)
    else:
        names = (booster.feature_name()
                 if callable(getattr(booster, "feature_name", None))
                 else [])
        for importance_type in ("split", "gain"):
            try:
                values = importance(importance_type=importance_type)
            except Exception:  # noqa: BLE001
                continue
            keys = names or [f"f{i}" for i in range(len(values))]
            scores[importance_type] = {
                str(k): float(v) for k, v in zip(keys, values)}
    log_importance_artifact(context, model_name, scores, "lightgbm")
    return scores


def mlrun_callback(context, log_every: int = 10):
    """A lightgbm training callback: logs each eval metric per iteration
    (lightgbm calls the callback with a CallbackEnv whose
    ``evaluation_result_list`` holds ``(data_name, metric, value, _)``
    tuples) and the final values as run results via ``.finalize()``."""
    state = {"last": []}

    def callback(env):
        state["last"] = list(env.evaluation_result_list or [])
        if env.iteration % max(1, log_every) == 0:
            metrics = {f"{item[0]}-{item[1]}": float(item[2])
                       for item in state["last"]}
            if metrics:
                context.log_metrics(metrics, step=env.iteration)

    def finalize():
        for item in state["last"]:
            context.log_result(f"{item[0]}-{item[1]}", float(item[2]))

    callback.order = 20  # lightgbm sorts callbacks by this attribute
    callback.finalize = finalize
    return callback


def log_booster(context, booster, model_name: str = "model",
                tag: str = "", metrics: dict | None = None,
                label_column: str | None = None):
    """Log a trained booster (native ``lightgbm.train`` path) as a model
    artifact with importance scores."""
    _importance_artifact(context, booster, model_name)
    return log_booster_model(
        context, booster, "lightgbm", ".txt", model_name=model_name,
        tag=tag, metrics=metrics, label_column=label_column)


def apply_mlrun(model=None, context=None, model_name: str = "model",
                tag: str = "", **kwargs):
    """Auto-log an sklearn-API lightgbm estimator: metrics via the
    sklearn fit patch, plus the importance artifact post-fit."""
    try:
        import lightgbm  # noqa: F401
    except ImportError as exc:
        raise ImportError(
            "lightgbm is not installed in this environment") from exc
    from ..sklearn import apply_mlrun as sklearn_apply

    handler = sklearn_apply(model=model, context=context,
                            model_name=model_name, tag=tag, **kwargs)
    return wrap_post_fit(handler, _importance_artifact)


def LGBMModelServer(*args, **kwargs):
    from ..sklearn import SKLearnModelServer

    return SKLearnModelServer(*args, **kwargs)
