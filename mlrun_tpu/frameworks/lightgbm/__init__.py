"""LightGBM auto-logger (reference analog: mlrun/frameworks/lgbm/).

Gated on the lightgbm package; sklearn-API estimators reuse the sklearn
handler.
"""

from __future__ import annotations


def apply_mlrun(model=None, context=None, model_name: str = "model",
                tag: str = "", **kwargs):
    try:
        import lightgbm  # noqa: F401
    except ImportError as exc:
        raise ImportError(
            "lightgbm is not installed in this environment") from exc
    from ..sklearn import apply_mlrun as sklearn_apply

    return sklearn_apply(model=model, context=context,
                         model_name=model_name, tag=tag, **kwargs)


def LGBMModelServer(*args, **kwargs):
    from ..sklearn import SKLearnModelServer

    return SKLearnModelServer(*args, **kwargs)
