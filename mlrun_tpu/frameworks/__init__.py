"""Framework adapters (reference analog: mlrun/frameworks/ —
``apply_mlrun`` per framework; the PyTorch/Horovod trainer is replaced by
the JAX auto-trainer in frameworks/jax)."""

from __future__ import annotations


def auto_mlrun(model=None, context=None, **kwargs):
    """Auto-detect the framework and apply tracking
    (reference analog: mlrun/frameworks/auto_mlrun/)."""
    module = type(model).__module__ if model is not None else ""
    if module.startswith("sklearn") or module.startswith("xgboost") \
            or module.startswith("lightgbm"):
        from .sklearn import apply_mlrun as apply

        return apply(model=model, context=context, **kwargs)
    if module.startswith(("flax", "jax")) or model is None:
        from .jax import apply_mlrun as apply

        return apply(model=model, context=context, **kwargs)
    raise ValueError(f"cannot auto-detect framework for {type(model)}")
