"""PyTorch user-code adapter (reference analog: mlrun/frameworks/pytorch/ —
apply_mlrun + train/evaluate helpers, mlrun_interface.py:106,220).

IMPORTANT design note: the reference's Horovod/NCCL distributed path
(hvd.init :561-566, allreduce :849, DistributedSampler :903) is deliberately
NOT reproduced — TPU-scale training goes through the JAX auto-trainer
(frameworks/jax). This adapter provides user-code parity for existing torch
training scripts running host-side (CPU): auto-logging of per-epoch metrics
and model registration into the same registry.
"""

from __future__ import annotations

import os
import tempfile
from typing import Callable, Optional

from ...execution import MLClientCtx
from ...utils import logger


def apply_mlrun(model=None, context: MLClientCtx | None = None,
                model_name: str = "model", tag: str = "", **kwargs):
    if context is None:
        import mlrun_tpu

        context = mlrun_tpu.get_or_create_ctx("torch")
    return TorchModelHandler(model, context, model_name, tag)


class TorchModelHandler:
    def __init__(self, model, context, model_name="model", tag=""):
        self.model = model
        self.context = context
        self.model_name = model_name
        self.tag = tag

    def log_epoch(self, epoch: int, metrics: dict):
        if self.context.is_logging_worker():
            self.context.log_metrics(
                {k: float(v) for k, v in metrics.items()}, step=epoch)

    def log_model(self, metrics: dict | None = None,
                  parameters: dict | None = None):
        import torch

        tmp_dir = tempfile.mkdtemp()
        path = os.path.join(tmp_dir, f"{self.model_name}.pt")
        torch.save(self.model.state_dict(), path)
        return self.context.log_model(
            self.model_name, model_file=path, framework="pytorch",
            metrics=metrics or {}, parameters=parameters or {},
            tag=self.tag)


def _metric_names(metrics: list) -> list[str]:
    """Unique reporting keys for metric callables: collisions (two
    lambdas, partials, or a metric shadowing 'loss'/'lr') get numeric
    suffixes instead of silently summing into one bucket."""
    names: list[str] = []
    taken = {"loss", "lr"}
    for metric in metrics:
        base = getattr(metric, "__name__", None) or type(metric).__name__
        name, n = base, 1
        while name in taken:
            n += 1
            name = f"{base}_{n}"
        taken.add(name)
        names.append(name)
    return names


def train(model, loss_fn, optimizer, train_loader,
          context: MLClientCtx | None = None, epochs: int = 1,
          validation_loader=None, model_name: str = "model",
          log_model: bool = True, callbacks: list | None = None,
          scheduler=None, metrics: list | None = None) -> dict:
    """Torch training loop driven by the shared callback architecture
    (reference pytorch/__init__.py:46 train +
    mlrun_interface.py:106 _epoch loop, minus Horovod): per-epoch metric
    logging, user ``metrics`` callables ``m(y_pred, y_true) -> float``
    averaged over train and validation epochs (reference
    logging_callback metric functions), and any
    ``frameworks._common.Callback`` — EarlyStopping/Checkpoint/
    TensorBoard/EvalPlan — plugs into the same hooks the JAX trainer
    drives."""
    import torch

    from .._common.callbacks import CallbackList

    handler = apply_mlrun(model, context, model_name)
    context = handler.context
    hooks = CallbackList(callbacks, context=context, model=model)
    hooks.on_train_begin()
    metrics = metrics or []
    metric_names = _metric_names(metrics)
    final: dict = {}
    step = 0
    for epoch in range(epochs):
        hooks.on_epoch_begin(epoch)
        model.train()
        sums = {"loss": 0.0, **{name: 0.0 for name in metric_names}}
        count = 0
        stop = False
        for inputs, targets in train_loader:
            optimizer.zero_grad()
            outputs = model(inputs)
            loss = loss_fn(outputs, targets)
            loss.backward()
            optimizer.step()
            loss_value = float(loss.detach())
            sums["loss"] += loss_value
            with torch.no_grad():
                for name, metric in zip(metric_names, metrics):
                    sums[name] += float(metric(outputs, targets))
            count += 1
            if not hooks.on_step_end(step, {"loss": loss_value}):
                stop = True
            step += 1
            if stop:
                break
        if scheduler is not None:
            scheduler.step()
        epoch_metrics = {k: v / max(count, 1) for k, v in sums.items()}
        if optimizer.param_groups:
            epoch_metrics["lr"] = float(
                optimizer.param_groups[0].get("lr", 0.0))
        if validation_loader is not None:
            epoch_metrics.update(evaluate(
                model, loss_fn, validation_loader, metrics=metrics,
                prefix="validation_"))
        handler.log_epoch(epoch, epoch_metrics)
        final = epoch_metrics
        if not hooks.on_epoch_end(epoch, epoch_metrics) or stop:
            final = dict(final)
            final["stopped_early"] = True
            break
    hooks.on_train_end(final)
    if context is not None:
        context.log_results(final)
    if log_model:
        handler.log_model(metrics={
            k: v for k, v in final.items()
            if isinstance(v, (int, float)) and not isinstance(v, bool)})
    return final


def evaluate(model, loss_fn, loader, context: MLClientCtx | None = None,
             metrics: list | None = None, prefix: str = "eval_") -> dict:
    """Evaluation loop with the same metric callables as train()
    (reference pytorch/__init__.py:212 analog)."""
    import torch

    model.eval()
    metrics = metrics or []
    metric_names = _metric_names(metrics)
    sums = {"loss": 0.0, **{name: 0.0 for name in metric_names}}
    count = 0
    with torch.no_grad():
        for inputs, targets in loader:
            outputs = model(inputs)
            sums["loss"] += float(loss_fn(outputs, targets))
            for name, metric in zip(metric_names, metrics):
                sums[name] += float(metric(outputs, targets))
            count += 1
    results = {f"{prefix}{k}": v / max(count, 1) for k, v in sums.items()}
    if context is not None:
        context.log_results(results)
    return results


class TorchModelServer:
    """V2ModelServer for saved torch state dicts; requires a model_class
    factory passed as a class arg."""

    def __new__(cls, *args, **kwargs):
        from ...serving.v2_serving import V2ModelServer

        class _Server(V2ModelServer):
            def __init__(self, *a, model_factory: Callable | None = None,
                         **kw):
                super().__init__(*a, **kw)
                self.model_factory = model_factory

            def load(self):
                import torch

                if self.model_factory is None:
                    raise ValueError(
                        "TorchModelServer needs a model_factory class arg")
                model_file, _ = self.get_model(".pt")
                self.model = self.model_factory()
                self.model.load_state_dict(
                    torch.load(model_file, weights_only=True))
                self.model.eval()

            def predict(self, request):
                import torch

                inputs = torch.tensor(request["inputs"])
                with torch.no_grad():
                    return self.model(inputs).tolist()

        return _Server(*args, **kwargs)
