"""Shared booster-model logging for the gradient-boosting frameworks
(xgboost/lightgbm): one save/log flow, per-framework importance
extraction stays in the framework modules."""

from __future__ import annotations

import json
import os
import tempfile


def log_importance_artifact(context, model_name: str, scores: dict,
                            framework: str):
    if not scores:
        return
    context.log_artifact(
        f"{model_name}_feature_importance",
        body=json.dumps(scores, indent=2),
        format="json", labels={"framework": framework})


def estimator_importance_scores(estimator) -> dict:
    """The sklearn-API branch shared by both boosting frameworks:
    ``feature_importances_`` -> {"importance": {name: value}}."""
    values = getattr(estimator, "feature_importances_", None)
    if values is None:
        return {}
    names = (getattr(estimator, "feature_names_in_", None)
             if getattr(estimator, "feature_names_in_", None) is not None
             else getattr(estimator, "feature_name_", None))
    if names is None:
        names = [f"f{i}" for i in range(len(values))]
    return {"importance": {str(n): float(v)
                           for n, v in zip(names, values)}}


def wrap_post_fit(handler, importance_fn):
    """Chain a framework-specific importance artifact onto the sklearn
    handler's post-fit hook (shared by the xgboost/lightgbm
    ``apply_mlrun`` wrappers)."""
    post_fit = handler._post_fit

    def wrapped(fit_args, fit_kwargs):
        post_fit(fit_args, fit_kwargs)
        importance_fn(handler.context, handler.model, handler.model_name)

    handler._post_fit = wrapped
    return handler


def log_booster_model(context, booster, framework: str, suffix: str,
                      model_name: str = "model", tag: str = "",
                      metrics: dict | None = None,
                      label_column: str | None = None):
    """Serialize a booster (native ``save_model`` when available, pickle
    otherwise) and log it as a model artifact; the temp file is removed
    after the artifact upload."""
    if not hasattr(booster, "save_model"):
        suffix = ".pkl"
    fd, path = tempfile.mkstemp(suffix=suffix)
    os.close(fd)
    try:
        if hasattr(booster, "save_model"):
            booster.save_model(path)
        else:
            import pickle

            with open(path, "wb") as fp:
                pickle.dump(booster, fp)
        parameters = {}
        best_iteration = getattr(booster, "best_iteration", None)
        # lightgbm uses -1 as its "no early stopping" sentinel
        if best_iteration is not None and int(best_iteration) >= 0:
            parameters["best_iteration"] = int(best_iteration)
        return context.log_model(
            model_name, model_file=path, framework=framework,
            algorithm=type(booster).__name__, metrics=metrics or {},
            tag=tag, label_column=label_column,
            parameters=parameters or None)
    finally:
        try:
            os.unlink(path)
        except OSError:
            pass
