"""Structured training callbacks — the framework-wide hook architecture.

Reference analog: ``mlrun/frameworks/pytorch/callbacks/`` (callback.py:25
Callback ABC; logging_callback.py; mlrun_logging_callback.py;
tensorboard_logging_callback.py), driven by
``mlrun/frameworks/pytorch/mlrun_interface.py:106,220``. Re-designed
framework-agnostic and minus the Horovod rank machinery (the execution
context's ``is_logging_worker()`` — ``jax.process_index() == 0`` — is the
rank gate here):

- the JAX ``Trainer.fit`` drives these hooks natively (steps, and epochs
  when ``epoch_steps`` is set);
- the torch/tf adapters translate their native epoch streams into the
  SAME hooks, so one EarlyStopping/Checkpoint/TensorBoard implementation
  serves every framework.

A hook returning ``False`` from ``on_step_end``/``on_epoch_end`` stops
training (graceful early stop — the trainer finishes bookkeeping and
reports ``stopped_early``).
"""

from __future__ import annotations

import math
import os
import tempfile
from typing import Callable, Optional, Sequence

from ...utils import logger


class Callback:
    """Base hook set. Subclass and override what you need; ``set_state``
    is called by the driver before ``on_train_begin`` with whatever
    handles exist (run context, jax Trainer, torch/keras model)."""

    context = None
    trainer = None
    model = None

    def set_state(self, context=None, trainer=None, model=None):
        self.context = context if context is not None else self.context
        self.trainer = trainer if trainer is not None else self.trainer
        self.model = model if model is not None else self.model

    def on_train_begin(self):
        pass

    def on_epoch_begin(self, epoch: int):
        pass

    def on_step_end(self, step: int, metrics: dict) -> Optional[bool]:
        pass

    def on_epoch_end(self, epoch: int, metrics: dict) -> Optional[bool]:
        pass

    def on_train_end(self, metrics: dict):
        pass


class FunctionCallback(Callback):
    """Adapter for the legacy bare-callable contract
    ``callback(step, metrics, trainer)`` (pre-r5 Trainer.fit): fired at
    LOG POINTS only, with the enriched metrics (tokens_per_sec/mfu/step)
    — exactly the old cadence, so pre-existing callables keep working."""

    log_points_only = True

    def __init__(self, fn: Callable):
        self.fn = fn

    def on_step_end(self, step: int, metrics: dict) -> None:
        # the old loop DISCARDED return values — keep that: a callable
        # returning something falsy (e.g. CheckpointManager.save's bool)
        # must not be read as a stop vote
        self.fn(step, metrics, self.trainer)


class CallbackList:
    """Dispatches one event to every callback; aggregates stop votes
    (any explicit ``False`` stops training)."""

    def __init__(self, callbacks: Sequence | None, context=None,
                 trainer=None, model=None):
        self.callbacks: list[Callback] = []
        for cb in callbacks or []:
            if isinstance(cb, Callback):
                self.callbacks.append(cb)
            elif callable(cb):
                self.callbacks.append(FunctionCallback(cb))
            else:
                raise TypeError(
                    f"callback {cb!r} is neither a Callback nor callable")
        for cb in self.callbacks:
            cb.set_state(context=context, trainer=trainer, model=model)

    def _dispatch(self, event: str, *args) -> bool:
        keep_going = True
        for cb in self.callbacks:
            try:
                if getattr(cb, event)(*args) is False:
                    keep_going = False
            except Exception as exc:  # noqa: BLE001 - a broken callback
                # must not kill the training run it observes
                logger.warning("callback failed", callback=type(cb).__name__,
                               event=event, error=str(exc))
        return keep_going

    def on_train_begin(self):
        self._dispatch("on_train_begin")

    def on_epoch_begin(self, epoch: int):
        self._dispatch("on_epoch_begin", epoch)

    def on_step_end(self, step: int, metrics: dict,
                    log_point: bool = True) -> bool:
        keep_going = True
        for cb in self.callbacks:
            if not log_point and getattr(cb, "log_points_only", False):
                continue
            try:
                if cb.on_step_end(step, metrics) is False:
                    keep_going = False
            except Exception as exc:  # noqa: BLE001
                logger.warning("callback failed",
                               callback=type(cb).__name__,
                               event="on_step_end", error=str(exc))
        return keep_going

    def on_epoch_end(self, epoch: int, metrics: dict) -> bool:
        return self._dispatch("on_epoch_end", epoch, metrics)

    def on_train_end(self, metrics: dict):
        self._dispatch("on_train_end", metrics)


class MetricsLoggingCallback(Callback):
    """Per-epoch metric logging into the run context (reference
    mlrun_logging_callback); the jax Trainer logs per-step itself, so
    this is mainly for the torch/tf adapters."""

    def on_epoch_end(self, epoch: int, metrics: dict) -> None:
        if self.context is not None and metrics \
                and self.context.is_logging_worker():
            self.context.log_metrics(
                {k: float(v) for k, v in metrics.items()
                 if isinstance(v, (int, float))}, step=epoch)


class EarlyStoppingCallback(Callback):
    """Stop when ``monitor`` hasn't improved by ``min_delta`` for
    ``patience`` evaluations (epochs when epochs exist, else steps)."""

    def __init__(self, monitor: str = "loss", patience: int = 3,
                 min_delta: float = 0.0, mode: str = "min"):
        if mode not in ("min", "max"):
            raise ValueError(f"mode must be min|max, got {mode!r}")
        self.monitor = monitor
        self.patience = patience
        self.min_delta = min_delta
        self.mode = mode
        self.best = math.inf if mode == "min" else -math.inf
        self.stale = 0
        self.stopped = False

    def on_train_begin(self):
        # a reused instance (e.g. stored on a keras handler and driven
        # through several fit() calls) must start each run fresh, or the
        # carried-over stale counter stops run 2 on its first epoch
        self.best = math.inf if self.mode == "min" else -math.inf
        self.stale = 0
        self.stopped = False
        self._epoch_driven = False

    def _observe(self, metrics: dict) -> Optional[bool]:
        value = metrics.get(self.monitor)
        if value is None:
            return None
        value = float(value)
        improved = (value < self.best - self.min_delta
                    if self.mode == "min"
                    else value > self.best + self.min_delta)
        if improved:
            self.best = value
            self.stale = 0
            return None
        self.stale += 1
        if self.stale >= self.patience:
            self.stopped = True
            logger.info("early stopping", monitor=self.monitor,
                        best=self.best, patience=self.patience)
            return False
        return None

    def on_epoch_end(self, epoch: int, metrics: dict) -> Optional[bool]:
        return self._observe(metrics)

    def on_step_end(self, step: int, metrics: dict) -> Optional[bool]:
        # only steps drive early stop when there is no epoch structure
        # (the jax Trainer without epoch_steps); the driver guarantees
        # at most one of the two streams carries metrics
        if getattr(self, "_epoch_driven", False):
            return None
        return self._observe(metrics)

    def on_epoch_begin(self, epoch: int):
        self._epoch_driven = True


class CheckpointCallback(Callback):
    """Checkpoint every N steps/epochs through a manager with
    ``save(step, state, force=False)`` (training.CheckpointManager), or a
    custom ``save_fn``. ``monitor`` + ``mode`` switch to best-only."""

    def __init__(self, manager=None, every_steps: int = 0,
                 every_epochs: int = 0, save_fn: Callable | None = None,
                 monitor: str | None = None, mode: str = "min"):
        if manager is None and save_fn is None:
            raise ValueError("CheckpointCallback needs manager= or save_fn=")
        self.manager = manager
        self.every_steps = every_steps
        self.every_epochs = every_epochs
        self.save_fn = save_fn
        self.monitor = monitor
        self.mode = mode
        self.best = math.inf if mode == "min" else -math.inf
        self.saves = 0

    def _improved(self, metrics: dict) -> bool:
        if not self.monitor:
            return True
        value = metrics.get(self.monitor)
        if value is None:
            return False
        value = float(value)
        better = value < self.best if self.mode == "min" \
            else value > self.best
        if better:
            self.best = value
        return better

    def _save(self, tag: int):
        if self.save_fn is not None:
            self.save_fn(tag)
        else:
            state = getattr(self.trainer, "state", None)
            if state is None:
                return
            self.manager.save(int(state.step), state, force=True)
            # record the resumable point on status.checkpoint: a
            # hard-killed run (no deliverable SIGTERM) is resubmitted with
            # whatever the service finds here — the graceful-preemption
            # branch in Trainer.fit never runs in that scenario
            if self.context is not None and \
                    hasattr(self.context, "log_checkpoint"):
                self.context.log_checkpoint(
                    self.manager.directory, step=int(state.step))
        self.saves += 1

    def on_step_end(self, step: int, metrics: dict) -> None:
        if self.every_steps and (step + 1) % self.every_steps == 0 \
                and self._improved(metrics):
            self._save(step)

    def on_epoch_end(self, epoch: int, metrics: dict) -> None:
        if self.every_epochs and (epoch + 1) % self.every_epochs == 0 \
                and self._improved(metrics):
            self._save(epoch)


class TensorBoardCallback(Callback):
    """Scalar summaries per step/epoch into TensorBoard event files; the
    log dir is registered as a run artifact at train end (reference
    tensorboard_logging_callback.py, framework-agnostic via
    torch.utils.tensorboard; import-gated)."""

    def __init__(self, log_dir: str = "", name: str = "tensorboard"):
        # import HERE so a missing writer fails loudly at construction
        # (CallbackList isolates hook exceptions, so an on_train_begin
        # ImportError would silently disable the requested feature)
        from torch.utils.tensorboard import SummaryWriter  # noqa: F401

        self.log_dir = log_dir
        self.name = name
        self._writer = None

    def on_train_begin(self):
        from torch.utils.tensorboard import SummaryWriter

        self.log_dir = self.log_dir or os.path.join(
            tempfile.mkdtemp(prefix="mlt-tb-"), "train")
        os.makedirs(self.log_dir, exist_ok=True)
        self._writer = SummaryWriter(self.log_dir)

    def _write(self, prefix: str, tick: int, metrics: dict):
        if self._writer is None:
            return
        for key, value in metrics.items():
            if isinstance(value, (int, float)) \
                    and not isinstance(value, bool) \
                    and math.isfinite(float(value)):
                self._writer.add_scalar(f"{prefix}/{key}", float(value),
                                        tick)

    def on_step_end(self, step: int, metrics: dict) -> None:
        # no per-step flush: SummaryWriter's periodic flushing covers the
        # steady state; explicit flushes ride the epoch/train boundaries
        self._write("step", step, metrics)

    def on_epoch_end(self, epoch: int, metrics: dict) -> None:
        self._write("epoch", epoch, metrics)
        if self._writer is not None:
            self._writer.flush()

    def on_train_end(self, metrics: dict):
        if self._writer is not None:
            self._writer.close()
        if self.context is not None and self.log_dir \
                and os.path.isdir(self.log_dir) \
                and self.context.is_logging_worker():
            try:
                self.context.log_artifact(
                    self.name, local_path=self.log_dir,
                    labels={"viewer": "tensorboard"})
            except Exception as exc:  # noqa: BLE001 - artifact best-effort
                logger.warning("tensorboard artifact failed",
                               error=str(exc))


class EvalPlanCallback(Callback):
    """Per-epoch artifact plans (confusion matrix / ROC / residuals ...)
    from ``_common.plans`` over a user eval set: ``eval_fn(model) ->
    (y_true, y_pred)`` runs every N epochs and at train end, each plan
    producing a versioned artifact (reference logging_callback's dynamic
    hyperparameter/metric artifacts generalized to the plan registry)."""

    def __init__(self, eval_fn: Callable, plans: Sequence | None = None,
                 every_epochs: int = 1, x=None):
        self.eval_fn = eval_fn
        self.plans = plans
        self.every_epochs = max(1, every_epochs)
        self.x = x

    def _produce(self, tick: int | None):
        from .plans import produce_artifacts

        if self.context is None or not self.context.is_logging_worker():
            return
        y_true, y_pred = self.eval_fn(self.model or self.trainer)
        suffix = "" if tick is None else f"-epoch{tick}"
        produce_artifacts(self.context, self.model, self.x, y_true,
                          y_pred=y_pred, plans=self.plans,
                          key_suffix=suffix)

    def on_epoch_end(self, epoch: int, metrics: dict) -> None:
        if (epoch + 1) % self.every_epochs == 0:
            self._produce(epoch)

    def on_train_end(self, metrics: dict):
        self._produce(None)
