"""Shared framework plumbing (reference analog: mlrun/frameworks/_common/ —
MLRunInterface, artifact plans, producers; ~6k LoC re-designed compactly).

The plan library turns a fitted model + evaluation data into artifact
plots/tables; a producer selects the applicable plans and runs them inside
the run context. Framework adapters (sklearn/xgboost/lightgbm) share it.
"""

from .callbacks import (  # noqa: F401
    Callback,
    CallbackList,
    CheckpointCallback,
    EarlyStoppingCallback,
    EvalPlanCallback,
    FunctionCallback,
    MetricsLoggingCallback,
    TensorBoardCallback,
)
from .plans import (  # noqa: F401
    ArtifactPlan,
    CalibrationCurvePlan,
    ConfusionMatrixPlan,
    DEFAULT_CLASSIFICATION_PLANS,
    DEFAULT_REGRESSION_PLANS,
    FeatureImportancePlan,
    ResidualsPlan,
    ROCCurvePlan,
    produce_artifacts,
)
