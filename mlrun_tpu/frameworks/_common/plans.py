"""Evaluation artifact plans (reference analog:
mlrun/frameworks/_ml_common/plans/ — ConfusionMatrixPlan, ROCCurvePlan,
CalibrationCurvePlan, FeatureImportancePlan + the producer flow in
mlrun/frameworks/_common/, re-implemented compactly).

Each plan decides whether it applies to a (model, data) pair and produces
one artifact — an html plot (matplotlib, gated) or a dataset table — into
the run context. ``produce_artifacts`` is the producer: it runs every
applicable plan and tolerates individual failures.
"""

from __future__ import annotations

import tempfile
from typing import Any, Optional

from ...utils import logger


def _is_classifier(model, y_pred) -> bool:
    import numpy as np

    if hasattr(model, "predict_proba"):
        return True
    # integer/bool OR string/object labels mean classification
    return np.asarray(y_pred).reshape(-1).dtype.kind in "iubUOS"


def _save_figure(fig, key: str) -> str:
    path = tempfile.NamedTemporaryFile(
        suffix=f"-{key}.html", delete=False).name
    import base64
    import io

    buf = io.BytesIO()
    fig.savefig(buf, format="png", bbox_inches="tight", dpi=110)
    encoded = base64.b64encode(buf.getvalue()).decode()
    with open(path, "w") as fp:
        fp.write(f'<img src="data:image/png;base64,{encoded}"/>')
    import matplotlib.pyplot as plt

    plt.close(fig)
    return path


class ArtifactPlan:
    """One evaluation artifact: applicability test + production."""

    key = "artifact"

    def is_applicable(self, model, y, y_pred) -> bool:
        raise NotImplementedError

    def produce(self, context, model, x, y, y_pred):
        raise NotImplementedError

    def safe_produce(self, context, model, x, y, y_pred) -> bool:
        try:
            if not self.is_applicable(model, y, y_pred):
                return False
            self.produce(context, model, x, y, y_pred)
            return True
        except Exception as exc:  # noqa: BLE001 - one plan's failure must
            # not break the training run
            logger.warning("artifact plan failed", plan=self.key,
                           error=str(exc))
            return False


class ConfusionMatrixPlan(ArtifactPlan):
    key = "confusion_matrix"

    def is_applicable(self, model, y, y_pred):
        return _is_classifier(model, y_pred)

    def produce(self, context, model, x, y, y_pred):
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
        import numpy as np
        from sklearn.metrics import confusion_matrix

        labels = np.unique(np.concatenate(
            [np.asarray(y).reshape(-1), np.asarray(y_pred).reshape(-1)]))
        cm = confusion_matrix(y, y_pred, labels=labels)
        fig, ax = plt.subplots(figsize=(4, 4))
        im = ax.imshow(cm, cmap="Blues")
        ax.set_xticks(range(len(labels)), labels)
        ax.set_yticks(range(len(labels)), labels)
        ax.set_xlabel("predicted")
        ax.set_ylabel("actual")
        for i in range(cm.shape[0]):
            for j in range(cm.shape[1]):
                ax.text(j, i, str(cm[i, j]), ha="center", va="center")
        fig.colorbar(im, ax=ax, fraction=0.046)
        context.log_artifact(self.key, local_path=_save_figure(fig, self.key),
                             format="html")


class ROCCurvePlan(ArtifactPlan):
    key = "roc_curve"

    def is_applicable(self, model, y, y_pred):
        import numpy as np

        return (hasattr(model, "predict_proba")
                and len(np.unique(np.asarray(y).reshape(-1))) == 2)

    def produce(self, context, model, x, y, y_pred):
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
        from sklearn.metrics import auc, roc_curve

        scores = model.predict_proba(x)[:, 1]
        fpr, tpr, _ = roc_curve(y, scores)
        fig, ax = plt.subplots(figsize=(4, 4))
        ax.plot(fpr, tpr, label=f"AUC = {auc(fpr, tpr):.3f}")
        ax.plot([0, 1], [0, 1], "--", color="gray")
        ax.set_xlabel("false positive rate")
        ax.set_ylabel("true positive rate")
        ax.legend()
        context.log_artifact(self.key, local_path=_save_figure(fig, self.key),
                             format="html")
        context.log_result("auc", float(auc(fpr, tpr)))


class CalibrationCurvePlan(ArtifactPlan):
    key = "calibration_curve"

    def is_applicable(self, model, y, y_pred):
        import numpy as np

        return (hasattr(model, "predict_proba")
                and len(np.unique(np.asarray(y).reshape(-1))) == 2)

    def produce(self, context, model, x, y, y_pred):
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
        from sklearn.calibration import calibration_curve

        prob = model.predict_proba(x)[:, 1]
        frac_pos, mean_pred = calibration_curve(y, prob, n_bins=10)
        fig, ax = plt.subplots(figsize=(4, 4))
        ax.plot(mean_pred, frac_pos, marker="o")
        ax.plot([0, 1], [0, 1], "--", color="gray")
        ax.set_xlabel("mean predicted probability")
        ax.set_ylabel("fraction of positives")
        context.log_artifact(self.key, local_path=_save_figure(fig, self.key),
                             format="html")


class FeatureImportancePlan(ArtifactPlan):
    key = "feature_importance"

    def is_applicable(self, model, y, y_pred):
        return hasattr(model, "feature_importances_") or \
            hasattr(model, "coef_")

    def produce(self, context, model, x, y, y_pred):
        import numpy as np
        import pandas as pd

        if hasattr(model, "feature_importances_"):
            scores = np.asarray(model.feature_importances_)
        else:
            scores = np.abs(np.asarray(model.coef_))
            if scores.ndim > 1:
                scores = scores.mean(axis=0)
        names = list(getattr(x, "columns", range(len(scores))))
        table = pd.DataFrame({"feature": [str(n) for n in names],
                              "importance": scores})
        table = table.sort_values("importance", ascending=False)
        context.log_dataset(self.key, df=table, format="parquet")


class ResidualsPlan(ArtifactPlan):
    key = "residuals"

    def is_applicable(self, model, y, y_pred):
        return not _is_classifier(model, y_pred)

    def produce(self, context, model, x, y, y_pred):
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
        import numpy as np

        y = np.asarray(y).reshape(-1)
        y_pred = np.asarray(y_pred).reshape(-1)
        fig, ax = plt.subplots(figsize=(4, 4))
        ax.scatter(y_pred, y - y_pred, s=8, alpha=0.6)
        ax.axhline(0.0, color="gray", linestyle="--")
        ax.set_xlabel("predicted")
        ax.set_ylabel("residual")
        context.log_artifact(self.key, local_path=_save_figure(fig, self.key),
                             format="html")


DEFAULT_CLASSIFICATION_PLANS = (ConfusionMatrixPlan, ROCCurvePlan,
                                CalibrationCurvePlan, FeatureImportancePlan)
DEFAULT_REGRESSION_PLANS = (ResidualsPlan, FeatureImportancePlan)


def produce_artifacts(context, model, x, y, y_pred=None,
                      plans: Optional[list] = None,
                      key_suffix: str = "") -> list[str]:
    """Run every applicable plan; returns the keys that produced
    artifacts (the producer flow of the reference's _common package).
    ``key_suffix`` distinguishes repeated productions (e.g. the
    EvalPlanCallback's per-epoch runs: 'confusion-matrix-epoch3')."""
    if y_pred is None:
        y_pred = model.predict(x)
    if plans is None:
        classes = (DEFAULT_CLASSIFICATION_PLANS
                   if _is_classifier(model, y_pred)
                   else DEFAULT_REGRESSION_PLANS)
        plans = [cls() for cls in classes]
    produced = []
    for plan in plans:
        original_key = plan.key
        if key_suffix:
            plan.key = f"{original_key}{key_suffix}"
        try:
            if plan.safe_produce(context, model, x, y, y_pred):
                produced.append(plan.key)
        finally:
            plan.key = original_key
    return produced
