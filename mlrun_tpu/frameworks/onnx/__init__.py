"""ONNX adapter (reference analog: mlrun/frameworks/onnx/).

Gated on onnx/onnxruntime. On TPU deployments the preferred path is native
jax export (the model registry stores orbax/jax trees); onnx remains for
interop with external serving stacks.
"""

from __future__ import annotations


def to_onnx(model, context=None, model_name: str = "model", **kwargs):
    raise ImportError(
        "onnx export requires the onnx package (not in this environment); "
        "use the jax/orbax model registry path instead")


def ONNXModelServer(*args, **kwargs):
    try:
        import onnxruntime  # noqa: F401
    except ImportError as exc:
        raise ImportError(
            "onnxruntime is not installed in this environment") from exc
    from ...serving.v2_serving import V2ModelServer

    class _Server(V2ModelServer):
        def load(self):
            import onnxruntime as ort

            model_file, _ = self.get_model(".onnx")
            self.model = ort.InferenceSession(model_file)

        def predict(self, request):
            import numpy as np

            inputs = np.asarray(request["inputs"], dtype=np.float32)
            input_name = self.model.get_inputs()[0].name
            return self.model.run(None, {input_name: inputs})[0].tolist()

    return _Server(*args, **kwargs)
