"""ONNX adapter (reference analog: mlrun/frameworks/onnx/ — to_onnx model
conversion + ONNXModelServer).

Gated on the onnx/onnxruntime packages (not in the TPU base image). On TPU
deployments the preferred path is native jax export (the model registry
stores orbax/jax trees); onnx remains for interop with external serving
stacks.
"""

from __future__ import annotations

import os
import tempfile
from typing import Any


def to_onnx(model: Any, context=None, model_name: str = "model",
            sample_input=None, input_names: list | None = None,
            output_names: list | None = None, target_path: str = "",
            **export_kwargs) -> str:
    """Convert a torch module / sklearn estimator / keras model to ONNX and
    (when a context is given) register it in the artifact registry.

    Returns the exported file path. Requires the ``onnx`` package plus the
    family converter (torch bundles its exporter; sklearn needs skl2onnx,
    keras needs tf2onnx).
    """
    try:
        import onnx  # noqa: F401  - gated: the serializer every path needs
    except ImportError as exc:
        raise ImportError(
            "onnx export requires the onnx package; use the jax/orbax "
            "model registry path on TPU deployments") from exc

    path = target_path or os.path.join(tempfile.mkdtemp(prefix="mlt-onnx-"),
                                       f"{model_name}.onnx")

    exported = False
    try:
        import torch
    except ImportError:  # guard ONLY the import — export errors must
        torch = None     # surface, not fall through to other families

    if torch is not None and isinstance(model, torch.nn.Module):
        if sample_input is None:
            raise ValueError(
                "torch export needs sample_input (example args)")
        if not isinstance(sample_input, tuple):
            sample_input = (sample_input,)
        torch.onnx.export(
            model, sample_input, path,
            input_names=input_names, output_names=output_names,
            **export_kwargs)
        exported = True

    if not exported and _is_sklearn(model):
        from skl2onnx import to_onnx as skl_to_onnx  # gated import

        onx = skl_to_onnx(model, X=sample_input, **export_kwargs)
        with open(path, "wb") as fp:
            fp.write(onx.SerializeToString())
        exported = True

    if not exported and _is_keras(model):
        import tf2onnx  # gated import

        model_proto, _ = tf2onnx.convert.from_keras(model, **export_kwargs)
        with open(path, "wb") as fp:
            fp.write(model_proto.SerializeToString())
        exported = True

    if not exported:
        raise ValueError(
            f"no onnx converter for model type {type(model).__name__} "
            "(torch module, sklearn estimator, or keras model expected)")

    if context is not None:
        context.log_model(model_name, model_file=path, framework="onnx",
                          upload=True)
    return path


def _is_sklearn(model) -> bool:
    try:
        from sklearn.base import BaseEstimator

        return isinstance(model, BaseEstimator)
    except ImportError:
        return False


def _is_keras(model) -> bool:
    try:
        from tensorflow import keras

        return isinstance(model, keras.Model)
    except ImportError:
        return False


def ONNXModelServer(*args, **kwargs):
    try:
        import onnxruntime  # noqa: F401
    except ImportError as exc:
        raise ImportError(
            "onnxruntime is not installed in this environment") from exc
    from ...serving.v2_serving import V2ModelServer

    class _Server(V2ModelServer):
        def load(self):
            import onnxruntime as ort

            model_file, _ = self.get_model(".onnx")
            self.model = ort.InferenceSession(model_file)

        def predict(self, request):
            import numpy as np

            inputs = np.asarray(request["inputs"], dtype=np.float32)
            input_name = self.model.get_inputs()[0].name
            return self.model.run(None, {input_name: inputs})[0].tolist()

    return _Server(*args, **kwargs)
