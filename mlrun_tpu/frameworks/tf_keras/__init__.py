"""TF/Keras auto-logger (reference analog: mlrun/frameworks/tf_keras/
mlrun_interface.py — wraps compile/fit with logging callbacks :51-95; the
Horovod optimizer-wrap + rank-0 callback logic :212-220 is replaced by the
ctx-layer rank-0 gate, since TPU training in this framework is the JAX
path — keras here is for existing keras user code, CPU/host-side)."""

from __future__ import annotations

import os
import tempfile
from typing import Optional

from ...execution import MLClientCtx
from ...utils import logger


def apply_mlrun(model=None, context: MLClientCtx | None = None,
                model_name: str = "model", tag: str = "",
                x_test=None, y_test=None, log_model: bool = True,
                tensorboard: bool = False,
                tensorboard_weights: bool = False,
                callbacks: list | None = None, **kwargs):
    """Patch a keras model so fit() logs per-epoch metrics and the final
    model to the run context. ``tensorboard=True`` additionally writes
    tf.summary event files (scalars per epoch; weight histograms with
    ``tensorboard_weights=True``) and registers the log dir as an
    artifact (reference tf_keras/callbacks TensorboardLoggingCallback)."""
    if context is None:
        import mlrun_tpu

        context = mlrun_tpu.get_or_create_ctx("tf-keras")
    handler = KerasModelHandler(model, context, model_name, tag,
                                x_test=x_test, y_test=y_test,
                                log_model=log_model,
                                tensorboard=tensorboard,
                                tensorboard_weights=tensorboard_weights,
                                callbacks=callbacks)
    if model is not None:
        handler.patch()
    return handler


class _MLRunLoggingCallback:
    """Per-epoch metric logging callback (reference logging_callback)."""

    def __new__(cls, context, handler):
        from tensorflow import keras

        class _Callback(keras.callbacks.Callback):
            def on_epoch_end(self, epoch, logs=None):
                if logs and context.is_logging_worker():
                    context.log_metrics(
                        {k: float(v) for k, v in logs.items()}, step=epoch)

            def on_train_end(self, logs=None):
                handler._post_fit(logs)

        return _Callback()


class TensorboardLoggingCallback:
    """tf.summary writer callback (reference analog:
    mlrun/frameworks/tf_keras/callbacks/tensorboard_logging_callback.py —
    per-epoch scalar summaries + optional weight histograms into a run-
    scoped log dir that lands in the artifact registry)."""

    def __new__(cls, context, log_dir: str, weights: bool = False):
        import tensorflow as tf
        from tensorflow import keras

        writer = tf.summary.create_file_writer(log_dir)

        class _Callback(keras.callbacks.Callback):
            def on_epoch_end(self, epoch, logs=None):
                if not context.is_logging_worker():
                    return
                with writer.as_default(step=epoch):
                    for key, value in (logs or {}).items():
                        tf.summary.scalar(key, float(value))
                    if weights:
                        for weight in self.model.weights:
                            tf.summary.histogram(
                                weight.name.replace(":", "_"), weight)
                writer.flush()

            def on_train_end(self, logs=None):
                writer.close()

        return _Callback()


class _SharedCallbackBridge:
    """Translate the keras event stream into the framework-wide
    ``frameworks._common.Callback`` hooks, so one EarlyStopping /
    Checkpoint / TensorBoard / EvalPlan implementation serves keras too.
    A False vote from an epoch hook sets ``model.stop_training`` (the
    keras-native graceful stop)."""

    def __new__(cls, hooks, model):
        from tensorflow import keras

        class _Bridge(keras.callbacks.Callback):
            _global_step = 0  # keras batch indexes reset per epoch; the
            # shared hooks (checkpoint-every-N etc.) need a monotonic step

            def on_train_begin(self, logs=None):
                hooks.on_train_begin()

            def on_epoch_begin(self, epoch, logs=None):
                hooks.on_epoch_begin(epoch)

            def on_train_batch_end(self, batch, logs=None):
                metrics = {k: float(v) for k, v in (logs or {}).items()}
                if not hooks.on_step_end(self._global_step, metrics):
                    model.stop_training = True
                self._global_step += 1

            def on_epoch_end(self, epoch, logs=None):
                metrics = {k: float(v) for k, v in (logs or {}).items()}
                if not hooks.on_epoch_end(epoch, metrics):
                    model.stop_training = True

            def on_train_end(self, logs=None):
                hooks.on_train_end(
                    {k: float(v) for k, v in (logs or {}).items()})

        return _Bridge()


class KerasModelHandler:
    def __init__(self, model, context, model_name="model", tag="",
                 x_test=None, y_test=None, log_model=True,
                 tensorboard=False, tensorboard_weights=False,
                 callbacks=None):
        self.model = model
        self.context = context
        self.model_name = model_name
        self.tag = tag
        self.x_test = x_test
        self.y_test = y_test
        self._log_model = log_model
        self._tensorboard = tensorboard
        self._tensorboard_weights = tensorboard_weights
        self._shared_callbacks = callbacks
        self._tb_dir: str | None = None
        self._patched = False

    def patch(self):
        if self._patched:
            return self.model
        original_fit = self.model.fit
        handler = self

        def wrapped_fit(*args, **kwargs):
            callbacks = list(kwargs.get("callbacks") or [])
            callbacks.append(_MLRunLoggingCallback(handler.context, handler))
            if handler._shared_callbacks:
                from .._common.callbacks import CallbackList

                hooks = CallbackList(handler._shared_callbacks,
                                     context=handler.context,
                                     model=handler.model)
                callbacks.append(
                    _SharedCallbackBridge(hooks, handler.model))
            if handler._tensorboard:
                handler._tb_dir = os.path.join(
                    tempfile.mkdtemp(prefix="mlt-tb-"), "train")
                callbacks.append(TensorboardLoggingCallback(
                    handler.context, handler._tb_dir,
                    weights=handler._tensorboard_weights))
            kwargs["callbacks"] = callbacks
            return original_fit(*args, **kwargs)

        self.model.fit = wrapped_fit
        self._patched = True
        return self.model

    def _post_fit(self, logs=None):
        metrics = {k: float(v) for k, v in (logs or {}).items()}
        if self.x_test is not None and self.y_test is not None:
            try:
                evaluation = self.model.evaluate(
                    self.x_test, self.y_test, verbose=0, return_dict=True)
                metrics.update(
                    {f"test_{k}": float(v) for k, v in evaluation.items()})
            except Exception as exc:  # noqa: BLE001
                logger.warning("keras evaluation failed", error=str(exc))
        if metrics:
            self.context.log_results(metrics)
        if self._tb_dir and os.path.isdir(self._tb_dir):
            try:
                self.context.log_artifact(
                    f"{self.model_name}-tensorboard",
                    local_path=self._tb_dir)
            except Exception as exc:  # noqa: BLE001 - tb dir best-effort
                logger.warning("tensorboard artifact failed",
                               error=str(exc))
        if self._log_model:
            self.log_model(metrics)

    def log_model(self, metrics: dict | None = None):
        tmp_dir = tempfile.mkdtemp()
        path = os.path.join(tmp_dir, f"{self.model_name}.keras")
        self.model.save(path)
        return self.context.log_model(
            self.model_name, model_file=path, framework="tf.keras",
            metrics=metrics or {}, tag=self.tag)


class TFKerasModelServer:
    """V2ModelServer for saved keras models."""

    def __new__(cls, *args, **kwargs):
        from ...serving.v2_serving import V2ModelServer

        class _Server(V2ModelServer):
            def load(self):
                from tensorflow import keras

                model_file, _ = self.get_model(".keras")
                self.model = keras.models.load_model(model_file)

            def predict(self, request):
                import numpy as np

                inputs = np.asarray(request["inputs"])
                return self.model.predict(inputs, verbose=0).tolist()

        return _Server(*args, **kwargs)
