from .auto_trainer import JaxTrainerInterface, apply_mlrun, train  # noqa: F401
