"""JAX auto-trainer — the TPU-native replacement for the reference's
PyTorch+Horovod trainer (mlrun/frameworks/pytorch/__init__.py:46 ``train``,
mlrun_interface.py:106 training loop, :561-566 hvd, :849 allreduce).

``train(...)`` runs a sharded fine-tune of a Llama-family model inside a run
context: builds the mesh from config/runtime spec, streams data, logs
per-step metrics + final MFU, checkpoints via orbax, and registers the model
(adapters or full weights) in the artifact registry — rank-0-only through the
ctx layer.
"""

from __future__ import annotations

import os
import time
from typing import Any, Callable, Iterator, Optional

from ...config import mlconf
from ...execution import MLClientCtx
from ...models import llama as llama_mod
from ...models.llama import LlamaConfig
from ...utils import logger

MODEL_PRESETS = {
    "llama3-8b": llama_mod.llama3_8b,
    "llama3-70b": llama_mod.llama3_70b,
    "llama3-1b": llama_mod.llama3_1b,
    "tiny": llama_mod.tiny_llama,
}


def apply_mlrun(model=None, context: MLClientCtx | None = None,
                model_name: str = "model", tag: str = "", **kwargs):
    """Wrap a (model_config, params) pair with context logging hooks."""
    return JaxTrainerInterface(model=model, context=context,
                               model_name=model_name, tag=tag, **kwargs)


class JaxTrainerInterface:
    """Lifecycle hooks around a training loop (metric logging + model
    registration), the `MLRunInterface` analog for JAX."""

    def __init__(self, model=None, context=None, model_name="model", tag="",
                 **kwargs):
        self.model = model
        self.context = context
        self.model_name = model_name
        self.tag = tag
        self._extra = kwargs

    def log_metrics(self, metrics: dict, step: int | None = None):
        if self.context is not None:
            self.context.log_metrics(metrics, step=step)

    def log_model(self, checkpoint_dir: str = "", metrics: dict | None = None,
                  parameters: dict | None = None, framework: str = "jax"):
        if self.context is None:
            return None
        return self.context.log_model(
            self.model_name, model_dir=checkpoint_dir or None,
            framework=framework, metrics=metrics, parameters=parameters,
            upload=False, target_path=checkpoint_dir or None, tag=self.tag)


def _resolve_model_config(model: str | LlamaConfig | dict,
                          overrides: dict | None = None) -> LlamaConfig:
    import dataclasses

    if isinstance(model, LlamaConfig):
        config = model
    elif isinstance(model, dict):
        config = LlamaConfig(**model)
    elif isinstance(model, str):
        preset = MODEL_PRESETS.get(model)
        if preset is None:
            raise ValueError(
                f"unknown model preset '{model}' "
                f"(have {sorted(MODEL_PRESETS)})")
        config = preset()
    else:
        raise ValueError(f"unsupported model spec {model!r}")
    if overrides:
        config = dataclasses.replace(config, **overrides)
    return config


def _make_stream(dataset: str | None, tokenizer: str | None, batch_size: int,
                 seq_len: int, vocab_size: int, seed: int) -> Iterator:
    """Resolve a dataset url (tokens .npy or text) into an LM batch stream;
    synthetic stream when no dataset is given."""
    from ...training import synthetic_token_stream
    from ...training.data import array_token_stream, text_file_stream

    if not dataset:
        return synthetic_token_stream(batch_size, seq_len, vocab_size,
                                      seed=seed)
    import numpy as np

    from ...datastore import store_manager

    local = store_manager.object(url=dataset).local()
    if local.endswith(".npy"):
        return array_token_stream(np.load(local), batch_size, seq_len,
                                  seed=seed)
    if not tokenizer:
        raise ValueError("text datasets need a tokenizer= id")
    from transformers import AutoTokenizer

    tok = AutoTokenizer.from_pretrained(tokenizer)
    return text_file_stream(local, tok, batch_size, seq_len, seed=seed)


def train(context: MLClientCtx | None = None,
          model: str | LlamaConfig | dict = "tiny",
          model_overrides: dict | None = None,
          dataset: str | None = None,
          tokenizer: str | None = None,
          batch_size: int = 8,
          seq_len: int = 512,
          steps: int = 100,
          learning_rate: float = 2e-4,
          lora_rank: int = 0,
          lora_alpha: float = 32.0,
          grad_accum: int = 1,
          mesh_shape: dict | None = None,
          context_parallel: str | None = None,
          seq_axis: str | None = None,
          pipeline_stages: int = 0,
          pipeline_microbatches: int = 0,
          moe_experts: int = 0,
          moe_top_k: int = 2,
          moe_capacity_factor: float = 1.25,
          checkpoint_dir: str = "",
          checkpoint_every: int = 0,
          resume: bool = True,
          epoch_steps: int = 0,
          early_stop: dict | None = None,
          tensorboard: bool = False,
          callbacks: list | None = None,
          model_name: str = "model",
          log_every: int = 10,
          seed: int = 0,
          prefetch: int | None = None,
          warmup: bool = True) -> dict:
    """Run a (LoRA) fine-tune end-to-end inside a run context.

    This is the handler the ``tpujob`` runtime executes on every host of the
    pod-slice (SPMD): same code everywhere, jax.distributed handles the rest.
    """
    import jax

    from ...parallel.mesh import initialize_distributed, make_mesh
    from ...training import (
        CheckpointManager,
        TrainConfig,
        Trainer,
        synthetic_token_stream,
    )
    from ...training.data import array_token_stream

    initialize_distributed()

    model_config = _resolve_model_config(model, model_overrides)
    if context_parallel and not mesh_shape:
        # long-context default: all chips on the sequence axis
        mesh_shape = {seq_axis or "seq": jax.device_count()}
    if pipeline_stages and not mesh_shape:
        # pipeline default: stages on 'pipe', the rest on 'data'
        n = jax.device_count()
        if n % pipeline_stages:
            raise ValueError(
                f"pipeline_stages={pipeline_stages} does not divide "
                f"{n} devices; pass mesh_shape explicitly")
        mesh_shape = {"data": n // pipeline_stages,
                      "pipe": pipeline_stages}
    if moe_experts and not mesh_shape:
        # expert default: as much of the expert dim on 'expert' as the
        # chip count divides, the rest on 'fsdp'
        import math

        n = jax.device_count()
        e = math.gcd(moe_experts, n)
        mesh_shape = {"expert": e, "fsdp": n // e}
    train_config = TrainConfig(
        learning_rate=learning_rate, total_steps=steps, lora_rank=lora_rank,
        lora_alpha=lora_alpha, grad_accum=grad_accum, mesh_shape=mesh_shape,
        context_parallel=context_parallel,
        seq_axis=seq_axis or ("seq" if context_parallel else None),
        pipeline_stages=pipeline_stages,
        pipeline_microbatches=pipeline_microbatches,
        moe_experts=moe_experts, moe_top_k=moe_top_k,
        moe_capacity_factor=moe_capacity_factor)
    mesh = make_mesh(mesh_shape)
    trainer = Trainer(model_config, train_config, mesh=mesh)
    trainer.init(seed)

    stream = _make_stream(dataset, tokenizer, batch_size, seq_len,
                          model_config.vocab_size, seed)

    # checkpointing
    manager = None
    if checkpoint_dir or checkpoint_every:
        checkpoint_dir = checkpoint_dir or os.path.join(
            (context.artifact_path if context else mlconf.home_dir),
            "checkpoints", model_name)
        manager = CheckpointManager(checkpoint_dir)
        if resume and manager.latest_step() is not None:
            trainer.state = manager.restore(trainer.state)
            logger.info("resumed from checkpoint",
                        step=int(trainer.state.step))

    from .._common.callbacks import (
        CheckpointCallback,
        EarlyStoppingCallback,
        TensorBoardCallback,
    )

    callbacks = list(callbacks or [])
    if manager is not None and checkpoint_every:
        callbacks.append(CheckpointCallback(manager,
                                            every_steps=checkpoint_every))
    if early_stop:
        # e.g. early_stop={"monitor": "loss", "patience": 3} — JSON-able
        # so it works as a run parameter through the handler contract
        callbacks.append(EarlyStoppingCallback(**early_stop))
    if tensorboard:
        callbacks.append(TensorBoardCallback(
            name=f"{model_name}-tensorboard"))

    interface = apply_mlrun(context=context, model_name=model_name)
    # SIGTERM (spot-slice eviction) → final checkpoint + clean resumable
    # exit instead of a killed run (training/preemption.py)
    from ...training.preemption import PreemptionGuard

    if warmup:
        # AOT-compile the step before the loop: compile time lands in
        # compile_seconds (kept out of steady-state MFU), and with
        # mlconf.training.compile_cache_dir set — threaded into
        # resubmitted JobSets by the service — a preemption-resume
        # restart skips XLA entirely (docs/training_performance.md)
        try:
            warm = trainer.warmup(batch_size, seq_len)
        except Exception as exc:  # noqa: BLE001 - a warmup failure must
            # degrade to a first-step compile, not kill the run
            logger.warning("warmup failed — compiling on first step",
                           error=str(exc))
        else:
            if context is not None and warm.get("compile_seconds"):
                context.log_result("compile_seconds",
                                   warm["compile_seconds"])

    guard = PreemptionGuard().install()
    start = time.perf_counter()
    try:
        final_metrics = trainer.fit(
            stream, steps=steps, context=context, log_every=log_every,
            callbacks=callbacks, checkpoint_manager=manager,
            preemption_guard=guard, epoch_steps=epoch_steps,
            prefetch=prefetch)
    finally:
        guard.restore()
    elapsed = time.perf_counter() - start

    final_metrics = {k: (v if isinstance(v, bool) else float(v))
                     for k, v in final_metrics.items()}
    final_metrics["train_time_s"] = elapsed
    if context is not None:
        context.log_results(final_metrics)

    if manager is not None:
        manager.save(int(trainer.state.step), trainer.state, force=True)
        manager.wait()
        interface.log_model(
            checkpoint_dir=manager.directory, metrics={
                "loss": final_metrics.get("loss"),
                "mfu": final_metrics.get("mfu"),
            },
            parameters={
                "model": str(model), "lora_rank": lora_rank,
                "steps": steps, "seq_len": seq_len,
            })
        manager.close()
    return final_metrics


def evaluate(context: MLClientCtx | None = None,
             model: str | LlamaConfig | dict = "tiny",
             model_overrides: dict | None = None,
             checkpoint_dir: str = "", dataset: str | None = None,
             tokenizer: str | None = None,
             batch_size: int = 8, seq_len: int = 512, steps: int = 10,
             mesh_shape: dict | None = None, seed: int = 0) -> dict:
    """Eval loop: average loss/accuracy over ``steps`` batches
    (reference analog: frameworks/pytorch/__init__.py:212 evaluate)."""
    import jax
    import jax.numpy as jnp

    from ...parallel.mesh import make_mesh
    from ...parallel.sharding import batch_sharding, tree_shardings

    model_config = _resolve_model_config(model, model_overrides)
    mesh = make_mesh(mesh_shape)
    params_shapes = llama_mod.param_shapes(model_config)
    shardings = tree_shardings(params_shapes, mesh)

    if checkpoint_dir:
        from ...training import CheckpointManager

        manager = CheckpointManager(checkpoint_dir)
        import functools

        init = jax.jit(functools.partial(llama_mod.init_params, model_config),
                       out_shardings=shardings)
        params = init(jax.random.PRNGKey(seed))
        restored = manager.restore({"params": params,
                                    "opt_state": None, "step": 0})
        params = restored["params"]
    else:
        import functools

        init = jax.jit(functools.partial(llama_mod.init_params, model_config),
                       out_shardings=shardings)
        params = init(jax.random.PRNGKey(seed))

    data_sh = batch_sharding(mesh)
    eval_step = jax.jit(
        lambda p, t, g: llama_mod.loss_fn(model_config, p, t, g)[1],
        in_shardings=(shardings, data_sh, data_sh))

    stream = _make_stream(dataset, tokenizer, batch_size, seq_len,
                          model_config.vocab_size, seed)
    totals: dict[str, float] = {}
    for _ in range(steps):
        tokens, targets = next(stream)
        metrics = eval_step(params, jax.device_put(tokens, data_sh),
                            jax.device_put(targets, data_sh))
        for key, value in metrics.items():
            totals[key] = totals.get(key, 0.0) + float(value)
    results = {f"eval_{k}": v / steps for k, v in totals.items()}
    if context is not None:
        context.log_results(results)
    return results
