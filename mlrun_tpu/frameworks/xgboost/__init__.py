"""XGBoost MLRun interface (reference analog: mlrun/frameworks/xgboost/ —
its own MLRunInterface rather than a pass-through to sklearn).

Two integration levels:

- sklearn-API estimators (``XGBClassifier``/``XGBRegressor``): the sklearn
  fit-patch carries the metric logging, and an xgboost-specific post-fit
  hook adds the feature-importance artifact.
- native ``xgboost.train`` Booster workflows: ``MLRunLoggingCallback``
  implements the xgboost callback contract (``after_iteration``) to log
  per-iteration eval results, and ``log_booster`` logs the trained booster
  with gain/weight importances.

Everything operates duck-typed on the booster object so the logic is
testable without the xgboost package; only ``apply_mlrun`` on a real
estimator requires the import.
"""

from __future__ import annotations

from .._common.boosters import (
    estimator_importance_scores,
    log_booster_model,
    log_importance_artifact,
    wrap_post_fit,
)

try:  # real xgboost requires callbacks to subclass TrainingCallback
    from xgboost.callback import TrainingCallback as _CallbackBase
except ImportError:
    class _CallbackBase:  # duck-typed stand-in when xgboost is absent
        pass


def _importance_artifact(context, booster, model_name: str) -> dict:
    """Log per-feature importance scores (gain + weight for boosters,
    ``feature_importances_`` for sklearn-API estimators) as a json
    artifact; returns the scores dict."""
    scores: dict = {}
    get_score = getattr(booster, "get_score", None)
    if get_score is None:  # sklearn-API estimator
        scores = estimator_importance_scores(booster)
    else:
        for importance_type in ("gain", "weight"):
            try:
                scores[importance_type] = {
                    k: float(v)
                    for k, v in get_score(
                        importance_type=importance_type).items()}
            except Exception:  # noqa: BLE001 - not all boosters score both
                continue
    log_importance_artifact(context, model_name, scores, "xgboost")
    return scores


class MLRunLoggingCallback(_CallbackBase):
    """xgboost training callback: logs eval metrics per iteration and the
    final values as results (xgboost invokes
    ``after_iteration(model, epoch, evals_log)`` each boosting round)."""

    def __init__(self, context, log_every: int = 10):
        self.context = context
        self.log_every = max(1, log_every)
        self.evals_log: dict = {}

    def before_training(self, model):
        return model

    def after_training(self, model):
        for data_name, metrics in self.evals_log.items():
            for metric_name, history in metrics.items():
                if history:
                    self.context.log_result(
                        f"{data_name}-{metric_name}", float(history[-1]))
        return model

    def after_iteration(self, model, epoch: int, evals_log: dict) -> bool:
        self.evals_log = evals_log
        if epoch % self.log_every == 0:
            for data_name, metrics in evals_log.items():
                for metric_name, history in metrics.items():
                    if history:
                        self.context.log_metrics(
                            {f"{data_name}-{metric_name}":
                             float(history[-1])}, step=epoch)
        return False  # never request early stop


def log_booster(context, booster, model_name: str = "model",
                tag: str = "", metrics: dict | None = None,
                label_column: str | None = None):
    """Log a trained booster (native ``xgboost.train`` path) as a model
    artifact with importance scores."""
    _importance_artifact(context, booster, model_name)
    return log_booster_model(
        context, booster, "xgboost", ".json", model_name=model_name,
        tag=tag, metrics=metrics, label_column=label_column)


def apply_mlrun(model=None, context=None, model_name: str = "model",
                tag: str = "", **kwargs):
    """Auto-log an sklearn-API xgboost estimator: metrics via the sklearn
    fit patch, plus the xgboost feature-importance artifact post-fit."""
    try:
        import xgboost  # noqa: F401
    except ImportError as exc:
        raise ImportError(
            "xgboost is not installed in this environment") from exc
    from ..sklearn import apply_mlrun as sklearn_apply

    handler = sklearn_apply(model=model, context=context,
                            model_name=model_name, tag=tag, **kwargs)
    return wrap_post_fit(handler, _importance_artifact)


def XGBoostModelServer(*args, **kwargs):
    from ..sklearn import SKLearnModelServer

    return SKLearnModelServer(*args, **kwargs)
