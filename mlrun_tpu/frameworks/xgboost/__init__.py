"""XGBoost auto-logger (reference analog: mlrun/frameworks/xgboost/).

xgboost follows the sklearn estimator API, so the sklearn handler carries the
logging; this module exists for API parity and gates on the library.
"""

from __future__ import annotations


def apply_mlrun(model=None, context=None, model_name: str = "model",
                tag: str = "", **kwargs):
    try:
        import xgboost  # noqa: F401
    except ImportError as exc:
        raise ImportError(
            "xgboost is not installed in this environment") from exc
    from ..sklearn import apply_mlrun as sklearn_apply

    handler = sklearn_apply(model=model, context=context,
                            model_name=model_name, tag=tag, **kwargs)
    return handler


def XGBoostModelServer(*args, **kwargs):
    from ..sklearn import SKLearnModelServer

    return SKLearnModelServer(*args, **kwargs)
