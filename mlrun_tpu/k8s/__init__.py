from .jobset import build_jobset, parse_topology  # noqa: F401
