from .jobset import (  # noqa: F401
    TopologyError,
    build_jobset,
    parse_topology,
)
