"""GKE JobSet spec builder for TPU pod-slices.

This replaces the reference's MPIJob CRD generation
(server/api/runtime_handlers/mpijob/v1.py:49 `_generate_mpi_job`,
:198-217 `apiVersion kubeflow.org/v1`): instead of a launcher pod running
``mpirun`` plus worker pods, a TPU run is a **JobSet** (jobset.x-k8s.io) of
``num_slices`` replicated indexed Jobs — one Job per TPU slice, one pod per
TPU host — where every pod runs the *same* SPMD program and JAX initializes
the collective runtime from the GKE-injected TPU environment (no launcher,
no ssh). Rank-0-only logging is enforced in the ctx layer
(mlrun_tpu/execution.py is_logging_worker).
"""

from __future__ import annotations

import math

from ..common.runtimes_constants import COMPILE_CACHE_ENV
from ..config import mlconf

JOBSET_API_VERSION = "jobset.x-k8s.io/v1alpha2"

# marks a JobSet as a SERVING pod-slice (serving/podfleet.py): the fake
# cluster auto-materializes its pods on create, and the pod fleet's
# lifecycle (prewarm -> readyz -> ring join -> drain -> delete) applies
SERVING_ANNOTATION = "mlrun-tpu/serving"


class TopologyError(ValueError):
    """Invalid TPU topology / host-geometry declaration (zero, negative
    or non-integer dims; non-positive chips_per_host). Typed so callers
    can catch the *declaration* error specifically — and raised at build
    time instead of letting a bad geometry silently produce a 0-host
    JobSet the cluster would park forever."""


def parse_topology(topology: str) -> tuple[int, ...]:
    """'2x4' -> (2, 4); '4x4x4' -> (4, 4, 4).

    Rejects empty, non-integer ('2.5x4', '2x', 'ax4') and
    zero/negative dims with a :class:`TopologyError`."""
    try:
        dims = tuple(int(d) for d in str(topology).lower().split("x"))
    except (ValueError, AttributeError) as exc:
        raise TopologyError(f"bad TPU topology '{topology}'") from exc
    if not dims or any(d <= 0 for d in dims):
        raise TopologyError(
            f"bad TPU topology '{topology}': dims must be positive "
            "integers")
    return dims


def chips_in_topology(topology: str) -> int:
    out = 1
    for dim in parse_topology(topology):
        out *= dim
    return out


def hosts_for_topology(topology: str, chips_per_host: int | None = None) -> int:
    # None means "use the config default"; an explicit 0 must NOT fall
    # back silently — it is exactly the bad declaration this validates
    if chips_per_host is None:
        chips_per_host = mlconf.tpu.chips_per_host
    try:
        chips_per_host = int(chips_per_host)
    except (TypeError, ValueError) as exc:
        raise TopologyError(
            f"bad chips_per_host '{chips_per_host}'") from exc
    if chips_per_host <= 0:
        raise TopologyError(
            f"chips_per_host must be positive, got {chips_per_host}")
    return max(1, math.ceil(chips_in_topology(topology) / chips_per_host))


def build_jobset(name: str, namespace: str, pod_spec: dict, *,
                 accelerator: str, topology: str, num_slices: int = 1,
                 chips_per_host: int | None = None, max_restarts: int = 0,
                 labels: dict | None = None, annotations: dict | None = None,
                 suspend: bool = False, elastic: bool = False) -> dict:
    """Build the JobSet dict for a TPU run.

    One replicated Job named 'slice' with ``num_slices`` replicas; each Job is
    Indexed with parallelism=completions=hosts-per-slice; every pod requests
    ``chips_per_host`` TPU chips and carries the GKE TPU node selectors. For
    multi-slice (num_slices>1) the MEGASCALE coordinator env is injected so
    XLA runs DCN collectives across slices.

    ``elastic`` marks a multi-slice run that survives losing a slice
    (docs/fault_tolerance.md "Elastic training"): the
    ``mlrun-tpu/elastic`` annotation declares the intent, and the
    failurePolicy restart budget is floored at ``num_slices`` so a
    single child-Job failure cannot fail the whole JobSet before the
    service's slice-replacement path (``TpuJobHandler._check_slices``)
    reacts.
    """
    # None = config default; an explicit 0 must reach the validation in
    # hosts_for_topology instead of silently becoming the default
    if chips_per_host is None:
        chips_per_host = mlconf.tpu.chips_per_host
    hosts = hosts_for_topology(topology, chips_per_host)
    labels = dict(labels or {})
    labels.setdefault("app.kubernetes.io/managed-by", "mlrun-tpu")
    annotations = dict(annotations or {})
    if elastic:
        annotations["mlrun-tpu/elastic"] = "true"
        max_restarts = max(int(max_restarts), int(num_slices))

    pod_spec = dict(pod_spec)
    pod_spec["subdomain"] = name  # headless service for host discovery
    node_selector = pod_spec.setdefault("nodeSelector", {})
    node_selector[mlconf.tpu.accelerator_node_selector] = accelerator
    node_selector[mlconf.tpu.topology_node_selector] = topology

    containers = pod_spec.get("containers", [])
    if containers:
        main = containers[0]
        limits = main.setdefault("resources", {}).setdefault("limits", {})
        limits[mlconf.tpu.resource_name] = chips_per_host
        ports = main.setdefault("ports", [])
        ports.append({"containerPort": mlconf.tpu.coordinator_port,
                      "name": "coordinator"})
        env = main.setdefault("env", [])
        if num_slices > 1:
            env.extend([
                {"name": "MEGASCALE_NUM_SLICES", "value": str(num_slices)},
                {
                    "name": "MEGASCALE_SLICE_ID",
                    "valueFrom": {"fieldRef": {"fieldPath": (
                        "metadata.annotations"
                        "['jobset.sigs.k8s.io/job-index']")}},
                },
                {"name": "MEGASCALE_COORDINATOR_ADDRESS",
                 "value": f"{name}-slice-0-0.{name}"},
            ])
        # worker identity for rank-0-only logging before jax init
        env.append({
            "name": "TPU_WORKER_ID",
            "valueFrom": {"fieldRef": {"fieldPath": (
                "metadata.annotations"
                "['batch.kubernetes.io/job-completion-index']")}},
        })

    job_template = {
        "spec": {
            "parallelism": hosts,
            "completions": hosts,
            "backoffLimit": 0,
            "completionMode": "Indexed",
            "template": {
                "metadata": {"labels": labels},
                "spec": pod_spec,
            },
        }
    }

    return {
        "apiVersion": JOBSET_API_VERSION,
        "kind": "JobSet",
        "metadata": {
            "name": name,
            "namespace": namespace,
            "labels": labels,
            "annotations": annotations,
        },
        "spec": {
            "suspend": suspend,
            "failurePolicy": {"maxRestarts": max_restarts},
            "replicatedJobs": [
                {"name": "slice", "replicas": num_slices,
                 "template": job_template}
            ],
        },
    }


def build_serving_jobset(name: str, namespace: str, pod_spec: dict, *,
                         accelerator: str, topology: str,
                         chips_per_host: int | None = None,
                         compile_cache_dir: str | None = None,
                         serve_port: int = 8080,
                         labels: dict | None = None,
                         annotations: dict | None = None) -> dict:
    """Build the JobSet for ONE serving pod-slice (serving/podfleet.py).

    A serving replica is a single-slice JobSet (one engine per
    pod-slice, scaled by submitting/deleting whole JobSets — the
    autoscaler's unit of elasticity), differing from a training JobSet
    in its lifecycle contract:

    - ``SERVING_ANNOTATION`` marks it for the pod fleet's state machine
      (and the fake cluster's pod auto-materialization in tests);
    - the readiness probe hits ``/readyz``, which gates on WARMTH
      (engine warmup + adapter prefetch done — serving/server.py), so
      k8s never routes to a cold pod and the ring join waits for it;
    - a ``preStop`` hook POSTs ``/__drain__`` so an eviction runs the
      graceful drain (in-flight requests finish or re-dispatch) before
      the kubelet sends SIGTERM;
    - ``compile_cache_dir`` rides in as ``COMPILE_CACHE_ENV`` so the
      replacement pod loads its executables from the shared cache
      instead of recompiling (the PR 5 warm-start path).
    """
    annotations = dict(annotations or {})
    annotations[SERVING_ANNOTATION] = "true"
    spec = build_jobset(name, namespace, pod_spec,
                        accelerator=accelerator, topology=topology,
                        num_slices=1, chips_per_host=chips_per_host,
                        labels=labels, annotations=annotations)
    pod = (spec["spec"]["replicatedJobs"][0]["template"]["spec"]
           ["template"]["spec"])
    containers = pod.get("containers", [])
    if containers:
        main = containers[0]
        if compile_cache_dir:
            env = main.setdefault("env", [])
            env.append({"name": COMPILE_CACHE_ENV,
                        "value": str(compile_cache_dir)})
        main["readinessProbe"] = {
            "httpGet": {"path": "/readyz", "port": serve_port},
            "periodSeconds": 2,
            "failureThreshold": 3,
        }
        main.setdefault("lifecycle", {})["preStop"] = {
            "httpGet": {"path": "/__drain__", "port": serve_port},
        }
    return spec
