"""mlrun-tpu — a TPU-native MLOps orchestration framework.

Re-creation of the capabilities of mlrun/mlrun (reference mounted at
/root/reference) designed for Cloud TPU: a ``tpujob`` runtime over GKE JobSet
pod-slices instead of MPIJob/Horovod/NCCL, a JAX/Flax auto-trainer sharded
with pjit/shard_map over ICI/DCN meshes, XLA-compiled serving steps, and an
aiohttp+SQLite metadata service.

Reference analog for this module: /root/reference/mlrun/__init__.py
(set_environment :90, set_env_from_file :187).
"""

__version__ = "0.1.0"

from .config import mlconf  # noqa: F401
from .datastore import DataItem, store_manager  # noqa: F401
from .db import get_run_db  # noqa: F401
from .execution import MLClientCtx  # noqa: F401
from .model import (  # noqa: F401
    HyperParamOptions,
    Notification,
    RunObject,
    RunTemplate,
    new_task,
)
from .run import (  # noqa: F401
    code_to_function,
    function_to_module,
    get_or_create_ctx,
    import_function,
    new_function,
    run_local,
    wait_for_pipeline_completion,
)

import os as _os


def set_environment(api_path: str | None = None, artifact_path: str = "",
                    project: str = "", access_key: str | None = None,
                    username: str | None = None, env_file: str | None = None,
                    mock_functions: str | None = None):
    """Set global api/artifact config (reference mlrun/__init__.py:90)."""
    if env_file:
        set_env_from_file(env_file)
    if api_path:
        mlconf.dbpath = api_path
        _os.environ["MLT_DBPATH"] = api_path
    if artifact_path:
        mlconf.artifact_path = artifact_path
    if project:
        mlconf.default_project = project
    if access_key:
        _os.environ["MLT_ACCESS_KEY"] = access_key
    return mlconf.default_project, mlconf.get("artifact_path") or None


def set_env_from_file(env_file: str, return_dict: bool = False):
    """Load KEY=VALUE lines into the environment (reference :187)."""
    env_vars = {}
    with open(_os.path.expanduser(env_file)) as fp:
        for line in fp:
            line = line.strip()
            if line and not line.startswith("#") and "=" in line:
                key, value = line.split("=", 1)
                env_vars[key.strip()] = value.strip()
    for key, value in env_vars.items():
        _os.environ[key] = value
    mlconf.reload()
    if return_dict:
        return env_vars


def get_version() -> str:
    return __version__


# projects API is imported lazily to avoid heavy import cost at package load;
# these are re-exported here for parity with the reference's top-level API
def new_project(*args, **kwargs):
    from .projects import new_project as _new_project

    return _new_project(*args, **kwargs)


def load_project(*args, **kwargs):
    from .projects import load_project as _load_project

    return _load_project(*args, **kwargs)


def get_or_create_project(*args, **kwargs):
    from .projects import get_or_create_project as _get_or_create_project

    return _get_or_create_project(*args, **kwargs)


def get_current_project(silent: bool = False):
    from .projects import get_current_project as _get_current_project

    return _get_current_project(silent)


def handler(labels: dict | None = None, outputs: list | None = None,
            inputs: bool = True):
    """Decorator marking a function as an mlrun-tpu handler with packaging
    hints (reference mlrun/handler decorator)."""

    def decorator(func):
        setattr(func, "_mlt_handler", {
            "labels": labels, "outputs": outputs, "inputs": inputs})
        return func

    return decorator
