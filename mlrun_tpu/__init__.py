"""mlrun-tpu — a TPU-native MLOps orchestration framework.

Re-creation of the capabilities of mlrun/mlrun (reference mounted at
/root/reference) designed for Cloud TPU: a ``tpujob`` runtime over GKE JobSet
pod-slices instead of MPIJob/Horovod/NCCL, a JAX/Flax auto-trainer sharded
with pjit/shard_map over ICI/DCN meshes, XLA-compiled serving steps, and an
aiohttp+SQLite metadata service.

Reference analog for this module: /root/reference/mlrun/__init__.py
(set_environment :90, set_env_from_file :187).
"""

__version__ = "0.1.0"

import os

environ = os.environ  # reference re-exports os.environ at top level

from .config import mlconf  # noqa: F401
from .datastore import DataItem, store_manager  # noqa: F401
from .db import get_run_db  # noqa: F401
from .errors import (  # noqa: F401
    MLRunBaseError,
    MLRunConflictError,
    MLRunInvalidArgumentError,
    MLRunNotFoundError,
    MLRunRuntimeError,
    MLRunTimeoutError,
)
from .execution import MLClientCtx  # noqa: F401
from .platforms import auto_mount, mount_pvc  # noqa: F401
from .secrets import get_secret_or_env  # noqa: F401
from .model import (  # noqa: F401
    HyperParamOptions,
    Notification,
    RunObject,
    RunTemplate,
    new_task,
)
from .run import (  # noqa: F401
    code_to_function,
    function_to_module,
    get_or_create_ctx,
    import_function,
    new_function,
    run_local,
    wait_for_pipeline_completion,
)

_os = os  # single os import; legacy alias kept for the helpers below


def set_environment(api_path: str | None = None, artifact_path: str = "",
                    project: str = "", access_key: str | None = None,
                    username: str | None = None, env_file: str | None = None,
                    mock_functions: str | None = None):
    """Set global api/artifact config (reference mlrun/__init__.py:90)."""
    if env_file:
        set_env_from_file(env_file)
    if api_path:
        mlconf.dbpath = api_path
        _os.environ["MLT_DBPATH"] = api_path
    if artifact_path:
        mlconf.artifact_path = artifact_path
    if project:
        mlconf.default_project = project
    if access_key:
        _os.environ["MLT_ACCESS_KEY"] = access_key
    return mlconf.default_project, mlconf.get("artifact_path") or None


def set_env_from_file(env_file: str, return_dict: bool = False):
    """Load KEY=VALUE lines into the environment (reference :187)."""
    env_vars = {}
    with open(_os.path.expanduser(env_file)) as fp:
        for line in fp:
            line = line.strip()
            if line and not line.startswith("#") and "=" in line:
                key, value = line.split("=", 1)
                env_vars[key.strip()] = value.strip()
    for key, value in env_vars.items():
        _os.environ[key] = value
    mlconf.reload()
    if return_dict:
        return env_vars


def get_version() -> str:
    return __version__


# projects API is imported lazily to avoid heavy import cost at package load;
# these are re-exported here for parity with the reference's top-level API
def new_project(*args, **kwargs):
    from .projects import new_project as _new_project

    return _new_project(*args, **kwargs)


def load_project(*args, **kwargs):
    from .projects import load_project as _load_project

    return _load_project(*args, **kwargs)


def get_or_create_project(*args, **kwargs):
    from .projects import get_or_create_project as _get_or_create_project

    return _get_or_create_project(*args, **kwargs)


def get_current_project(silent: bool = False):
    from .projects import get_current_project as _get_current_project

    return _get_current_project(silent)


def get_dataitem(url: str, secrets: dict | None = None) -> "DataItem":
    """Resolve any url (file/gs/s3/redis/store://...) into a DataItem
    (reference mlrun/run.py get_dataitem)."""
    return store_manager.object(url=url, secrets=secrets)


def get_object(url: str, secrets: dict | None = None,
               size: int | None = None, offset: int = 0) -> bytes:
    """Read an object's bytes from any datastore url (reference
    get_object)."""
    return get_dataitem(url, secrets=secrets).get(size=size, offset=offset)


def get_pipeline(run_id: str, project: str = ""):
    """Fetch a workflow/pipeline run record from the service (reference
    get_pipeline — a KFP proxy there, the native workflow backend
    here)."""
    db = get_run_db()
    getter = getattr(db, "get_pipeline", None)
    if getter:
        return getter(run_id, project=project)
    raise MLRunInvalidArgumentError(
        "the configured run DB does not expose pipeline runs "
        "(connect to the service with MLT_DBPATH)")


class _PipelineContextProxy:
    """Attribute-access proxy over the ACTIVE workflow context (the
    reference's top-level ``pipeline_context`` is an object —
    ``pipeline_context.project`` — not a callable). Attributes resolve
    against the current context; None-safe outside a workflow."""

    def _current(self):
        from .projects.pipelines import pipeline_context as _context

        return _context()

    def __getattr__(self, name):
        current = self._current()
        if current is None:
            if name in ("project", "workflow", "workflow_id"):
                return None
            raise AttributeError(
                f"no active pipeline context (attribute {name!r})")
        return getattr(current, name)

    def __bool__(self):
        return self._current() is not None


pipeline_context = _PipelineContextProxy()


def run_function(function, *args, **kwargs):
    """Run a function through the CURRENT project (reference top-level
    run_function — project-scope sugar)."""
    return get_current_project(silent=False).run_function(
        function, *args, **kwargs)


def build_function(function, *args, **kwargs):
    """Build a function's image through the current project (reference
    build_function)."""
    return get_current_project(silent=False).build_function(
        function, *args, **kwargs)


def deploy_function(function, *args, **kwargs):
    """Deploy a serving function through the current project (reference
    deploy_function)."""
    return get_current_project(silent=False).deploy_function(
        function, *args, **kwargs)


class Version:
    """Version info provider (reference mlrun/utils/version)."""

    @staticmethod
    def get() -> dict:
        return {"version": __version__}


class ArtifactType:
    """Log-hint artifact types (reference mlrun/package ArtifactType)."""

    result = "result"
    artifact = "artifact"
    dataset = "dataset"
    model = "model"
    file = "file"
    plot = "plot"


# heavier symbols resolve lazily so `import mlrun_tpu` stays light
_LAZY_EXPORTS = {
    "ProjectMetadata": ("mlrun_tpu.projects.project", "ProjectMetadata"),
    "MlrunProject": ("mlrun_tpu.projects.project", "MlrunProject"),
    "DefaultPackager": ("mlrun_tpu.package.packagers.default",
                        "DefaultPackager"),
    "Packager": ("mlrun_tpu.package.packagers_manager", "Packager"),
}


def __getattr__(name: str):
    target = _LAZY_EXPORTS.get(name)
    if target is None:
        raise AttributeError(f"module 'mlrun_tpu' has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(target[0]), target[1])


def handler(labels: dict | None = None, outputs: list | None = None,
            inputs: bool = True):
    """Decorator marking a function as an mlrun-tpu handler with packaging
    hints (reference mlrun/handler decorator)."""

    def decorator(func):
        setattr(func, "_mlt_handler", {
            "labels": labels, "outputs": outputs, "inputs": inputs})
        return func

    return decorator
