"""Data sources (reference analog: mlrun/datastore/sources.py — CSVSource
:162, ParquetSource :278, BigQuerySource :517, HttpSource :969, StreamSource
:979, KafkaSource :1052, SQLSource :1221 — fresh, pandas-engine
implementations; engine-specific ones are gated on their client libs)."""

from __future__ import annotations

import os
from typing import Optional

from ..model import ModelObj
from ..utils import logger


class BaseSource(ModelObj):
    kind = "base"
    _dict_fields = ["kind", "name", "path", "attributes", "key_field",
                    "time_field", "schedule", "start_time", "end_time"]

    def __init__(self, name: str = "", path: str = "",
                 attributes: dict | None = None, key_field: str = "",
                 time_field: str = "", schedule: str = "",
                 start_time=None, end_time=None):
        self.name = name
        self.path = path
        self.attributes = attributes or {}
        self.key_field = key_field
        self.time_field = time_field
        self.schedule = schedule
        self.start_time = start_time
        self.end_time = end_time

    def to_dataframe(self, columns=None, df_module=None, **kwargs):
        raise NotImplementedError

    def filter_df(self, df):
        if self.time_field and (self.start_time or self.end_time):
            import pandas as pd

            series = pd.to_datetime(df[self.time_field])
            if self.start_time:
                df = df[series >= pd.to_datetime(self.start_time)]
            if self.end_time:
                df = df[series <= pd.to_datetime(self.end_time)]
        return df


class CSVSource(BaseSource):
    kind = "csv"

    def to_dataframe(self, columns=None, df_module=None, **kwargs):
        from . import store_manager

        parse_dates = self.attributes.get("parse_dates")
        df = store_manager.object(url=self.path).as_df(
            columns=None, format="csv", parse_dates=parse_dates, **kwargs)
        df = self.filter_df(df)
        return df[columns] if columns else df


class ParquetSource(BaseSource):
    kind = "parquet"

    def to_dataframe(self, columns=None, df_module=None, **kwargs):
        from . import store_manager

        df = store_manager.object(url=self.path).as_df(
            format="parquet", **kwargs)
        df = self.filter_df(df)
        return df[columns] if columns else df


class DataFrameSource(BaseSource):
    kind = "dataframe"

    def __init__(self, df=None, **kwargs):
        super().__init__(**kwargs)
        self._df = df

    def to_dataframe(self, columns=None, df_module=None, **kwargs):
        df = self.filter_df(self._df)
        return df[columns] if columns else df


class HttpSource(BaseSource):
    kind = "http"

    def to_dataframe(self, columns=None, df_module=None, **kwargs):
        import io

        import pandas as pd
        import requests

        resp = requests.get(self.path, timeout=60,
                            headers=self.attributes.get("headers"))
        resp.raise_for_status()
        fmt = self.attributes.get("format") or self.path.rsplit(
            ".", 1)[-1].lower()
        buf = io.BytesIO(resp.content)
        if fmt == "csv":
            df = pd.read_csv(buf)
        elif fmt in ("parquet", "pq"):
            df = pd.read_parquet(buf)
        else:
            df = pd.read_json(buf)
        return df[columns] if columns else df


class SQLSource(BaseSource):
    """SQL table source via sqlite3/dbapi url in attributes["db_url"]."""

    kind = "sql"

    def to_dataframe(self, columns=None, df_module=None, **kwargs):
        import sqlite3

        import pandas as pd

        db_url = self.attributes.get("db_url", "")
        table = self.attributes.get("table") or self.path
        query = self.attributes.get("query") or f"SELECT * FROM {table}"
        if db_url.startswith("sqlite://"):
            db_url = db_url[len("sqlite://"):]
        with sqlite3.connect(db_url) as conn:
            df = pd.read_sql(query, conn)
        df = self.filter_df(df)
        return df[columns] if columns else df


class BigQuerySource(BaseSource):
    kind = "bigquery"

    def to_dataframe(self, columns=None, df_module=None, **kwargs):
        try:
            from google.cloud import bigquery  # gated
        except ImportError as exc:
            raise ImportError(
                "BigQuerySource requires google-cloud-bigquery") from exc
        client = bigquery.Client()
        query = self.attributes.get("query") or f"SELECT * FROM `{self.path}`"
        df = client.query(query).to_dataframe()
        return df[columns] if columns else df


class SnowflakeSource(BaseSource):
    """Snowflake table/query source (reference: mlrun/datastore/
    sources.py:737 SnowflakeSource — spark-engine there; here the
    snowflake connector is gated and the connection kwargs builder is
    testable without it)."""

    kind = "snowflake"

    def connection_kwargs(self) -> dict:
        """Connector kwargs from attributes + SNOWFLAKE_PASSWORD env (the
        secret never lives in the source spec)."""
        attrs = self.attributes
        kwargs = {key: attrs[key] for key in
                  ("account", "user", "warehouse", "database", "schema",
                   "role") if attrs.get(key)}
        password = os.environ.get("SNOWFLAKE_PASSWORD", "")
        if password:
            kwargs["password"] = password
        return kwargs

    def to_dataframe(self, columns=None, df_module=None, **kwargs):
        try:
            import snowflake.connector  # gated
        except ImportError as exc:
            raise ImportError(
                "SnowflakeSource requires snowflake-connector-python"
            ) from exc
        query = self.attributes.get("query") or f"SELECT * FROM {self.path}"
        with snowflake.connector.connect(
                **self.connection_kwargs()) as conn:
            df = conn.cursor().execute(query).fetch_pandas_all()
        df = self.filter_df(df)
        return df[columns] if columns else df


class StreamSource(BaseSource):
    """In-memory/file stream source (serving-graph queue input)."""

    kind = "stream"

    def to_dataframe(self, columns=None, df_module=None, **kwargs):
        import pandas as pd

        from ..serving.streams import _FileStream, get_stream_pusher

        stream = get_stream_pusher(self.path)
        if isinstance(stream, _FileStream):
            items, _ = stream.pull(offset=0, max_items=0)
        elif hasattr(stream, "pull"):
            items = stream.pull(1_000_000)
        else:
            items = []
        df = pd.DataFrame(items)
        return df[columns] if columns else df


class KafkaSource(BaseSource):
    kind = "kafka"

    def to_dataframe(self, columns=None, df_module=None, **kwargs):
        try:
            from kafka import KafkaConsumer  # gated
        except ImportError as exc:
            raise ImportError("KafkaSource requires kafka-python") from exc
        import json

        import pandas as pd

        consumer = KafkaConsumer(
            self.path, bootstrap_servers=self.attributes.get("brokers"),
            consumer_timeout_ms=int(self.attributes.get("timeout_ms", 5000)),
            auto_offset_reset="earliest")
        rows = [json.loads(m.value) for m in consumer]
        df = pd.DataFrame(rows)
        return df[columns] if columns else df


class GenericUrlSource(BaseSource):
    """Any datastore url; format inferred from the suffix by DataItem.as_df
    (csv/parquet/json)."""

    kind = "url"

    def to_dataframe(self, columns=None, df_module=None, **kwargs):
        from . import store_manager

        df = store_manager.object(url=self.path).as_df(**kwargs)
        df = self.filter_df(df)
        return df[columns] if columns else df


source_kind_to_class = {
    cls.kind: cls for cls in (
        CSVSource, ParquetSource, DataFrameSource, HttpSource, SQLSource,
        BigQuerySource, SnowflakeSource, StreamSource, KafkaSource,
        GenericUrlSource)
}


def get_source_from_dict(struct: dict) -> BaseSource:
    kind = struct.get("kind", "csv")
    cls = source_kind_to_class.get(kind)
    if cls is None:
        raise ValueError(f"unknown source kind '{kind}'")
    return cls.from_dict(struct)


def resolve_source(source) -> BaseSource:
    """Accept a BaseSource, DataFrame, url string, or dict."""
    import pandas as pd

    if isinstance(source, BaseSource):
        return source
    if isinstance(source, pd.DataFrame):
        return DataFrameSource(df=source)
    if isinstance(source, dict):
        return get_source_from_dict(source)
    if isinstance(source, str):
        suffix = source.rsplit(".", 1)[-1].lower()
        if suffix == "csv":
            return CSVSource(path=source)
        if suffix in ("parquet", "pq"):
            return ParquetSource(path=source)
        if source.startswith(("http://", "https://")):
            return HttpSource(path=source)
        return GenericUrlSource(path=source)
    raise ValueError(f"unsupported source {type(source)}")
