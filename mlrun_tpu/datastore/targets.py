"""Data targets (reference analog: mlrun/datastore/targets.py —
ParquetTarget :800, CSVTarget :1082, NoSqlTarget :1409, StreamTarget :1597,
KafkaTarget :1634, SQLTarget :1895, DFTarget :1834).

The online "NoSql" target is a sqlite-backed KV (replacing V3IO-KV/Redis in
the reference's default path; Redis/Kafka remain gated on their clients).
"""

from __future__ import annotations

import json
import os
import sqlite3
from typing import Optional

from ..config import mlconf
from ..model import ModelObj
from ..utils import logger, now_iso


class BaseTarget(ModelObj):
    kind = "base"
    _dict_fields = ["kind", "name", "path", "attributes", "partitioned",
                    "key_bucketing_number", "partition_cols", "time_col"]
    is_online = False

    def __init__(self, name: str = "", path: str = "",
                 attributes: dict | None = None, partitioned: bool = False,
                 key_bucketing_number=None, partition_cols=None,
                 time_col=None):
        self.name = name or self.kind
        self.path = path
        self.attributes = attributes or {}
        self.partitioned = partitioned
        self.key_bucketing_number = key_bucketing_number
        self.partition_cols = partition_cols
        self.time_col = time_col

    def default_path(self, project: str, feature_set: str) -> str:
        suffix = {"parquet": ".parquet", "csv": ".csv"}.get(self.kind, "")
        return os.path.join(mlconf.home_dir, "feature-store", project,
                            f"{feature_set}-{self.kind}{suffix}")

    def write_dataframe(self, df, key_columns: list | None = None,
                        timestamp_key: str | None = None) -> str:
        raise NotImplementedError

    def as_df(self, columns=None):
        from . import store_manager

        df = store_manager.object(url=self.path).as_df(format=self.kind)
        return df[columns] if columns else df

    def status_record(self) -> dict:
        return {"name": self.name, "kind": self.kind, "path": self.path,
                "updated": now_iso()}


class ParquetTarget(BaseTarget):
    kind = "parquet"

    def write_dataframe(self, df, key_columns=None, timestamp_key=None) -> str:
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        if self.partitioned and (self.partition_cols or timestamp_key):
            cols = self.partition_cols or [timestamp_key]
            df.to_parquet(self.path, partition_cols=cols)
        else:
            df.to_parquet(self.path, index=False)
        return self.path


class CSVTarget(BaseTarget):
    kind = "csv"

    def write_dataframe(self, df, key_columns=None, timestamp_key=None) -> str:
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        df.to_csv(self.path, index=False)
        return self.path


class NoSqlTarget(BaseTarget):
    """Online KV target on sqlite (key → json record)."""

    kind = "nosql"
    is_online = True

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._cached_conn = None

    def _conn(self):
        # one cached connection per target instance — get() sits on the
        # online-lookup hot path
        if self._cached_conn is not None:
            return self._cached_conn
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        conn = sqlite3.connect(self.path, check_same_thread=False)
        conn.execute("CREATE TABLE IF NOT EXISTS kv "
                     "(key TEXT PRIMARY KEY, value TEXT)")
        self._cached_conn = conn
        return conn

    def close(self):
        if self._cached_conn is not None:
            self._cached_conn.close()
            self._cached_conn = None

    def default_path(self, project: str, feature_set: str) -> str:
        return os.path.join(mlconf.home_dir, "feature-store", project,
                            f"{feature_set}-kv.sqlite")

    def write_dataframe(self, df, key_columns=None, timestamp_key=None) -> str:
        if not key_columns:
            raise ValueError("nosql target requires key columns (entities)")
        with self._conn() as conn:
            for _, row in df.iterrows():
                key = "|".join(str(row[k]) for k in key_columns)
                conn.execute(
                    "INSERT OR REPLACE INTO kv (key, value) VALUES (?, ?)",
                    (key, json.dumps(row.to_dict(), default=str)))
        return self.path

    def get(self, key_values: list) -> Optional[dict]:
        key = "|".join(str(v) for v in key_values)
        with self._conn() as conn:
            row = conn.execute("SELECT value FROM kv WHERE key=?",
                               (key,)).fetchone()
        return json.loads(row[0]) if row else None


class RedisNoSqlTarget(NoSqlTarget):
    """Online KV target on redis (reference datastore/redis.py backs the
    same role): rows live as redis HASHes under
    ``mlt:{project}:{feature_set}:{entity-key}`` so the online feature
    service reads single rows with one HGETALL — the low-latency path a
    shared serving fleet needs (the sqlite NoSqlTarget is single-host)."""

    kind = "redisnosql"

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._prefix = ""
        self._cached_client = None

    def _client(self):
        if self._cached_client is None:
            try:
                import redis  # gated
            except ImportError as exc:
                raise ImportError(
                    "RedisNoSqlTarget requires redis-py") from exc
            self._cached_client = redis.from_url(
                self.path or str(mlconf.redis.url))
        return self._cached_client

    def close(self):
        if self._cached_client is not None:
            # actually release the pool's sockets (redis-py keeps them
            # until GC otherwise); close() exists on redis>=4, fall back
            # to the pool disconnect
            client = self._cached_client
            closer = getattr(client, "close", None) or getattr(
                getattr(client, "connection_pool", None), "disconnect",
                None)
            if closer:
                try:
                    closer()
                except Exception:  # noqa: BLE001 - already gone
                    pass
        self._cached_client = None

    def set_namespace(self, project: str, feature_set: str):
        """Key namespace — set on EVERY ingest (a user-supplied redis url
        must not make two feature sets share un-prefixed row keys)."""
        self._prefix = f"mlt:{project}:{feature_set}"

    def default_path(self, project: str, feature_set: str) -> str:
        self.set_namespace(project, feature_set)
        return str(mlconf.redis.url)

    def _row_key(self, key_values: list) -> str:
        key = "|".join(str(v) for v in key_values)
        return f"{self._prefix}:{key}" if self._prefix else key

    def write_dataframe(self, df, key_columns=None, timestamp_key=None) -> str:
        if not key_columns:
            raise ValueError("redis target requires key columns (entities)")
        client = self._client()
        for _, row in df.iterrows():
            key = self._row_key([row[k] for k in key_columns])
            client.hset(key, mapping={
                k: json.dumps(v, default=str)
                for k, v in row.to_dict().items()})
        return self.path or str(mlconf.redis.url)

    def get(self, key_values: list) -> Optional[dict]:
        raw = self._client().hgetall(self._row_key(key_values))
        if not raw:
            return None
        return {
            (k.decode() if isinstance(k, bytes) else k):
            json.loads(v.decode() if isinstance(v, bytes) else v)
            for k, v in raw.items()}

    def status_record(self) -> dict:
        record = super().status_record()
        record["prefix"] = self._prefix
        return record


class StreamTarget(BaseTarget):
    kind = "stream"
    is_online = True

    def write_dataframe(self, df, key_columns=None, timestamp_key=None) -> str:
        from ..serving.streams import get_stream_pusher

        stream = get_stream_pusher(self.path)
        stream.push([row.to_dict() for _, row in df.iterrows()])
        return self.path


class KafkaTarget(BaseTarget):
    kind = "kafka"
    is_online = True

    def write_dataframe(self, df, key_columns=None, timestamp_key=None) -> str:
        from ..serving.streams import _KafkaStream

        brokers = self.attributes.get("brokers", "")
        stream = _KafkaStream(brokers, self.path)
        stream.push([row.to_dict() for _, row in df.iterrows()])
        return self.path


class SQLTarget(BaseTarget):
    kind = "sql"

    def write_dataframe(self, df, key_columns=None, timestamp_key=None) -> str:
        db_url = self.attributes.get("db_url", "")
        table = self.attributes.get("table") or self.name
        if db_url.startswith("sqlite://"):
            db_url = db_url[len("sqlite://"):]
        os.makedirs(os.path.dirname(db_url) or ".", exist_ok=True)
        with sqlite3.connect(db_url) as conn:
            df.to_sql(table, conn, if_exists=self.attributes.get(
                "if_exists", "replace"), index=False)
        return f"sqlite://{db_url}#{table}"


class DFTarget(BaseTarget):
    kind = "dataframe"

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._df = None

    def write_dataframe(self, df, key_columns=None, timestamp_key=None) -> str:
        self._df = df
        return "memory://df"

    def as_df(self, columns=None):
        return self._df[columns] if columns else self._df


class TSDBTarget(BaseTarget):
    """Time-series metrics target: append-only parquet keyed by time."""

    kind = "tsdb"

    def default_path(self, project: str, feature_set: str) -> str:
        return os.path.join(mlconf.home_dir, "feature-store", project,
                            f"{feature_set}-tsdb.parquet")

    def write_dataframe(self, df, key_columns=None, timestamp_key=None) -> str:
        import pandas as pd

        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        if os.path.isfile(self.path):
            df = pd.concat([pd.read_parquet(self.path), df],
                           ignore_index=True)
        df.to_parquet(self.path, index=False)
        return self.path


target_kind_to_class = {
    cls.kind: cls for cls in (
        ParquetTarget, CSVTarget, NoSqlTarget, RedisNoSqlTarget,
        StreamTarget, KafkaTarget, SQLTarget, DFTarget, TSDBTarget)
}


def resolve_target(target) -> BaseTarget:
    if isinstance(target, BaseTarget):
        return target
    if isinstance(target, dict):
        kind = target.get("kind", "parquet")
        cls = target_kind_to_class.get(kind)
        if cls is None:
            raise ValueError(f"unknown target kind '{kind}'")
        return cls.from_dict(target)
    if isinstance(target, str):
        cls = target_kind_to_class.get(target)
        if cls is None:
            raise ValueError(f"unknown target kind '{target}'")
        return cls()
    raise ValueError(f"unsupported target {type(target)}")
