"""Store registry + manager (reference analog: mlrun/datastore/datastore.py:56
``schema_to_store``, :118 ``StoreManager`` — fresh implementation).

Also resolves ``store://`` artifact URIs against the run DB
(reference analog: mlrun/datastore/store_resources.py).
"""

from __future__ import annotations

from typing import Optional

from .base import DataItem, DataStore, parse_url
from .redis import RedisStore
from .stores import FileStore, FsspecStore, HttpStore, InMemoryStore

schema_to_store: dict[str, type] = {
    "file": FileStore,
    "": FileStore,
    "memory": InMemoryStore,
    "gs": FsspecStore,
    "gcs": FsspecStore,
    "s3": FsspecStore,
    "az": FsspecStore,
    "abfs": FsspecStore,
    "hdfs": FsspecStore,
    "dbfs": FsspecStore,
    "oss": FsspecStore,
    "http": HttpStore,
    "https": HttpStore,
    "redis": RedisStore,
    "rediss": RedisStore,
}


def register_store(scheme: str, cls: type):
    schema_to_store[scheme] = cls


class StoreManager:
    """Caches DataStore instances per (scheme, endpoint) and mints DataItems."""

    def __init__(self, secrets: dict | None = None, db=None):
        self._stores: dict[str, DataStore] = {}
        self._secrets = secrets or {}
        self._db = db

    def set(self, secrets: dict | None = None, db=None) -> "StoreManager":
        if secrets:
            self._secrets.update(secrets)
        if db is not None:
            self._db = db
        return self

    def _get_db(self):
        if self._db is None:
            from ..db import get_run_db

            self._db = get_run_db()
        return self._db

    def get_or_create_store(self, url: str, secrets: dict | None = None,
                            project: str = "") -> tuple[DataStore, str]:
        scheme, endpoint, path = parse_url(url)
        if scheme == "ds":
            # ds://<profile>/<subpath> → the profile's real url + secrets
            # (reference datastore_profile.py resolution); resolved against
            # this manager's db and the caller's project scope
            from .profiles import datastore_profile_read

            profile = datastore_profile_read(endpoint, project=project,
                                             db=self._get_db())
            real_url = profile.url(path)
            merged = dict(profile.secrets())
            merged.update(secrets or {})
            return self.get_or_create_store(real_url, secrets=merged or None,
                                            project=project)
        store_key = f"{scheme}://{endpoint}"
        if store_key not in self._stores or secrets:
            cls = schema_to_store.get(scheme)
            if cls is None:
                raise ValueError(f"unsupported url scheme '{scheme}' ({url})")
            merged = dict(self._secrets)
            merged.update(secrets or {})
            store = cls(self, store_key, scheme, endpoint, secrets=merged)
            if secrets:
                return store, path  # don't cache credentialed stores
            self._stores[store_key] = store
        return self._stores[store_key], path

    def object(self, url: str, key: str = "", project: str = "",
               secrets: dict | None = None, allow_empty_resources=None) -> DataItem:
        meta = {}
        artifact_url = ""
        if url.startswith("store://"):
            artifact_url = url
            resource = self._resolve_store_resource(url, project)
            meta = resource or {}
            target = (
                meta.get("spec", {}).get("target_path")
                or meta.get("target_path")
            )
            if not target:
                raise ValueError(f"artifact {url} has no target_path")
            key = key or meta.get("metadata", {}).get("key", "")
            url = target
        store, path = self.get_or_create_store(url, secrets=secrets,
                                               project=project)
        return DataItem(key or path, store, path, url=url, meta=meta,
                        artifact_url=artifact_url)

    def _resolve_store_resource(self, url: str, project: str = "") -> Optional[dict]:
        """store://artifacts/<project>/<key>[#iter][:tag][@uid] or
        store://<project>/<key> (same grammar as the reference store
        uris — ``#iter`` addresses a hyper-run iteration's artifact)."""
        body = url[len("store://"):]
        for prefix in ("artifacts/", "datasets/", "models/"):
            if body.startswith(prefix) and body.count("/") >= 2:
                body = body[len(prefix):]
                break
        tree = None
        if "@" in body:
            body, tree = body.rsplit("@", 1)
        tag = None
        if ":" in body:
            body, tag = body.rsplit(":", 1)
        iteration = None
        if "#" in body:
            body, _, iter_part = body.rpartition("#")
            try:
                iteration = int(iter_part)
            except ValueError:
                body = f"{body}#{iter_part}"  # '#' was part of the key
        parts = body.split("/", 1)
        if len(parts) == 2:
            project, key = parts
        else:
            key = parts[0]
        db = self._get_db()
        return db.read_artifact(key, tag=tag, project=project or None,
                                tree=tree, iter=iteration)


store_manager = StoreManager()
