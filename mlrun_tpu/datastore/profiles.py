"""Datastore profiles — named connection configs addressable as
``ds://<profile>/<path>``.

Reference analog: mlrun/datastore/datastore_profile.py (DatastoreProfile
subclasses, register_temporary_client_datastore_profile, the public/private
attribute split) — re-implemented compactly. The PUBLIC part of a profile
(type, bucket, endpoint...) lives in the DB; the PRIVATE part (keys,
tokens) rides the project-secret store under
``mlrun.datastore-profiles.<name>`` and never crosses the REST list
surface.
"""

from __future__ import annotations

import json
from typing import Optional

PROFILE_SECRET_PREFIX = "mlrun.datastore-profiles."

_TEMP_PROFILES: dict[str, "DatastoreProfile"] = {}


class DatastoreProfile:
    """Base profile: subclasses declare which fields are private."""

    type = "basic"
    _private_fields: tuple = ()

    def __init__(self, name: str, **fields):
        self.name = name
        self.fields = fields

    # -- serialization ------------------------------------------------------
    def public_dict(self) -> dict:
        return {
            "name": self.name, "type": self.type,
            "fields": {k: v for k, v in self.fields.items()
                       if k not in self._private_fields},
        }

    def private_dict(self) -> dict:
        return {k: v for k, v in self.fields.items()
                if k in self._private_fields and v is not None}

    @staticmethod
    def from_parts(public: dict, private: dict | None = None
                   ) -> "DatastoreProfile":
        cls = _PROFILE_TYPES.get(public.get("type", "basic"),
                                 DatastoreProfile)
        fields = dict(public.get("fields") or {})
        fields.update(private or {})
        profile = cls(public["name"], **fields)
        return profile

    # -- resolution ---------------------------------------------------------
    def url(self, subpath: str) -> str:
        """The real datastore url for a ds:// subpath."""
        base = self.fields.get("url", "")
        if not base:
            raise ValueError(
                f"profile '{self.name}' has no url field")
        return base.rstrip("/") + ("/" + subpath.lstrip("/") if subpath
                                   else "")

    def secrets(self) -> dict:
        """Credential env-style secrets for the underlying store."""
        return {}


class DatastoreProfileBasic(DatastoreProfile):
    """Arbitrary url + private token (reference DatastoreProfileBasic)."""

    type = "basic"
    _private_fields = ("private",)


class DatastoreProfileS3(DatastoreProfile):
    type = "s3"
    _private_fields = ("access_key_id", "secret_key")

    def url(self, subpath: str) -> str:
        bucket = self.fields.get("bucket", "")
        prefix = f"s3://{bucket}" if bucket else "s3:/"
        return prefix + "/" + subpath.lstrip("/")

    def secrets(self) -> dict:
        out = {}
        if self.fields.get("access_key_id"):
            out["AWS_ACCESS_KEY_ID"] = self.fields["access_key_id"]
        if self.fields.get("secret_key"):
            out["AWS_SECRET_ACCESS_KEY"] = self.fields["secret_key"]
        if self.fields.get("endpoint_url"):
            out["S3_ENDPOINT_URL"] = self.fields["endpoint_url"]
        if self.fields.get("region"):
            out["AWS_REGION"] = self.fields["region"]
        return out


class DatastoreProfileGCS(DatastoreProfile):
    type = "gcs"
    _private_fields = ("credentials_json",)

    def url(self, subpath: str) -> str:
        bucket = self.fields.get("bucket", "")
        return f"gs://{bucket}/" + subpath.lstrip("/")

    def secrets(self) -> dict:
        out = {}
        if self.fields.get("credentials_json"):
            out["GCP_CREDENTIALS"] = self.fields["credentials_json"]
        if self.fields.get("credentials_path"):
            out["GOOGLE_APPLICATION_CREDENTIALS"] = \
                self.fields["credentials_path"]
        return out


class DatastoreProfileAzureBlob(DatastoreProfile):
    type = "az"
    _private_fields = ("connection_string", "account_key", "client_secret")

    def url(self, subpath: str) -> str:
        container = self.fields.get("container", "")
        return f"az://{container}/" + subpath.lstrip("/")

    def secrets(self) -> dict:
        out = {}
        for field, env in (("connection_string",
                            "AZURE_STORAGE_CONNECTION_STRING"),
                           ("account_name", "AZURE_STORAGE_ACCOUNT_NAME"),
                           ("account_key", "AZURE_STORAGE_ACCOUNT_KEY"),
                           ("client_id", "AZURE_STORAGE_CLIENT_ID"),
                           ("client_secret", "AZURE_STORAGE_CLIENT_SECRET"),
                           ("tenant_id", "AZURE_STORAGE_TENANT_ID")):
            if self.fields.get(field):
                out[env] = self.fields[field]
        return out


class DatastoreProfileRedis(DatastoreProfile):
    type = "redis"
    _private_fields = ("password",)

    def url(self, subpath: str) -> str:
        endpoint = self.fields.get("endpoint", "localhost:6379")
        return f"redis://{endpoint}/" + subpath.lstrip("/")

    def secrets(self) -> dict:
        out = {}
        if self.fields.get("username"):
            out["REDIS_USERNAME"] = self.fields["username"]
        if self.fields.get("password"):
            out["REDIS_PASSWORD"] = self.fields["password"]
        return out


_PROFILE_TYPES = {
    cls.type: cls for cls in
    (DatastoreProfileBasic, DatastoreProfileS3, DatastoreProfileGCS,
     DatastoreProfileAzureBlob, DatastoreProfileRedis)
}


def register_temporary_client_datastore_profile(profile: DatastoreProfile):
    """Client-side (process-local) registration — nothing leaves the
    process (reference function of the same name)."""
    _TEMP_PROFILES[profile.name] = profile


def remove_temporary_client_datastore_profile(name: str):
    _TEMP_PROFILES.pop(name, None)


def datastore_profile_read(name: str, project: str = "",
                           db=None) -> DatastoreProfile:
    """Resolve a profile: temporary client registry first, then the DB
    (+ project secrets for the private part when the db exposes them)."""
    profile = _TEMP_PROFILES.get(name)
    if profile is not None:
        return profile
    if db is None:
        from ..db import get_run_db

        try:
            db = get_run_db()
        except Exception as exc:  # noqa: BLE001 - no db configured
            raise ValueError(
                f"datastore profile '{name}' not registered client-side "
                f"and no run db is configured ({exc})") from exc
    getter = getattr(db, "get_datastore_profile", None)
    if getter is None:
        raise ValueError(
            f"datastore profile '{name}' not registered client-side and "
            "the db cannot resolve profiles")
    public = getter(name, project=project)
    if not public:
        raise ValueError(f"datastore profile '{name}' not found")
    private: dict = {}
    secret_getter = getattr(db, "get_project_secrets", None)
    if secret_getter is not None:
        # server-side: private part straight from the secret store
        raw = secret_getter(project,
                            keys=[PROFILE_SECRET_PREFIX + name])
        blob = raw.get(PROFILE_SECRET_PREFIX + name)
        if blob:
            private = json.loads(blob)
    else:
        # in-run: the secret was injected into the resource env as
        # MLT_SECRET_<key> by the runtime handler
        import os

        blob = os.environ.get(
            "MLT_SECRET_" + PROFILE_SECRET_PREFIX + name)
        if blob:
            private = json.loads(blob)
    return DatastoreProfile.from_parts(public, private)
