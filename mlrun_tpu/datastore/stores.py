"""Concrete datastores (reference analogs: mlrun/datastore/filestore.py:25,
inmem.py:24, google_cloud_storage.py:31, s3.py:26 — fresh implementations).

``FileStore`` and ``InMemoryStore`` are dependency-free; cloud stores (gs/s3/az)
ride a generic fsspec-backed store so that any installed fsspec protocol works —
on TPU the native object store is GCS.
"""

from __future__ import annotations

import glob as globlib
import os
import time

from .base import DataStore, FileStats


class FileStore(DataStore):
    kind = "file"

    def _abs(self, key: str) -> str:
        return os.path.abspath(os.path.expanduser(key))

    def get(self, key, size=None, offset=0) -> bytes:
        with open(self._abs(key), "rb") as fp:
            if offset:
                fp.seek(offset)
            return fp.read(size) if size else fp.read()

    def put(self, key, data, append=False):
        path = self._abs(key)
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        mode = "a" if append else "w"
        if isinstance(data, bytes):
            mode += "b"
        with open(path, mode) as fp:
            fp.write(data)

    def stat(self, key) -> FileStats:
        st = os.stat(self._abs(key))
        return FileStats(size=st.st_size, modified=st.st_mtime)

    def listdir(self, key) -> list[str]:
        path = self._abs(key)
        if os.path.isdir(path):
            out = []
            for root, _, files in os.walk(path):
                rel = os.path.relpath(root, path)
                for f in files:
                    out.append(f if rel == "." else os.path.join(rel, f))
            return out
        return [os.path.basename(p) for p in globlib.glob(path)]

    def delete(self, key):
        path = self._abs(key)
        if os.path.isfile(path):
            os.remove(path)

    def exists(self, key) -> bool:
        return os.path.exists(self._abs(key))


class InMemoryStore(DataStore):
    """memory:// store for tests and serving-graph queues."""

    kind = "memory"
    _items: dict[str, bytes] = {}

    def get(self, key, size=None, offset=0):
        data = self._items[key]
        if offset:
            data = data[offset:]
        if size:
            data = data[:size]
        return data

    def put(self, key, data, append=False):
        if isinstance(data, str):
            data = data.encode()
        if append and key in self._items:
            self._items[key] += data
        else:
            self._items[key] = data

    def stat(self, key):
        if key not in self._items:
            raise FileNotFoundError(key)
        return FileStats(size=len(self._items[key]), modified=time.time())

    def listdir(self, key):
        prefix = key.rstrip("/") + "/" if key else ""
        return [k[len(prefix):] for k in self._items if k.startswith(prefix)]

    def delete(self, key):
        self._items.pop(key, None)

    def exists(self, key):
        return key in self._items


class FsspecStore(DataStore):
    """Generic fsspec-protocol store: gs/gcs, s3, az/abfs, http(s), hdfs...

    On TPU deployments GCS is the primary object store (artifacts, orbax
    checkpoints); credentials flow via standard env (GOOGLE_APPLICATION_CREDENTIALS,
    AWS_ACCESS_KEY_ID...) or per-store secrets, like the reference's per-store
    secret plumbing (mlrun/datastore/base.py _get_secret_or_env).
    """

    def __init__(self, parent, name, kind, endpoint="", secrets=None):
        super().__init__(parent, name, kind, endpoint, secrets)
        self._fs = None

    @property
    def filesystem(self):
        if self._fs is None:
            import fsspec

            protocol = {"gs": "gcs", "az": "abfs"}.get(self.kind, self.kind)
            self._fs = fsspec.filesystem(protocol, **self.storage_options())
        return self._fs

    def storage_options(self) -> dict:
        """Per-kind credential/option mapping (reference analog: the
        per-store option handling in mlrun/datastore/s3.py:26,
        azure_blob.py:31, google_cloud_storage.py) — values come from the
        store's secrets (e.g. a ds:// profile) or the environment."""
        options: dict = {}
        if self.kind == "s3":
            key = self._get_secret_or_env("AWS_ACCESS_KEY_ID")
            secret = self._get_secret_or_env("AWS_SECRET_ACCESS_KEY")
            if key:
                options["key"] = key
                options["secret"] = secret
            endpoint = self._get_secret_or_env("S3_ENDPOINT_URL")
            if endpoint:
                options["endpoint_url"] = endpoint
            region = self._get_secret_or_env("AWS_REGION")
            if region:
                options.setdefault("client_kwargs", {})[
                    "region_name"] = region
            if self._get_secret_or_env("S3_ANONYMOUS").strip().lower() in \
                    ("1", "true", "yes"):
                options["anon"] = True
        elif self.kind in ("gs", "gcs"):
            creds_json = self._get_secret_or_env("GCP_CREDENTIALS")
            creds_path = self._get_secret_or_env(
                "GOOGLE_APPLICATION_CREDENTIALS")
            if creds_json:
                import json as jsonlib

                options["token"] = jsonlib.loads(creds_json)
            elif creds_path:
                options["token"] = creds_path
        elif self.kind in ("az", "abfs"):
            conn = self._get_secret_or_env("AZURE_STORAGE_CONNECTION_STRING")
            if conn:
                options["connection_string"] = conn
            account = self._get_secret_or_env("AZURE_STORAGE_ACCOUNT_NAME")
            if account:
                options["account_name"] = account
            account_key = self._get_secret_or_env(
                "AZURE_STORAGE_ACCOUNT_KEY")
            if account_key:
                options["account_key"] = account_key
            for field, env in (("client_id", "AZURE_STORAGE_CLIENT_ID"),
                               ("client_secret",
                                "AZURE_STORAGE_CLIENT_SECRET"),
                               ("tenant_id", "AZURE_STORAGE_TENANT_ID")):
                value = self._get_secret_or_env(env)
                if value:
                    options[field] = value
        return options

    def _full(self, key: str) -> str:
        return f"{self.endpoint}{key}" if self.endpoint else key.lstrip("/")

    def get(self, key, size=None, offset=0):
        end = offset + size if size else None
        return self.filesystem.cat_file(self._full(key), start=offset or None,
                                        end=end)

    def put(self, key, data, append=False):
        if append:
            raise ValueError(f"append is not supported on {self.kind} store")
        if isinstance(data, str):
            data = data.encode()
        with self.filesystem.open(self._full(key), "wb") as fp:
            fp.write(data)

    def stat(self, key):
        info = self.filesystem.info(self._full(key))
        return FileStats(size=info.get("size"),
                         modified=info.get("mtime") or info.get("LastModified"))

    def listdir(self, key):
        full = self._full(key).rstrip("/")
        return [p[len(full):].lstrip("/") for p in self.filesystem.ls(full)]

    def delete(self, key):
        self.filesystem.rm(self._full(key))

    def exists(self, key):
        return self.filesystem.exists(self._full(key))


class HttpStore(DataStore):
    """Read-only http(s):// store."""

    def __init__(self, parent, name, kind, endpoint="", secrets=None):
        super().__init__(parent, name, kind, endpoint, secrets)

    def get(self, key, size=None, offset=0):
        import requests

        url = f"{self.kind}://{self.endpoint}{key}"
        resp = requests.get(url, timeout=30)
        resp.raise_for_status()
        data = resp.content
        if offset:
            data = data[offset:]
        if size:
            data = data[:size]
        return data

    def put(self, key, data, append=False):
        raise ValueError("http store is read-only")

    def stat(self, key):
        data = self.get(key)
        return FileStats(size=len(data))

    def listdir(self, key):
        raise ValueError("http store does not support listdir")

    def delete(self, key):
        raise ValueError("http store is read-only")
