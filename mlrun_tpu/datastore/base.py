"""Datastore base classes (reference analog: mlrun/datastore/base.py:48 DataStore,
:424 DataItem — fresh implementation).

A ``DataStore`` is a scheme-keyed backend (file, memory, gcs, s3, ...); a
``DataItem`` is the lazy handle users receive for run inputs and artifacts.
"""

from __future__ import annotations

import os
import tempfile
from typing import Any, Optional
from urllib.parse import urlparse

from ..chaos import fire as chaos_fire


class FileStats:
    def __init__(self, size: int | None = None, modified: float | None = None,
                 content_type: str | None = None):
        self.size = size
        self.modified = modified
        self.content_type = content_type

    def __repr__(self):
        return f"FileStats(size={self.size}, modified={self.modified})"


class DataStore:
    """Abstract storage backend keyed by url scheme."""

    kind = "base"
    using_bucket = False

    def __init__(self, parent, name: str, kind: str, endpoint: str = "",
                 secrets: dict | None = None):
        self._parent = parent
        self.name = name
        self.kind = kind
        self.endpoint = endpoint
        self._secrets = secrets or {}

    def _get_secret_or_env(self, key: str, default: str = "") -> str:
        return self._secrets.get(key) or os.environ.get(key, default)

    # -- required backend api ---------------------------------------------
    def get(self, key: str, size: int | None = None, offset: int = 0) -> bytes:
        raise NotImplementedError

    def put(self, key: str, data: bytes | str, append: bool = False):
        raise NotImplementedError

    def stat(self, key: str) -> FileStats:
        raise NotImplementedError

    def listdir(self, key: str) -> list[str]:
        raise NotImplementedError

    def delete(self, key: str):
        raise NotImplementedError

    def exists(self, key: str) -> bool:
        try:
            self.stat(key)
            return True
        except (FileNotFoundError, KeyError):
            return False

    # -- derived helpers ---------------------------------------------------
    def upload(self, key: str, src_path: str):
        chaos_fire("datastore.write", kind=self.kind, key=key)
        with open(src_path, "rb") as fp:
            self.put(key, fp.read())

    def download(self, key: str, target_path: str):
        chaos_fire("datastore.read", kind=self.kind, key=key)
        data = self.get(key)
        os.makedirs(os.path.dirname(target_path) or ".", exist_ok=True)
        with open(target_path, "wb") as fp:
            fp.write(data if isinstance(data, bytes) else data.encode())

    def url(self, key: str) -> str:
        if self.kind == "file":
            return key
        return f"{self.kind}://{self.endpoint}{key}"

    def as_df(self, key: str, columns=None, df_module=None, format: str = "",
              **kwargs):
        """Load an object into a dataframe (csv/parquet/json by suffix)."""
        import pandas as pd

        df_module = df_module or pd
        fmt = format or key.rsplit(".", 1)[-1].lower()
        import io

        raw = self.get(key)
        buf = io.BytesIO(raw if isinstance(raw, bytes) else raw.encode())
        if fmt in ("csv",):
            df = df_module.read_csv(buf, **kwargs)
        elif fmt in ("parquet", "pq"):
            df = df_module.read_parquet(buf, **kwargs)
        elif fmt == "json":
            df = df_module.read_json(buf, **kwargs)
        else:
            raise ValueError(f"cannot load dataframe from format '{fmt}'")
        if columns:
            df = df[columns]
        return df

    def rm(self, path: str, recursive: bool = False):
        self.delete(path)


class DataItem:
    """Lazy data handle passed to handlers (reference base.py:424)."""

    def __init__(self, key: str, store: DataStore, subpath: str, url: str = "",
                 meta: dict | None = None, artifact_url: str = ""):
        self._key = key
        self._store = store
        self._path = subpath
        self._url = url
        self._meta = meta or {}
        self._artifact_url = artifact_url
        self._local_path = ""

    @property
    def key(self) -> str:
        return self._key

    @property
    def kind(self) -> str:
        return self._store.kind

    @property
    def meta(self) -> dict:
        return self._meta

    @property
    def artifact_url(self) -> str:
        return self._artifact_url or self._url

    @property
    def url(self) -> str:
        return self._url

    @property
    def suffix(self) -> str:
        _, ext = os.path.splitext(self._path)
        return ext

    def get(self, size=None, offset=0, encoding: str | None = None) -> Any:
        chaos_fire("datastore.read", kind=self.kind, key=self._path,
                   url=self._url)
        body = self._store.get(self._path, size=size, offset=offset)
        if encoding and isinstance(body, bytes):
            body = body.decode(encoding)
        return body

    def put(self, data, append: bool = False):
        chaos_fire("datastore.write", kind=self.kind, key=self._path,
                   url=self._url)
        self._store.put(self._path, data, append=append)

    def delete(self):
        self._store.delete(self._path)

    def download(self, target_path: str):
        self._store.download(self._path, target_path)

    def stat(self) -> FileStats:
        return self._store.stat(self._path)

    def exists(self) -> bool:
        return self._store.exists(self._path)

    def listdir(self) -> list[str]:
        return self._store.listdir(self._path)

    def local(self) -> str:
        """Materialize to a local file path (or directory, for artifacts
        uploaded as a file tree) and return it."""
        if self._store.kind == "file":
            return self._path
        if self._local_path:
            return self._local_path
        if not self._store.exists(self._path):
            # a directory prefix (e.g. tensorboard logs): mirror every key
            # under it into a temp dir
            entries = self._store.listdir(self._path)
            if entries:
                local_dir = tempfile.mkdtemp(prefix="mlt-item-")
                prefix = self._path.rstrip("/")
                for entry in entries:
                    target = os.path.join(local_dir, entry)
                    self._store.download(f"{prefix}/{entry}", target)
                self._local_path = local_dir
                return local_dir
        suffix = self.suffix or ".tmp"
        temp = tempfile.NamedTemporaryFile(suffix=suffix, delete=False)
        temp.close()
        self.download(temp.name)
        self._local_path = temp.name
        return self._local_path

    def as_df(self, columns=None, df_module=None, format: str = "", **kwargs):
        return self._store.as_df(self._path, columns=columns,
                                 df_module=df_module, format=format, **kwargs)

    # -- reference-contract parity (mlrun/datastore/base.py DataItem) ------
    @property
    def store(self) -> "DataStore":
        return self._store

    def ls(self) -> list[str]:
        """Alias of listdir (reference base.py ls)."""
        return self.listdir()

    def open(self, mode: str = "rb"):
        """Open the (locally materialized) item as a file object
        (reference base.py open)."""
        return open(self.local(), mode)

    def upload(self, src_path: str):
        """Upload a local file into this item's target (reference
        base.py upload)."""
        self._store.upload(self._path, src_path)

    def remove_local(self):
        """Drop the temp copy created by local() (reference
        base.py remove_local); no-op for file-store items."""
        if self._local_path and self._store.kind != "file":
            if os.path.isdir(self._local_path):
                import shutil

                shutil.rmtree(self._local_path, ignore_errors=True)
            elif os.path.exists(self._local_path):
                os.remove(self._local_path)
            self._local_path = ""

    def get_artifact_type(self) -> Optional[str]:
        """Artifact kind when this item resolves a store:// uri
        (reference base.py get_artifact_type)."""
        return self._meta.get("kind") if self._meta else None

    def show(self):
        from ..utils import logger

        logger.info("data item", url=self._url, kind=self.kind)

    def __str__(self):
        return self._url

    def __repr__(self):
        return f"DataItem({self._url})"


def parse_url(url: str) -> tuple[str, str, str]:
    """Return (scheme, endpoint, path)."""
    parsed = urlparse(url)
    scheme = parsed.scheme or "file"
    endpoint = parsed.netloc
    path = parsed.path
    if scheme == "file" and endpoint:
        path = endpoint + path
        endpoint = ""
    return scheme, endpoint, path


def basic_auth_header(user, password):
    import base64

    token = base64.b64encode(f"{user}:{password}".encode()).decode()
    return {"Authorization": f"Basic {token}"}
