from .base import DataItem, DataStore, FileStats, parse_url  # noqa: F401
from .datastore import StoreManager, register_store, schema_to_store, store_manager  # noqa: F401
from .profiles import (  # noqa: F401
    DatastoreProfile,
    DatastoreProfileAzureBlob,
    DatastoreProfileBasic,
    DatastoreProfileGCS,
    DatastoreProfileRedis,
    DatastoreProfileS3,
    register_temporary_client_datastore_profile,
    remove_temporary_client_datastore_profile,
)
from .sources import (  # noqa: F401
    BigQuerySource,
    CSVSource,
    DataFrameSource,
    HttpSource,
    KafkaSource,
    ParquetSource,
    SnowflakeSource,
    SQLSource,
    StreamSource,
)
from .stores import FileStore, FsspecStore, HttpStore, InMemoryStore  # noqa: F401
from .targets import (  # noqa: F401
    CSVTarget,
    DFTarget,
    KafkaTarget,
    NoSqlTarget,
    ParquetTarget,
    RedisNoSqlTarget,
    SQLTarget,
    StreamTarget,
    TSDBTarget,
)


def get_store_resource(url: str, db=None, secrets: dict | None = None,
                       project: str = ""):
    """Resolve a store:// uri into a DataItem (reference analog:
    mlrun/datastore/store_resources.py get_store_resource)."""
    manager = store_manager if db is None else StoreManager(secrets, db)
    return manager.object(url=url, project=project, secrets=secrets)
