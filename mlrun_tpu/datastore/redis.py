"""Dedicated redis datastore driver (reference analog:
mlrun/datastore/redis.py:25 RedisStore — the backend of the reference's
online feature path).

Keys are plain redis strings under the url path; a parallel ``<key>#t``
member records the write time so ``stat`` can answer ``modified``.
Import-gated on the ``redis`` package (like the reference); the client
is created lazily and cached per store instance.
"""

from __future__ import annotations

import time
from typing import Optional

from .base import DataStore, FileStats


class RedisStore(DataStore):
    kind = "redis"

    def __init__(self, parent, name: str, kind: str, endpoint: str = "",
                 secrets: dict | None = None):
        super().__init__(parent, name, kind, endpoint, secrets)
        self._client = None

    @property
    def client(self):
        if self._client is None:
            try:
                import redis  # gated
            except ImportError as exc:
                raise ImportError(
                    "redis:// urls need the redis package installed"
                ) from exc
            scheme = "rediss" if self.kind == "rediss" else "redis"
            url = f"{scheme}://{self.endpoint or 'localhost:6379'}"
            password = self._get_secret_or_env("REDIS_PASSWORD")
            self._client = redis.from_url(
                url, **({"password": password} if password else {}))
        return self._client

    @staticmethod
    def _key(key: str) -> str:
        return key.lstrip("/")

    def get(self, key, size=None, offset=0) -> bytes:
        value = self.client.get(self._key(key))
        if value is None:
            raise FileNotFoundError(f"redis key {key} not found")
        if offset or size:
            end = (offset + size - 1) if size else -1
            return bytes(value)[offset:None if end == -1 else end + 1]
        return bytes(value)

    def put(self, key, data, append=False):
        data = data.encode() if isinstance(data, str) else bytes(data)
        name = self._key(key)
        if append:
            self.client.append(name, data)
        else:
            self.client.set(name, data)
        self.client.set(f"{name}#t", str(time.time()))

    def stat(self, key) -> FileStats:
        name = self._key(key)
        size = self.client.strlen(name)
        if not size and not self.client.exists(name):
            raise FileNotFoundError(f"redis key {key} not found")
        stamp = self.client.get(f"{name}#t")
        return FileStats(size=int(size),
                         modified=float(stamp) if stamp else None)

    def listdir(self, key) -> list[str]:
        prefix = self._key(key).rstrip("/")
        pattern = f"{prefix}/*" if prefix else "*"
        out = []
        for name in self.client.scan_iter(match=pattern):
            text = name.decode() if isinstance(name, bytes) else name
            if text.endswith("#t"):
                continue
            out.append(text[len(prefix) + 1:] if prefix else text)
        return sorted(out)

    def delete(self, key):
        name = self._key(key)
        self.client.delete(name, f"{name}#t")

    def exists(self, key) -> bool:
        return bool(self.client.exists(self._key(key)))
