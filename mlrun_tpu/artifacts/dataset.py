"""Dataset artifact (reference analog: mlrun/artifacts/dataset.py)."""

from __future__ import annotations

from io import BytesIO

from .base import Artifact, ArtifactSpec

default_preview_rows = 20


class DatasetArtifact(Artifact):
    kind = "dataset"
    _store_prefix = "datasets"

    def __init__(self, key=None, df=None, preview=None, format="parquet",
                 stats=None, target_path=None, **kwargs):
        super().__init__(key, target_path=target_path, format=format, **kwargs)
        self.kind = "dataset"
        self._df = df
        self.spec.extra_data = self.spec.extra_data or {}
        self.header = None
        self.preview = preview
        self.stats = stats

    def before_log(self):
        df = self._df
        if df is None:
            return
        self.header = list(map(str, df.columns)) if hasattr(df, "columns") else None
        n = self.preview if isinstance(self.preview, int) else default_preview_rows
        try:
            preview_df = df.head(n)
            self.preview = [list(map(str, row)) for row in preview_df.itertuples(index=False)]
        except Exception:
            self.preview = None
        try:
            self.stats = {
                col: {
                    "count": int(df[col].count()),
                    "mean": float(df[col].mean()) if df[col].dtype.kind in "if" else None,
                }
                for col in df.columns
            }
        except Exception:
            self.stats = None
        self.spec.extra_data["length"] = len(df)

    def to_dict(self, exclude=None):
        out = super().to_dict(exclude)
        out.setdefault("spec", {})
        for field in ("header", "preview", "stats"):
            value = getattr(self, field, None)
            if value is not None:
                out["spec"][field] = value
        return out

    def get_body(self):
        if self._body is not None:
            return self._body
        if self._df is None:
            return None
        fmt = self.spec.format or "parquet"
        buf = BytesIO()
        if fmt == "csv":
            self._df.to_csv(buf, index=False)
        else:
            self._df.to_parquet(buf, index=False)
        return buf.getvalue()

    @property
    def df(self):
        return self._df


def update_dataset_meta(artifact, from_df=None, **kwargs):
    if from_df is not None:
        artifact._df = from_df
        artifact.before_log()
    for key, value in kwargs.items():
        setattr(artifact, key, value)
    return artifact
