"""Model artifact (reference analog: mlrun/artifacts/model.py).

A model artifact is a directory-ish artifact: a primary model file plus
``extra_data`` side files (metrics json, tokenizer, orbax checkpoint dir, ...)
and framework/algorithm metadata used by serving and monitoring.
"""

from __future__ import annotations

import os
from typing import Optional

from .base import Artifact


class ModelArtifact(Artifact):
    kind = "model"
    _store_prefix = "models"

    def __init__(self, key=None, body=None, model_file=None, model_dir=None,
                 metrics=None, parameters=None, inputs=None, outputs=None,
                 framework=None, algorithm=None, feature_vector=None,
                 feature_weights=None, extra_data=None, **kwargs):
        super().__init__(key, body=body, **kwargs)
        self.kind = "model"
        self.model_file = model_file
        self.model_dir = model_dir
        self.metrics = metrics or {}
        self.parameters = parameters or {}
        self.inputs = inputs or []      # feature schema
        self.outputs = outputs or []    # label schema
        self.framework = framework
        self.algorithm = algorithm
        self.feature_vector = feature_vector
        self.feature_weights = feature_weights
        self.spec.extra_data = extra_data or {}

    def to_dict(self, exclude=None):
        out = super().to_dict(exclude)
        spec = out.setdefault("spec", {})
        for field in ("model_file", "model_dir", "metrics", "parameters",
                      "inputs", "outputs", "framework", "algorithm",
                      "feature_vector", "feature_weights"):
            value = getattr(self, field, None)
            if value:
                spec[field] = value
        return out

    @classmethod
    def from_dict(cls, struct=None, deprecated_fields=None):
        obj = super().from_dict(struct or {})
        spec = (struct or {}).get("spec", {})
        for field in ("model_file", "model_dir", "metrics", "parameters",
                      "inputs", "outputs", "framework", "algorithm",
                      "feature_vector", "feature_weights"):
            if field in spec:
                setattr(obj, field, spec[field])
        return obj

    def before_log(self):
        if self.model_file:
            self.spec.format = self.spec.format or os.path.splitext(
                self.model_file)[-1].lstrip(".")

    def upload(self, data_item_factory=None):
        """Upload model file/dir + extra_data files under target_path."""
        from ..datastore import store_manager

        target = self.spec.target_path
        if not target:
            raise ValueError("model artifact has no target_path")
        if self.get_body() is not None:
            store, path = store_manager.get_or_create_store(
                os.path.join(target, self.model_file or self.key))
            body = self.get_body()
            store.put(path, body)
            self.spec.size = len(body)
            return
        src_dir = self.model_dir or (
            os.path.dirname(self.model_file) if self.model_file else None)
        if self.model_file and os.path.isfile(self.model_file):
            fname = os.path.basename(self.model_file)
            store, path = store_manager.get_or_create_store(
                os.path.join(target, fname))
            store.upload(path, self.model_file)
            self.spec.size = os.path.getsize(self.model_file)
            self.model_file = fname
        elif src_dir and os.path.isdir(src_dir):
            from .base import upload_directory

            self.spec.size, self.spec.hash = upload_directory(target,
                                                              src_dir)
        # upload extra_data values that are local files
        for key, value in list(self.spec.extra_data.items()):
            if isinstance(value, str) and os.path.isfile(value):
                fname = os.path.basename(value)
                store, path = store_manager.get_or_create_store(
                    os.path.join(target, fname))
                store.upload(path, value)
                self.spec.extra_data[key] = os.path.join(target, fname)


def get_model(model_dir: str, suffix: str = "") -> tuple[str, Optional["ModelArtifact"], dict]:
    """Resolve a model uri/dir to (local_model_file, model_artifact, extra_data)
    (reference analog: mlrun/artifacts/model.py get_model)."""
    from ..datastore import store_manager

    model_spec = None
    extra_data = {}
    if model_dir.startswith("store://"):
        item = store_manager.object(url=model_dir)
        meta = item.meta or {}
        model_spec = ModelArtifact.from_dict(meta)
        target = model_spec.spec.target_path
        model_file = os.path.join(target, model_spec.model_file or "")
        item = store_manager.object(url=model_file)
        local = item.local()
        extra_data = model_spec.spec.extra_data or {}
        return local, model_spec, extra_data
    if os.path.isdir(model_dir):
        candidates = [f for f in os.listdir(model_dir)
                      if not suffix or f.endswith(suffix)]
        if not candidates:
            raise FileNotFoundError(f"no model file found in {model_dir}")
        return os.path.join(model_dir, candidates[0]), None, {}
    item = store_manager.object(url=model_dir)
    return item.local(), None, {}
