from .base import Artifact, ArtifactMetadata, ArtifactSpec, LinkArtifact  # noqa: F401
from .dataset import DatasetArtifact, update_dataset_meta  # noqa: F401
from .manager import (  # noqa: F401
    ArtifactManager,
    ArtifactProducer,
    artifact_types,
    dict_to_artifact,
)
from .model import ModelArtifact, get_model  # noqa: F401
from .plots import ChartArtifact, PlotArtifact, TableArtifact  # noqa: F401
