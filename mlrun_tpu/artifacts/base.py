"""Artifact model (reference analog: mlrun/artifacts/base.py:179 Artifact,
:833 target-path generation — fresh implementation)."""

from __future__ import annotations

import hashlib
import os
import pathlib
from typing import Any, Optional

from ..model import ModelObj
from ..utils import generate_uid, now_iso


class ArtifactMetadata(ModelObj):
    _dict_fields = ["key", "project", "iter", "tree", "tag", "labels",
                    "annotations", "created", "updated", "uid"]

    def __init__(self, key=None, project=None, iter=None, tree=None, tag=None,
                 labels=None, annotations=None, created=None, updated=None,
                 uid=None):
        self.key = key
        self.project = project
        self.iter = iter or 0
        self.tree = tree  # producer id (run uid)
        self.tag = tag
        self.labels = labels or {}
        self.annotations = annotations or {}
        self.created = created
        self.updated = updated
        self.uid = uid


class ArtifactSpec(ModelObj):
    _dict_fields = ["src_path", "target_path", "viewer", "format", "size", "db_key",
                    "extra_data", "unpackaging_instructions", "producer", "hash"]

    def __init__(self, src_path=None, target_path=None, viewer=None, format=None,
                 size=None, db_key=None, extra_data=None,
                 unpackaging_instructions=None, producer=None, hash=None):
        self.src_path = src_path
        self.target_path = target_path
        self.viewer = viewer
        self.format = format
        self.size = size
        self.db_key = db_key
        self.extra_data = extra_data or {}
        self.unpackaging_instructions = unpackaging_instructions
        self.producer = producer
        self.hash = hash


class ArtifactStatus(ModelObj):
    _dict_fields = ["state", "stats"]

    def __init__(self, state="created", stats=None):
        self.state = state
        self.stats = stats


def upload_directory(target: str, src_dir: str) -> tuple[int, str]:
    """Upload a local directory tree file-by-file under a target prefix
    (shared by base/model artifacts). Returns (total_size, tree_hash) —
    the hash digests sorted (relpath, file_sha1) pairs so identical trees
    compare equal."""
    from ..datastore import store_manager

    store, prefix = store_manager.get_or_create_store(target)
    prefix = prefix.rstrip("/")
    total = 0
    digest = hashlib.sha1()
    entries = []
    for root, _, files in os.walk(src_dir):
        for name in files:
            full = os.path.join(root, name)
            rel = os.path.relpath(full, src_dir)
            entries.append((rel, full))
    for rel, full in sorted(entries):
        store.upload(f"{prefix}/{rel}", full)
        total += os.path.getsize(full)
        with open(full, "rb") as fp:
            digest.update(rel.encode())
            digest.update(hashlib.sha1(fp.read()).digest())
    return total, digest.hexdigest()


class Artifact(ModelObj):
    kind = "artifact"
    _dict_fields = ["kind", "metadata", "spec", "status"]
    _nested_fields = {"metadata": ArtifactMetadata, "spec": ArtifactSpec,
                      "status": ArtifactStatus}
    _store_prefix = "artifacts"

    def __init__(self, key=None, body=None, local_path=None, target_path=None,
                 viewer=None, format=None, project=None, metadata=None, spec=None,
                 status=None):
        self.metadata = metadata or ArtifactMetadata(key=key, project=project)
        self.spec = spec or ArtifactSpec(src_path=local_path,
                                         target_path=target_path,
                                         viewer=viewer, format=format)
        self.status = status or ArtifactStatus()
        self._body = body

    # convenience accessors
    @property
    def key(self):
        return self.metadata.key

    @property
    def target_path(self):
        return self.spec.target_path

    @target_path.setter
    def target_path(self, value):
        self.spec.target_path = value

    @property
    def uri(self) -> str:
        uri = f"store://{self._store_prefix}/{self.metadata.project}/{self.metadata.key}"
        if self.metadata.tag:
            uri += f":{self.metadata.tag}"
        if self.metadata.tree:
            uri += f"@{self.metadata.tree}"
        return uri

    def get_body(self):
        return self._body

    def before_log(self):
        """Hook for subtypes to finalize spec before upload/registration."""

    def generate_target_path(self, artifact_path: str, producer=None) -> str:
        """Compute target path under the run artifact path (base.py:833 analog)."""
        suffix = ""
        if self.spec.src_path:
            suffix = pathlib.Path(self.spec.src_path).suffix
        elif self.spec.format:
            suffix = f".{self.spec.format}"
        version = self.metadata.tree or "0"
        return os.path.join(
            artifact_path, f"{self.metadata.key}{('-' + version[:8]) if version else ''}{suffix}"
        ).replace("\\", "/")

    def upload(self, data_item_factory=None):
        """Write body or src file to target_path via the datastore layer."""
        from ..datastore import store_manager

        target = self.spec.target_path
        if not target:
            raise ValueError(f"artifact {self.key} has no target_path")
        body = self.get_body()
        if body is not None:
            if isinstance(body, (dict, list)):
                import json

                body = json.dumps(body, default=str)
            store, path = store_manager.get_or_create_store(target)
            store.put(path, body)
            raw = body.encode() if isinstance(body, str) else body
            self.spec.size = len(raw)
            self.spec.hash = hashlib.sha1(raw).hexdigest()
        elif self.spec.src_path and os.path.isfile(self.spec.src_path):
            store, path = store_manager.get_or_create_store(target)
            store.upload(path, self.spec.src_path)
            self.spec.size = os.path.getsize(self.spec.src_path)
            with open(self.spec.src_path, "rb") as fp:
                self.spec.hash = hashlib.sha1(fp.read()).hexdigest()
        elif self.spec.src_path and os.path.isdir(self.spec.src_path):
            # directory artifacts (tensorboard logs, checkpoints): upload
            # the tree file by file under the target prefix
            self.spec.size, self.spec.hash = upload_directory(
                target, self.spec.src_path)

    def to_dataitem(self):
        from ..datastore import store_manager

        return store_manager.object(url=self.spec.target_path, key=self.key)


class LinkArtifact(Artifact):
    """Points the parent key at a best-iteration child (reference base.py link)."""

    kind = "link"
    _dict_fields = Artifact._dict_fields

    def __init__(self, key=None, link_iteration=None, link_key=None,
                 link_tree=None, **kwargs):
        super().__init__(key, **kwargs)
        self.spec.extra_data = {
            "link_iteration": link_iteration,
            "link_key": link_key,
            "link_tree": link_tree,
        }
