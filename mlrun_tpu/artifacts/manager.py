"""ArtifactManager (reference analog: mlrun/artifacts/manager.py:117).

Owns the log-artifact flow: resolve target path → subtype before_log() →
upload → register in the run DB → record uri on the producing run.
"""

from __future__ import annotations

import os
from typing import Optional, Union

from ..utils import generate_uid, logger, now_iso, template_artifact_path
from .base import Artifact, LinkArtifact
from .dataset import DatasetArtifact
from .model import ModelArtifact
from .plots import ChartArtifact, PlotArtifact, TableArtifact

artifact_types: dict[str, type] = {
    "": Artifact,
    "artifact": Artifact,
    "dataset": DatasetArtifact,
    "model": ModelArtifact,
    "plot": PlotArtifact,
    "chart": ChartArtifact,
    "table": TableArtifact,
    "link": LinkArtifact,
}


def dict_to_artifact(struct: dict) -> Artifact:
    kind = struct.get("kind", "")
    cls = artifact_types.get(kind, Artifact)
    return cls.from_dict(struct)


class ArtifactProducer:
    def __init__(self, kind: str, project: str, name: str, tag: str | None = None,
                 owner: str | None = None, uid: str | None = None):
        self.kind = kind
        self.project = project
        self.name = name
        self.tag = tag
        self.owner = owner
        self.uid = uid or generate_uid()
        self.inputs = {}

    def get_meta(self) -> dict:
        return {"kind": self.kind, "name": self.name, "tag": self.tag,
                "owner": self.owner, "uri": f"{self.project}/{self.uid}"}


class ArtifactManager:
    def __init__(self, db=None, calc_hash: bool = True):
        self.artifact_db = db
        self.calc_hash = calc_hash
        self.artifacts: dict[str, Artifact] = {}
        self.artifact_uris: dict[str, str] = {}

    def artifact_list(self, full: bool = False) -> list:
        return [a.to_dict() if full else {
            "key": a.key, "kind": a.kind, "uri": a.uri,
            "target_path": a.spec.target_path,
        } for a in self.artifacts.values()]

    def log_artifact(self, producer: ArtifactProducer,
                     item: Union[str, Artifact], body=None, target_path: str = "",
                     tag: str = "", viewer: str = "", local_path: str = "",
                     artifact_path: str | None = None, format: str | None = None,
                     upload: bool | None = None, labels: dict | None = None,
                     db_key: str | None = None, is_retained_producer=None,
                     unpackaging_instructions: dict | None = None,
                     **kwargs) -> Artifact:
        if isinstance(item, str):
            key = item
            if body is not None and not isinstance(body, (str, bytes, dict, list)):
                item = DatasetArtifact(key, df=body, format=format or "parquet")
            else:
                item = Artifact(key, body=body, viewer=viewer, format=format)
        else:
            key = item.key
            if body is not None:
                item._body = body

        meta = item.metadata
        meta.project = meta.project or producer.project
        meta.tree = meta.tree or producer.uid
        meta.tag = tag or meta.tag or "latest"
        meta.uid = meta.uid or generate_uid()
        meta.created = meta.created or now_iso()
        meta.updated = now_iso()
        if labels:
            meta.labels.update(labels)
        item.spec.src_path = local_path or item.spec.src_path
        item.spec.db_key = db_key or key
        item.spec.producer = producer.get_meta()
        if unpackaging_instructions:
            # stamped on the FIRST store (the packagers manager records
            # how to reconstruct the packed object without a type hint)
            item.spec.unpackaging_instructions = unpackaging_instructions

        item.before_log()

        if target_path:
            item.spec.target_path = target_path
        elif not item.spec.target_path:
            artifact_path = template_artifact_path(
                artifact_path or "", producer.project, producer.uid)
            if not artifact_path:
                from ..config import mlconf

                artifact_path = mlconf.resolve_artifact_path(producer.project)
            item.spec.target_path = item.generate_target_path(
                artifact_path, producer)

        model_file = getattr(item, "model_file", None)
        model_dir = getattr(item, "model_dir", None)
        should_upload = upload if upload is not None else (
            item.get_body() is not None
            or (item.spec.src_path
                and os.path.exists(item.spec.src_path))  # file OR directory
            # model artifacts carry their payload in model_file/model_dir,
            # not src_path — without this the model stays a dangling local
            # path and can never be served from another machine
            or (model_file and os.path.isfile(model_file))
            or (model_dir and os.path.isdir(model_dir))
        )
        if should_upload:
            try:
                item.upload()
            except Exception as exc:  # noqa: BLE001
                logger.warning("artifact upload failed", key=key, error=str(exc))

        item.status.state = "created"
        if self.artifact_db:
            self.artifact_db.store_artifact(
                item.spec.db_key, item.to_dict(), uid=meta.uid,
                iter=meta.iter, tag=meta.tag, project=meta.project,
                tree=meta.tree,
            )
        self.artifacts[key] = item
        self.artifact_uris[key] = item.uri
        return item

    def link_artifact(self, producer: ArtifactProducer, key: str,
                      iteration: int, link_key: str | None = None,
                      artifact_path: str = ""):
        link = LinkArtifact(
            key, link_iteration=iteration, link_key=link_key or key,
            link_tree=producer.uid,
        )
        link.metadata.project = producer.project
        link.metadata.tree = producer.uid
        link.spec.target_path = ""
        if self.artifact_db:
            self.artifact_db.store_artifact(
                key, link.to_dict(), uid=generate_uid(), iter=0,
                tag="latest", project=producer.project, tree=producer.uid,
            )
        return link
