"""Plot/chart artifacts (reference analog: mlrun/artifacts/plots.py)."""

from __future__ import annotations

import base64
import json

from .base import Artifact


class PlotArtifact(Artifact):
    """A matplotlib-figure artifact rendered to an html <img> page."""

    kind = "plot"

    def __init__(self, key=None, body=None, title=None, **kwargs):
        super().__init__(key, body=body, format="html", **kwargs)
        self.kind = "plot"
        self.title = title

    def get_body(self):
        body = self._body
        if body is None:
            return None
        if hasattr(body, "savefig"):  # a figure or pyplot module
            from io import BytesIO

            buf = BytesIO()
            body.savefig(buf, format="png", bbox_inches="tight")
            data = base64.b64encode(buf.getvalue()).decode()
            title = self.title or self.key
            return (
                f"<html><head><title>{title}</title></head><body>"
                f"<h3>{title}</h3><img src=\"data:image/png;base64,{data}\">"
                "</body></html>"
            )
        return body


class ChartArtifact(Artifact):
    """Tabular chart artifact rendered with a simple html table fallback."""

    kind = "chart"

    def __init__(self, key=None, data=None, header=None, options=None, **kwargs):
        super().__init__(key, format="html", **kwargs)
        self.kind = "chart"
        self.header = header or []
        self.options = options or {}
        self._rows = []
        if data:
            for row in data:
                self.add_row(row)

    def add_row(self, row):
        self._rows.append(list(row))

    def get_body(self):
        rows = self._rows
        header = self.header or (rows[0] if rows else [])
        body_rows = rows if not self.header else rows
        head_html = "".join(f"<th>{h}</th>" for h in header)
        rows_html = "".join(
            "<tr>" + "".join(f"<td>{c}</td>" for c in row) + "</tr>"
            for row in body_rows
        )
        return (
            f"<html><body><table border=1><tr>{head_html}</tr>{rows_html}"
            "</table></body></html>"
        )


class BokehArtifact(Artifact):
    kind = "bokeh"


class TableArtifact(Artifact):
    """CSV/table body artifact (reference mlrun/artifacts/base.py TableArtifact)."""

    kind = "table"

    def __init__(self, key=None, body=None, df=None, viewer="table", **kwargs):
        if df is not None:
            body = df.to_csv(index=False)
            kwargs.setdefault("format", "csv")
        super().__init__(key, body=body, viewer=viewer, **kwargs)
        self.kind = "table"
