"""Execution-resource providers (reference analog:
server/api/utils/singletons/k8s.py K8sHelper + the fake local tier the
reference tests with K8sHelperMock, tests/api/conftest.py:208).

Providers decouple "what resource to create" from "where": the
``KubernetesProvider`` creates pods/JobSets/Deployments via the k8s API
(gated on the kubernetes package); the ``LocalProcessProvider`` executes
the same `mlrun-tpu run --from-env` contract as subprocesses so the full
submit -> pod -> run -> logs path works on a single machine.
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading

from ..chaos import fire as chaos_fire
from ..common.runtimes_constants import (
    JobSetConditions,
    PodPhases,
    RunStates,
)
from ..config import mlconf


# CRD kinds the kubernetes provider speaks: kind -> (group, version,
# plural); _CRD_BY_LOWER keys by the resource-id prefix
_CRD_KINDS = {
    "JobSet": ("jobset.x-k8s.io", "v1alpha2", "jobsets"),
    "SparkApplication": ("sparkoperator.k8s.io", "v1beta2",
                         "sparkapplications"),
}
_CRD_BY_LOWER = {k.lower(): v for k, v in _CRD_KINDS.items()}


def _extract_pod_spec(resource: dict) -> dict:
    if resource.get("kind") == "JobSet":
        return resource["spec"]["replicatedJobs"][0]["template"]["spec"][
            "template"]["spec"]
    if resource.get("kind") == "Deployment":
        return resource["spec"]["template"]["spec"]
    return resource.get("spec", resource)


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except OSError:
        return False
    return True


def _proc_start_ticks(pid: int) -> int:
    """Kernel start time (jiffies since boot, /proc/<pid>/stat field 22) —
    a stable process identity that survives pid reuse. 0 when unavailable
    (non-linux), which degrades to pid-only liveness."""
    try:
        with open(f"/proc/{pid}/stat", "rb") as f:
            stat = f.read().decode(errors="replace")
        return int(stat.rsplit(") ", 1)[1].split()[19])
    except (OSError, ValueError, IndexError):
        return 0



class Provider:
    """Creates/inspects/deletes execution resources."""

    kind = "base"

    def create(self, resource: dict, run_uid: str) -> str:
        raise NotImplementedError

    def state(self, resource_id: str) -> str:
        raise NotImplementedError

    def delete(self, resource_id: str):
        raise NotImplementedError

    def logs(self, resource_id: str, offset: int = 0) -> bytes:
        return b""


class LocalProcessProvider(Provider):
    """Runs the pod command as a local subprocess (dev/single-host mode)."""

    kind = "local-process"

    def __init__(self, db):
        self._db = db
        self._procs: dict[str, subprocess.Popen] = {}
        self._threads: dict[str, threading.Thread] = {}
        self._lock = threading.Lock()

    def create(self, resource: dict, run_uid: str) -> str:
        chaos_fire("provider.create", kind=self.kind, run_uid=run_uid,
                   resource=resource)
        pod_spec = _extract_pod_spec(resource)
        container = pod_spec["containers"][0]
        env = dict(os.environ)
        for item in container.get("env", []):
            if "value" in item:
                env[item["name"]] = str(item["value"])
        # single-process resource = rank 0 (skips jax probing in the ctx)
        env.setdefault("MLT_WORKER_RANK", "0")
        # execution happens in-process-tree: swap the container entry for
        # the same CLI contract
        command = container.get("command") or ["mlrun-tpu", "run",
                                               "--from-env"]
        if command[0] in ("mlrun-tpu", "mlrun_tpu"):
            command = [sys.executable, "-m", "mlrun_tpu"] + command[1:]
        args = container.get("args", [])
        project = resource.get("metadata", {}).get("labels", {}).get(
            "mlrun-tpu/project", "")

        proc = subprocess.Popen(
            command + list(args), env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, cwd=container.get("workingDir") or None)
        # fingerprint with the kernel start time so a recovered resource id
        # can never be confused with a recycled pid
        resource_id = f"proc-{proc.pid}-{_proc_start_ticks(proc.pid)}"
        with self._lock:
            self._procs[resource_id] = proc

        def pump():
            for line in proc.stdout:
                try:
                    self._db.store_log(run_uid, project, line)
                except Exception:  # noqa: BLE001
                    pass
            proc.wait()

        thread = threading.Thread(target=pump, daemon=True)
        thread.start()
        self._threads[resource_id] = thread
        return resource_id

    def state(self, resource_id: str) -> str:
        chaos_fire("provider.state", kind=self.kind,
                   resource_id=resource_id)
        proc = self._procs.get(resource_id)
        if proc is None:
            # recovered resource from a previous service process: the Popen
            # handle is gone, but pid + start-time fingerprint tell us
            # whether the same process still runs (the run itself reports
            # its state over HTTP, so liveness is all the monitor needs)
            if self._recovered_alive(resource_id):
                return PodPhases.running
            return PodPhases.failed
        code = proc.poll()
        if code is None:
            return PodPhases.running
        return PodPhases.succeeded if code == 0 else PodPhases.failed

    def delete(self, resource_id: str):
        chaos_fire("provider.delete", kind=self.kind,
                   resource_id=resource_id)
        proc = self._procs.pop(resource_id, None)
        if proc is not None:
            if proc.poll() is None:
                proc.terminate()
            return
        if self._recovered_alive(resource_id):
            pid, _ = self._pid_of(resource_id)
            try:
                os.kill(pid, 15)
            except OSError:
                pass

    @classmethod
    def _recovered_alive(cls, resource_id: str) -> bool:
        """True only when the pid is alive AND (when recorded) its kernel
        start time matches — a recycled pid never counts as the run."""
        pid, ticks = cls._pid_of(resource_id)
        if not pid or not _pid_alive(pid):
            return False
        return ticks == 0 or _proc_start_ticks(pid) == ticks

    @staticmethod
    def _pid_of(resource_id: str) -> tuple[int, int]:
        if resource_id.startswith("proc-"):
            parts = resource_id[5:].split("-")
            try:
                pid = int(parts[0])
                ticks = int(parts[1]) if len(parts) > 1 else 0
                return pid, ticks
            except ValueError:
                return 0, 0
        return 0, 0


class KubernetesProvider(Provider):
    """Creates real pods / JobSet CRDs (requires the kubernetes package)."""

    kind = "kubernetes"

    def __init__(self, namespace: str | None = None):
        import kubernetes  # gated import

        kubernetes.config.load_incluster_config() \
            if os.environ.get("KUBERNETES_SERVICE_HOST") \
            else kubernetes.config.load_kube_config()
        self._core = kubernetes.client.CoreV1Api()
        self._custom = kubernetes.client.CustomObjectsApi()
        self.namespace = namespace or mlconf.namespace

    # the ONE registry of CRD kinds the provider speaks (create/state/
    # delete/list all read it): kind -> (group, version, plural).
    # SparkApplication is the spark-operator contract
    # (runtimes/sparkjob.py generate_spark_application)
    CRD_KINDS = _CRD_KINDS

    def create(self, resource: dict, run_uid: str) -> str:
        chaos_fire("provider.create", kind=self.kind, run_uid=run_uid,
                   resource=resource)
        kind = resource.get("kind")
        if kind in self.CRD_KINDS:
            group, version, plural = self.CRD_KINDS[kind]
            self._custom.create_namespaced_custom_object(
                group, version, self.namespace, plural, resource)
            return f"{kind.lower()}/{resource['metadata']['name']}"
        if resource.get("kind") == "Deployment":
            # long-running gateway Deployments (service/deployments.py) —
            # replicas come from the function's min_replicas
            import kubernetes

            kubernetes.client.AppsV1Api(
                self._core.api_client).create_namespaced_deployment(
                self.namespace, resource)
            return f"deployment/{resource['metadata']['name']}"
        self._core.create_namespaced_pod(self.namespace, resource)
        return f"pod/{resource['metadata']['name']}"

    def create_service(self, manifest: dict) -> str:
        """Create/replace the Service fronting a gateway Deployment."""
        import kubernetes

        name = manifest["metadata"]["name"]
        try:
            self._core.replace_namespaced_service(name, self.namespace,
                                                  manifest)
        except kubernetes.client.exceptions.ApiException as exc:
            if exc.status != 404:
                raise
            self._core.create_namespaced_service(self.namespace, manifest)
        return name

    def state(self, resource_id: str) -> str:
        chaos_fire("provider.state", kind=self.kind,
                   resource_id=resource_id)
        kind, _, name = resource_id.partition("/")
        if kind == "deployment":
            import kubernetes

            dep = kubernetes.client.AppsV1Api(
                self._core.api_client).read_namespaced_deployment(
                name, self.namespace)
            status = dep.status
            if (getattr(status, "available_replicas", 0) or 0) >= 1:
                return PodPhases.running
            # distinguish "rolling out" from "dead": a deployment whose
            # pods are crash-looping still reports 0 available
            conditions = getattr(status, "conditions", None) or []
            for cond in conditions:
                if (getattr(cond, "type", "") == "Progressing"
                        and getattr(cond, "status", "") == "False"):
                    return PodPhases.failed
            return PodPhases.pending
        if kind == "jobset":
            group, version, plural = _CRD_BY_LOWER["jobset"]
            obj = self._custom.get_namespaced_custom_object(
                group, version, self.namespace, plural, name)
            run_state = JobSetConditions.to_run_state(
                obj.get("status", {}).get("conditions", []))
            return {
                RunStates.completed: PodPhases.succeeded,
                RunStates.error: PodPhases.failed,
                RunStates.pending: PodPhases.pending,
            }.get(run_state, PodPhases.running)
        if kind == "sparkapplication":
            group, version, plural = _CRD_BY_LOWER["sparkapplication"]
            obj = self._custom.get_namespaced_custom_object(
                group, version, self.namespace, plural, name)
            # spark-operator applicationState.state contract
            app_state = (obj.get("status", {})
                         .get("applicationState", {})
                         .get("state", "")).upper()
            return {
                "COMPLETED": PodPhases.succeeded,
                "FAILED": PodPhases.failed,
                "SUBMISSION_FAILED": PodPhases.failed,
                "FAILING": PodPhases.failed,
                "": PodPhases.pending,
                "NEW": PodPhases.pending,
                "SUBMITTED": PodPhases.pending,
                "PENDING_RERUN": PodPhases.pending,
            }.get(app_state, PodPhases.running)
        pod = self._core.read_namespaced_pod(name, self.namespace)
        return pod.status.phase

    def delete(self, resource_id: str):
        chaos_fire("provider.delete", kind=self.kind,
                   resource_id=resource_id)
        kind, _, name = resource_id.partition("/")
        crd = _CRD_BY_LOWER.get(kind)
        if crd:
            group, version, plural = crd
            self._custom.delete_namespaced_custom_object(
                group, version, self.namespace, plural, name)
        elif kind == "deployment":
            import kubernetes

            kubernetes.client.AppsV1Api(
                self._core.api_client).delete_namespaced_deployment(
                name, self.namespace)
            # the fronting Service shares the Deployment's name
            try:
                self._core.delete_namespaced_service(name, self.namespace)
            except kubernetes.client.exceptions.ApiException as exc:
                if exc.status != 404:
                    raise
        else:
            self._core.delete_namespaced_pod(name, self.namespace)

    # -- slice elasticity (docs/fault_tolerance.md "Elastic training") ------
    def slice_status(self, resource_id: str) -> dict:
        """Per-slice health of a multi-slice JobSet: ``{"failed_slices":
        [indices], "replicas": N}`` (empty dict for non-JobSet
        resources). The contract field is ``status.failedSlices`` — the
        fake cluster maintains it directly; a production deployment
        derives it from the JobSet controller's child-Job states (the
        stock ``replicatedJobsStatus`` carries counts, not indices, so a
        real watcher enumerates child Jobs ``<name>-slice-<i>``). This is
        what lets ``monitor_runs`` tell "one slice gone, job alive"
        (elastic replacement) from "job dead" (full resubmit)."""
        kind, _, name = resource_id.partition("/")
        if kind != "jobset":
            return {}
        group, version, plural = _CRD_BY_LOWER["jobset"]
        obj = self._custom.get_namespaced_custom_object(
            group, version, self.namespace, plural, name)
        status = obj.get("status", {}) or {}
        failed = status.get("failedSlices") or []
        jobs = obj.get("spec", {}).get("replicatedJobs") or [{}]
        replicas = int(jobs[0].get("replicas", 1) or 1)
        annotations = obj.get("metadata", {}).get("annotations") or {}
        return {"failed_slices": sorted(int(s) for s in failed),
                "replicas": replicas,
                # the with_elastic() opt-in, carried on the resource so
                # a restarted service still honors it
                "elastic": annotations.get("mlrun-tpu/elastic") == "true"}

    def replace_slice(self, resource_id: str, slice_index: int,
                      extra_env: dict | None = None) -> str:
        """Submit a replacement for ONE preempted slice of a live JobSet
        — the survivors keep running. ``extra_env`` (checkpoint-resume +
        compile-cache env) is upserted into the JobSet's pod template
        first, so the replacement pod joins warm; then the failed child
        Job is deleted and the JobSet controller recreates it from the
        updated template. Returns the child-Job name."""
        chaos_fire("provider.replace_slice", kind=self.kind,
                   resource_id=resource_id, slice_index=slice_index)
        kind, _, name = resource_id.partition("/")
        if kind != "jobset":
            raise ValueError(
                f"slice replacement only applies to JobSets, not "
                f"'{resource_id}'")
        group, version, plural = _CRD_BY_LOWER["jobset"]
        if extra_env:
            obj = self._custom.get_namespaced_custom_object(
                group, version, self.namespace, plural, name)
            jobs = obj.get("spec", {}).get("replicatedJobs") or []
            for job in jobs:
                pod_spec = (job.get("template", {}).get("spec", {})
                            .get("template", {}).get("spec", {}))
                for container in pod_spec.get("containers", []):
                    env = container.setdefault("env", [])
                    for key, value in extra_env.items():
                        for existing in env:
                            if existing.get("name") == key:
                                existing["value"] = str(value)
                                break
                        else:
                            env.append({"name": key, "value": str(value)})
            self._custom.patch_namespaced_custom_object(
                group, version, self.namespace, plural, name,
                {"spec": {"replicatedJobs": jobs}})
        import kubernetes

        child = f"{name}-slice-{int(slice_index)}"
        kubernetes.client.BatchV1Api(
            self._core.api_client).delete_namespaced_job(
            child, self.namespace)
        return child

    def ensure_project_secret(self, project: str, secrets: dict) -> str:
        """Create/replace the project's k8s Secret and return its name."""
        import base64

        import kubernetes

        name = f"mlrun-tpu-secrets-{project}"
        body = kubernetes.client.V1Secret(
            metadata=kubernetes.client.V1ObjectMeta(
                name=name, labels={"mlrun-tpu/project": project}),
            data={k: base64.b64encode(str(v).encode()).decode()
                  for k, v in secrets.items()})
        try:
            self._core.replace_namespaced_secret(name, self.namespace, body)
        except kubernetes.client.exceptions.ApiException as exc:
            if exc.status != 404:
                raise
            self._core.create_namespaced_secret(self.namespace, body)
        return name

    def delete_project_secret(self, project: str):
        import kubernetes

        try:
            self._core.delete_namespaced_secret(
                f"mlrun-tpu-secrets-{project}", self.namespace)
        except kubernetes.client.exceptions.ApiException as exc:
            if exc.status != 404:
                raise

    def list_resources(self, class_label: str) -> list[tuple[str, str, str]]:
        """Discover live cluster resources by label selector (reference
        base.py:65,189 recovers handler state the same way). Returns
        (resource_id, run_uid, project) triples. Listing is PAGINATED via
        the k8s continue token so a large cluster can't blow one response
        (reference paginates the same way)."""
        selector = f"mlrun-tpu/class={class_label}"
        found = []
        token = None
        while True:
            pods = self._core.list_namespaced_pod(
                self.namespace, label_selector=selector, limit=500,
                _continue=token)
            for pod in pods.items:
                labels = pod.metadata.labels or {}
                found.append((f"pod/{pod.metadata.name}",
                              labels.get("mlrun-tpu/uid", ""),
                              labels.get("mlrun-tpu/project", "")))
            token = getattr(pods.metadata, "_continue", None) or getattr(
                pods.metadata, "continue_", None)
            if not token:
                break
        for crd_kind, (group, version, plural) in _CRD_KINDS.items():
            token = None
            while True:
                objs = self._custom.list_namespaced_custom_object(
                    group, version, self.namespace, plural,
                    label_selector=selector, limit=500,
                    **({"_continue": token} if token else {}))
                for obj in objs.get("items", []):
                    labels = obj.get("metadata", {}).get("labels", {})
                    found.append(
                        (f"{crd_kind.lower()}/{obj['metadata']['name']}",
                         labels.get("mlrun-tpu/uid", ""),
                         labels.get("mlrun-tpu/project", "")))
                token = objs.get("metadata", {}).get("continue")
                if not token:
                    break
        return [f for f in found if f[1]]

    def list_serving_jobsets(self) -> dict[str, dict]:
        """The observed world for control-plane reconciliation: every
        serving JobSet (``mlrun-tpu/serving`` annotation) actually on the
        cluster, name → manifest. A restarted ``ServingPodFleet`` diffs
        this against its replayed intent journal (docs/fault_tolerance.md
        "Control-plane crash recovery"). Paginated like
        :meth:`list_resources`."""
        from ..k8s.jobset import SERVING_ANNOTATION

        group, version, plural = _CRD_BY_LOWER["jobset"]
        found: dict[str, dict] = {}
        token = None
        while True:
            objs = self._custom.list_namespaced_custom_object(
                group, version, self.namespace, plural,
                limit=500, **({"_continue": token} if token else {}))
            for obj in objs.get("items", []):
                meta = obj.get("metadata", {})
                annotations = meta.get("annotations", {}) or {}
                if annotations.get(SERVING_ANNOTATION) != "true":
                    continue
                found[meta.get("name", "")] = obj
            token = objs.get("metadata", {}).get("continue")
            if not token:
                break
        found.pop("", None)
        return found


