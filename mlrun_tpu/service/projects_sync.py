"""Projects leader/follower sync.

Reference analog: server/api/utils/projects/leader.py:42 (Member owning the
project lifecycle) and follower.py:46 (periodic ``_sync_projects`` pulling
the leader's project list and reconciling the local store). Here any
mlrun-tpu service acts as leader by default; pointing
``mlconf.projects.leader_url`` at another service turns this instance into
a follower: the sync loop upserts the leader's projects into the local DB
and archives local projects the leader no longer has, while project
mutations are forwarded leader-first.
"""

from __future__ import annotations

from ..config import mlconf
from ..utils import logger


class ProjectsFollower:
    def __init__(self, db, leader_url: str = ""):
        self.db = db
        self.leader_url = leader_url or mlconf.projects.leader_url
        self._leader_db = None

    @property
    def enabled(self) -> bool:
        return bool(self.leader_url)

    def _leader(self):
        if self._leader_db is None:
            from ..db.httpdb import HTTPRunDB

            self._leader_db = HTTPRunDB(self.leader_url)
        return self._leader_db

    def forward_store(self, name: str, project: dict) -> dict:
        """Leader-first create/update (reference follower create flow)."""
        stored = self._leader().store_project(name, project)
        self.db.store_project(name, stored or project)
        return stored or project

    def forward_delete(self, name: str,
                       deletion_strategy: str = "restricted"):
        self._leader().delete_project(name,
                                      deletion_strategy=deletion_strategy)
        self.db.delete_project(name, deletion_strategy=deletion_strategy)

    def sync_once(self) -> dict:
        """One reconciliation pass; returns counters (for tests/ops)."""
        leader_projects = {p["metadata"]["name"]
                          if isinstance(p.get("metadata"), dict)
                          else p.get("name"): p
                          for p in self._leader().list_projects()}
        leader_projects.pop(None, None)
        local = {p.get("metadata", {}).get("name") or p.get("name"): p
                 for p in self.db.list_projects()}
        created = updated = archived = 0
        for name, project in leader_projects.items():
            if name not in local:
                self.db.store_project(name, project)
                created += 1
            elif local[name] != project:
                self.db.store_project(name, project)
                updated += 1
        for name, project in local.items():
            if name in leader_projects or name == mlconf.default_project:
                continue
            # the leader no longer has it → archive locally (never a hard
            # delete from a sync pass; reference archives on desync too)
            if not isinstance(project.get("status"), dict):
                project["status"] = {}
            if project["status"].get("state") != "archived":
                project["status"]["state"] = "archived"
                self.db.store_project(name, project)
                archived += 1
        return {"created": created, "updated": updated,
                "archived": archived}

    def sync_safe(self):
        try:
            counters = self.sync_once()
            if any(counters.values()):
                logger.info("projects synced from leader",
                            leader=self.leader_url, **counters)
        except Exception as exc:  # noqa: BLE001 - keep the loop alive
            logger.warning("projects sync failed", leader=self.leader_url,
                           error=str(exc))
