"""``python -m mlrun_tpu.service`` — start the orchestration service
(same entry as the ``mlrun-tpu db`` CLI command)."""

import argparse

from .app import run_app


def main():
    parser = argparse.ArgumentParser(description="mlrun-tpu API service")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--host", default="")
    args = parser.parse_args()
    run_app(host=args.host, port=args.port)


if __name__ == "__main__":
    main()
