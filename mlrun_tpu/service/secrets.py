"""Server-side secrets helpers — notification-param masking and runtime
injection.

Reference analog: server/api/api/utils.py:221-300 (mask_notification_params
stores notification secret-params in the project secret store and replaces
them with a secret reference) and the per-runtime secret env projection.
Secret VALUES live in the DB-backed project-secret store (db/sqlitedb.py)
and never cross the REST list surface.
"""

from __future__ import annotations

import json

from ..utils import logger

NOTIFICATION_SECRET_PREFIX = "mlrun.notifications."
SECRET_ENV_PREFIX = "MLT_SECRET_"


def mask_notification_params(db, run) -> None:
    """Move each notification's params into a project secret and replace
    them with ``{"secret": <key>}`` before the run spec is stored or
    shipped to a resource."""
    store = getattr(db, "store_project_secrets", None)
    if store is None:
        return
    notifications = run.spec.notifications or []
    project = run.metadata.project
    for index, notification in enumerate(notifications):
        params = (notification.get("params") if isinstance(notification,
                                                           dict)
                  else getattr(notification, "params", None))
        if not params or "secret" in params:
            continue
        secret_key = (f"{NOTIFICATION_SECRET_PREFIX}"
                      f"{run.metadata.uid}.{index}")
        try:
            store(project, {secret_key: json.dumps(params)})
        except Exception as exc:  # noqa: BLE001 - leave unmasked rather
            # than lose the notification entirely
            logger.warning("notification param masking failed",
                           error=str(exc))
            continue
        masked = {"secret": secret_key}
        if isinstance(notification, dict):
            notification["params"] = masked
        else:
            notification.params = masked


def resolve_notification_params(db, project: str, params: dict) -> dict:
    """Inverse of masking: fetch the stored params for a secret reference
    (server-side only — db must expose get_project_secrets)."""
    secret_key = (params or {}).get("secret")
    if not secret_key:
        return params or {}
    getter = getattr(db, "get_project_secrets", None)
    if getter is None:
        raise ValueError("secret-backed notification params need a "
                         "server-side db")
    values = getter(project, keys=[secret_key])
    raw = values.get(secret_key)
    if raw is None:
        raise KeyError(f"notification secret '{secret_key}' not found")
    return json.loads(raw)


def project_secret_env(db, project: str) -> dict:
    """Project secrets as MLT_SECRET_* env entries for resource injection
    (the client-side SecretsStore env source picks the prefix up, so
    ``context.get_secret(name)`` works inside the run)."""
    getter = getattr(db, "get_project_secrets", None)
    if getter is None:
        return {}
    try:
        secrets = getter(project)
    except Exception as exc:  # noqa: BLE001
        logger.warning("project secret fetch failed", error=str(exc))
        return {}
    # masked notification params are per-run server-side material — they
    # must never ride into resource envs
    return {SECRET_ENV_PREFIX + name: value
            for name, value in secrets.items()
            if not name.startswith(NOTIFICATION_SECRET_PREFIX)}
