"""Server-side runtime handlers — the execution backend.

Reference analog: server/api/runtime_handlers/base.py:50 BaseRuntimeHandler
(run :57, monitor_runs :189, delete_resources :115, stuck-state thresholds
:518,:1368) and kubejob.py:45 / mpijob/v1.py:49. The MPIJob CRD path is
replaced by the TPU JobSet builder (mlrun_tpu/k8s/jobset.py).

Providers decouple "what resource to create" from "where": the
``KubernetesProvider`` creates pods/JobSets via the k8s API (gated on the
kubernetes package); the ``LocalProcessProvider`` executes the same
`mlrun-tpu run --from-env` contract as subprocesses so the full
submit→pod→run→logs path works on a single machine (and in tests, mirroring
the reference's K8sHelperMock tier).
"""

from __future__ import annotations

import json
import threading
import time

from ..common.runtimes_constants import (
    JobSetConditions,
    PodPhases,
    RunStates,
    RuntimeKinds,
)
from ..config import mlconf
from ..model import RunObject
from ..utils import get_in, logger, now_iso


from .providers import (  # noqa: F401 - canonical home is
    # service/providers.py; re-exported for existing importers
    KubernetesProvider,
    LocalProcessProvider,
    Provider,
    _extract_pod_spec,
)


def _wrap_with_bootstrap(runtime, command: list[str]) -> list[str]:
    """Functions that declare build.requirements run under the cached
    requirements venv: the pod command becomes `mlrun-tpu bootstrap -r ...
    -- <command>` (the zero-registry half of the reference's Kaniko image
    build, utils/bootstrap.py)."""
    build = getattr(runtime.spec, "build", None)
    requirements = list(getattr(build, "requirements", []) or [])
    if not requirements:
        return command
    wrapped = ["mlrun-tpu", "bootstrap"]
    for req in requirements:
        wrapped += ["-r", req]
    return wrapped + ["--"] + command


class BaseRuntimeHandler:
    kind = "base"

    def __init__(self, db, provider: Provider):
        self.db = db
        self.provider = provider
        # run uid -> (resource_id, project, started_walltime); mirrored in
        # the DB's runtime_resources table so a service restart can rebuild
        # it (reference recovers via cluster label listing, base.py:65)
        self._resources: dict[str, tuple[str, str, float]] = {}
        self._lock = threading.RLock()

    # -- resource building --------------------------------------------------
    def build_resource(self, runtime, run: RunObject) -> dict:
        raise NotImplementedError

    def run(self, runtime, run: RunObject, execution=None) -> dict:
        resource = self.build_resource(runtime, run)
        self._apply_secret_projection(resource, run.metadata.project)
        resource_id = self.provider.create(resource, run.metadata.uid)
        started = time.time()
        with self._lock:
            self._resources[run.metadata.uid] = (
                resource_id, run.metadata.project, started)
        self._persist(run.metadata.uid, run.metadata.project, resource_id,
                      started)
        self.db.update_run(
            {"status.state": RunStates.running,
             "status.start_time": now_iso()},
            run.metadata.uid, run.metadata.project)
        logger.info("runtime resource created", kind=self.kind,
                    resource=resource_id, uid=run.metadata.uid)
        return {"resource_id": resource_id}

    # -- durable state ------------------------------------------------------
    def _persist(self, uid: str, project: str, resource_id: str,
                 started: float):
        store = getattr(self.db, "store_runtime_resource", None)
        if store:
            try:
                store(uid, project, self.kind, resource_id, started)
            except Exception as exc:  # noqa: BLE001 - tracking best-effort
                logger.warning("runtime resource persist failed",
                               error=str(exc))

    def _forget(self, uid: str, project: str):
        with self._lock:
            self._resources.pop(uid, None)
        drop = getattr(self.db, "del_runtime_resource", None)
        if drop:
            try:
                drop(uid, project)
            except Exception as exc:  # noqa: BLE001
                logger.warning("runtime resource forget failed",
                               error=str(exc))

    def recover_resources(self):
        """Rebuild the resource map after a service restart: DB rows first,
        then provider label discovery for resources the DB missed."""
        lister = getattr(self.db, "list_runtime_resources", None)
        recovered = 0
        if lister:
            for row in lister(kind=self.kind):
                with self._lock:
                    if row["uid"] not in self._resources:
                        self._resources[row["uid"]] = (
                            row["resource_id"], row["project"],
                            float(row["started"] or time.time()))
                        recovered += 1
        discover = getattr(self.provider, "list_resources", None)
        if discover:
            try:
                for resource_id, uid, project in discover(self.kind):
                    with self._lock:
                        if uid not in self._resources:
                            self._resources[uid] = (
                                resource_id, project, time.time())
                            recovered += 1
                            self._persist(uid, project, resource_id,
                                          time.time())
            except Exception as exc:  # noqa: BLE001 - discovery best-effort
                logger.warning("provider resource discovery failed",
                               kind=self.kind, error=str(exc))
        if recovered:
            logger.info("recovered runtime resources", kind=self.kind,
                        count=recovered)

    # -- monitoring (reference base.py:189 monitor_runs) ---------------------
    def monitor_runs(self):
        with self._lock:
            snapshot = list(self._resources.items())
        for uid, (resource_id, project, started) in snapshot:
            try:
                self._monitor_one(uid, resource_id, project, started)
            except Exception as exc:  # noqa: BLE001 - one bad resource must
                # not wedge monitoring for every other run of this kind
                logger.warning("monitoring resource failed", uid=uid,
                               resource=resource_id, error=str(exc))

    def _monitor_one(self, uid: str, resource_id: str, project: str,
                     started: float):
        try:
            phase = self.provider.state(resource_id)
        except Exception as exc:  # noqa: BLE001 - e.g. k8s 404 after the
            # resource was GC'd while the service was down
            logger.warning("resource state probe failed — treating as gone",
                           uid=uid, resource=resource_id, error=str(exc))
            phase = PodPhases.failed
        run_state = PodPhases.to_run_state(phase)
        run = self.db.read_run(uid, project)
        if run is None:
            self._delete_quietly(resource_id)
            self._forget(uid, project)
            return
        current = get_in(run, "status.state")
        if current in (RunStates.aborting,):
            self._delete_quietly(resource_id)
            self.db.update_run({"status.state": RunStates.aborted},
                               uid, project)
            self._forget(uid, project)
            return
        if run_state in RunStates.terminal_states():
            updates = {"status.last_update": now_iso()}
            # the in-run process writes richer state; only force error
            # when the resource failed but the run never reported it
            if run_state == RunStates.error and current not in \
                    RunStates.terminal_states():
                updates["status.state"] = RunStates.error
                updates["status.error"] = (
                    get_in(run, "status.error")
                    or "execution resource failed")
            elif current not in RunStates.terminal_states():
                updates["status.state"] = run_state
            self.db.update_run(updates, uid, project)
            self._forget(uid, project)
            self._push_notifications(uid, project, run)
            return
        # stuck-state thresholds (reference base.py:518)
        threshold = self._state_threshold(run, run_state)
        if threshold > 0 and time.time() - started > threshold:
            logger.warning("aborting stuck run", uid=uid,
                           state=run_state, threshold=threshold)
            self._delete_quietly(resource_id)
            self.db.update_run(
                {"status.state": RunStates.aborted,
                 "status.status_text":
                 f"stuck in state {run_state} over {threshold}s"},
                uid, project)
            self._forget(uid, project)

    def _push_notifications(self, uid: str, project: str, run: dict):
        """Server-side push when the monitor retires a terminal resource —
        the only place masked (secret-backed) notification params can be
        resolved (reference RunNotificationPusher). ``run`` is the dict the
        monitor already read; statuses are re-read so an in-run push that
        landed after the monitor's read is not repeated."""
        if not get_in(run, "spec.notifications"):
            return
        run = self.db.read_run(uid, project) or run
        specs = run.get("spec", {}).get("notifications") or []
        # the in-run process already pushed what it could (unmasked specs);
        # the server covers masked ones and anything not yet sent
        pending = [s for s in specs if isinstance(s, dict)
                   and s.get("status") != "sent"]
        if not pending:
            return
        from ..utils.notifications import NotificationPusher
        from .secrets import NOTIFICATION_SECRET_PREFIX, \
            resolve_notification_params

        run = dict(run)
        run["spec"] = dict(run["spec"])
        run["spec"]["notifications"] = pending
        try:
            NotificationPusher(
                [run],
                secret_resolver=lambda proj, params:
                resolve_notification_params(self.db, proj, params)).push()
            # pending entries are the same dict objects as in specs, so
            # their pushed statuses are visible in the full list
            self.db.update_run({"spec.notifications": specs}, uid, project)
        except Exception as exc:  # noqa: BLE001 - notification is best-effort
            logger.warning("server-side notification push failed", uid=uid,
                           error=str(exc))
        # per-run notification secrets are single-use — drop them so the
        # store (and any projected k8s Secret) does not grow unboundedly
        drop = getattr(self.db, "delete_project_secrets", None)
        if drop:
            used = [s.get("params", {}).get("secret") for s in specs
                    if isinstance(s, dict)
                    and (s.get("params") or {}).get("secret", "").startswith(
                        NOTIFICATION_SECRET_PREFIX)]
            if used:
                try:
                    drop(project, keys=[k for k in used if k])
                except Exception as exc:  # noqa: BLE001
                    logger.warning("notification secret cleanup failed",
                                   error=str(exc))

    def _delete_quietly(self, resource_id: str):
        try:
            self.provider.delete(resource_id)
        except Exception as exc:  # noqa: BLE001 - already-gone is fine
            logger.warning("resource delete failed", resource=resource_id,
                           error=str(exc))

    @staticmethod
    def _state_threshold(run: dict, state: str) -> float:
        thresholds = dict(mlconf.runs.state_thresholds.to_dict()
                          if hasattr(mlconf.runs.state_thresholds, "to_dict")
                          else {})
        thresholds.update(get_in(run, "spec.state_thresholds", {}) or {})
        if state == RunStates.pending:
            return float(thresholds.get("pending_scheduled", 3600))
        if state == RunStates.running:
            return float(thresholds.get("executing", -1))
        return -1

    def _secret_env(self, project: str) -> dict:
        """Project secrets as MLT_SECRET_* env for the resource. With a
        kubernetes provider the values ride a k8s Secret + envFrom instead
        (``_apply_secret_projection``) so they never appear in the pod
        manifest; the local provider carries them as plain subprocess env."""
        if hasattr(self.provider, "ensure_project_secret"):
            return {}
        from .secrets import project_secret_env

        return project_secret_env(self.db, project)

    def _apply_secret_projection(self, resource: dict, project: str):
        """Project the project-secret store into the pod spec via a k8s
        Secret object + envFrom secretRef (reference pod.py secret mounts)."""
        ensure = getattr(self.provider, "ensure_project_secret", None)
        if ensure is None:
            return
        from .secrets import project_secret_env

        secrets = project_secret_env(self.db, project)
        if not secrets:
            return
        secret_name = ensure(project, secrets)
        ref = {"secretRef": {"name": secret_name}}
        if resource.get("kind") == "SparkApplication":
            # spark-operator takes envFrom on the driver/executor specs,
            # not a containers list — without this branch spark runs got
            # NO project secrets at all
            for role in ("driver", "executor"):
                section = resource["spec"].setdefault(role, {})
                section.setdefault("envFrom", []).append(dict(ref))
            return
        pod_spec = _extract_pod_spec(resource)
        for container in pod_spec.get("containers", []):
            container.setdefault("envFrom", []).append(dict(ref))

    def delete_resources(self, uid: str, project: str = "",
                         resource_id: str = ""):
        """Delete a run's cluster resource + both tracking layers (the
        in-memory map and the DB row). ``project``/``resource_id`` serve as
        a fallback for rows that were never adopted in-memory (e.g. listed
        straight from the DB after a restart)."""
        with self._lock:
            entry = self._resources.get(uid)
        if entry:
            self.provider.delete(entry[0])
            self._forget(uid, entry[1])
        elif resource_id:
            self.provider.delete(resource_id)
            self._forget(uid, project)

    def abort_run(self, uid: str, project: str):
        self.db.update_run({"status.state": RunStates.aborting}, uid, project)
        with self._lock:
            entry = self._resources.get(uid)
        if entry:
            self.provider.delete(entry[0])
            self._forget(uid, project)
        self.db.update_run({"status.state": RunStates.aborted}, uid, project)


class KubeJobHandler(BaseRuntimeHandler):
    """Single-pod batch job (reference kubejob.py:45)."""

    kind = RuntimeKinds.job

    def build_resource(self, runtime, run: RunObject) -> dict:
        env = {
            mlconf.exec_config_env: json.dumps(run.to_dict(), default=str),
            "MLT_DBPATH": mlconf.get("dbpath", "")
            or f"http://127.0.0.1:{mlconf.httpdb.port}",
        }
        env.update(self._secret_env(run.metadata.project))
        build = runtime.spec.build
        if build and build.functionSourceCode:
            env[mlconf.exec_code_env] = build.functionSourceCode
        command = ["mlrun-tpu", "run", "--from-env"]
        handler = run.spec.handler_name
        if handler:
            command += ["--handler", handler]
        command = _wrap_with_bootstrap(runtime, command)
        pod_spec = runtime.to_pod_spec(command=command, extra_env=env)
        return {
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": {
                "name": f"{run.metadata.name}-{run.metadata.uid[:8]}",
                "namespace": mlconf.namespace,
                "labels": {
                    "mlrun-tpu/project": run.metadata.project,
                    "mlrun-tpu/uid": run.metadata.uid,
                    "mlrun-tpu/class": self.kind,
                },
            },
            "spec": pod_spec,
        }


class TpuJobHandler(BaseRuntimeHandler):
    """TPU pod-slice JobSet (replaces MpiV1RuntimeHandler, mpijob/v1.py:49)."""

    kind = RuntimeKinds.tpujob

    def build_resource(self, runtime, run: RunObject) -> dict:
        env = {
            "MLT_DBPATH": mlconf.get("dbpath", "")
            or f"http://127.0.0.1:{mlconf.httpdb.port}",
        }
        env.update(self._secret_env(run.metadata.project))
        build = runtime.spec.build
        if build and build.functionSourceCode:
            env[mlconf.exec_code_env] = build.functionSourceCode
        command = ["mlrun-tpu", "run", "--from-env"]
        handler = run.spec.handler_name
        if handler:
            command += ["--handler", handler]
        command = _wrap_with_bootstrap(runtime, command)
        return runtime.generate_jobset(run, extra_env=env, command=command)


class DaskHandler(KubeJobHandler):
    kind = RuntimeKinds.dask


class SparkHandler(BaseRuntimeHandler):
    """SparkApplication CRD (reference sparkjob handler). Requires the
    kubernetes provider — a local process cannot materialize a spark
    cluster."""

    kind = RuntimeKinds.spark

    def build_resource(self, runtime, run: RunObject) -> dict:
        if isinstance(self.provider, LocalProcessProvider):
            raise ValueError(
                "the spark runtime needs a kubernetes provider with the "
                "spark-operator installed; run with local=True for a local "
                "SparkSession instead")
        return runtime.generate_spark_application(run)


def get_runtime_handler(kind: str, db, provider: Provider
                        ) -> BaseRuntimeHandler:
    cls = {
        RuntimeKinds.job: KubeJobHandler,
        RuntimeKinds.tpujob: TpuJobHandler,
        RuntimeKinds.dask: DaskHandler,
        RuntimeKinds.spark: SparkHandler,
    }.get(kind)
    if cls is None:
        raise ValueError(f"no runtime handler for kind '{kind}'")
    return cls(db, provider)
