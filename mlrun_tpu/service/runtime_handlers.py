"""Server-side runtime handlers — the execution backend.

Reference analog: server/api/runtime_handlers/base.py:50 BaseRuntimeHandler
(run :57, monitor_runs :189, delete_resources :115, stuck-state thresholds
:518,:1368) and kubejob.py:45 / mpijob/v1.py:49. The MPIJob CRD path is
replaced by the TPU JobSet builder (mlrun_tpu/k8s/jobset.py).

Providers decouple "what resource to create" from "where": the
``KubernetesProvider`` creates pods/JobSets via the k8s API (gated on the
kubernetes package); the ``LocalProcessProvider`` executes the same
`mlrun-tpu run --from-env` contract as subprocesses so the full
submit→pod→run→logs path works on a single machine (and in tests, mirroring
the reference's K8sHelperMock tier).
"""

from __future__ import annotations

import copy
import json
import threading
import time
from datetime import datetime

from ..common.retry import (
    FailureClass,
    classify_failure,
    compute_backoff,
    resolve_retry_policy,
)
from ..common.runtimes_constants import (
    COMPILE_CACHE_ENV,
    RESUME_CHECKPOINT_ENV,
    RESUME_STEP_ENV,
    JobSetConditions,
    PodPhases,
    RunStates,
    RuntimeKinds,
)
from ..config import mlconf
from ..model import RunObject
from ..obs import (
    RUN_RETRIES,
    RUN_STALL_ABORTS,
    flight_record,
    get_flight_recorder,
    get_tracer,
    record_badput,
    trace_id_for,
)
from ..utils import get_in, logger, now_iso


from .providers import (  # noqa: F401 - canonical home is
    # service/providers.py; re-exported for existing importers
    KubernetesProvider,
    LocalProcessProvider,
    Provider,
    _extract_pod_spec,
)


def _epoch(iso: str | None) -> float | None:
    """ISO timestamp (utils.now_iso) → epoch seconds; None when absent or
    unparseable."""
    if not iso:
        return None
    try:
        return datetime.fromisoformat(str(iso)).timestamp()
    except ValueError:
        return None


def _rewrite_exec_config(node, value: str):
    """Replace every baked exec-config env value (any container, any
    manifest shape — Pod, JobSet, Deployment) with ``value``."""
    if isinstance(node, dict):
        for key, child in node.items():
            if key == "containers" and isinstance(child, list):
                for container in child:
                    for env in container.get("env", []) or []:
                        if env.get("name") == mlconf.exec_config_env:
                            env["value"] = value
            else:
                _rewrite_exec_config(child, value)
    elif isinstance(node, list):
        for item in node:
            _rewrite_exec_config(item, value)


def _wrap_with_bootstrap(runtime, command: list[str]) -> list[str]:
    """Functions that declare build.requirements run under the cached
    requirements venv: the pod command becomes `mlrun-tpu bootstrap -r ...
    -- <command>` (the zero-registry half of the reference's Kaniko image
    build, utils/bootstrap.py)."""
    build = getattr(runtime.spec, "build", None)
    requirements = list(getattr(build, "requirements", []) or [])
    if not requirements:
        return command
    wrapped = ["mlrun-tpu", "bootstrap"]
    for req in requirements:
        wrapped += ["-r", req]
    return wrapped + ["--"] + command


class BaseRuntimeHandler:
    kind = "base"

    def __init__(self, db, provider: Provider):
        self.db = db
        self.provider = provider
        # run key -> (resource_id, project, started_walltime); mirrored in
        # the DB's runtime_resources table so a service restart can rebuild
        # it (reference recovers via cluster label listing, base.py:65).
        # The key is the run uid for iteration 0 and "uid#iter" for hyper
        # children — they share the parent's uid, and keying by bare uid
        # would make child resources overwrite each other AND make the
        # monitor write child terminal states onto the PARENT run doc
        self._resources: dict[str, tuple[str, str, float]] = {}
        # run uid -> pristine resource manifest as built by build_resource,
        # kept so a retryable failure can be resubmitted without the
        # runtime object; a restarted service falls back to rebuilding the
        # runtime from the stored function (``_build_retry_manifest``)
        self._manifests: dict[str, dict] = {}
        # run uid -> wall-clock before which a scheduled retry must wait
        self._retry_at: dict[str, float] = {}
        # run uid -> consecutive state-probe failures; a single apiserver
        # blip must not be mistaken for a dead resource
        self._probe_failures: dict[str, int] = {}
        self._lock = threading.RLock()

    @staticmethod
    def _run_key(uid: str, iteration: int) -> str:
        return f"{uid}#{iteration}" if iteration else uid

    @staticmethod
    def _split_key(key: str) -> tuple[str, int]:
        uid, _, iteration = key.partition("#")
        return uid, int(iteration or 0)

    # -- resource building --------------------------------------------------
    def build_resource(self, runtime, run: RunObject) -> dict:
        raise NotImplementedError

    def run(self, runtime, run: RunObject, execution=None) -> dict:
        resource = self.build_resource(runtime, run)
        self._apply_secret_projection(resource, run.metadata.project)
        iteration = run.metadata.iteration or 0
        key = self._run_key(run.metadata.uid, iteration)
        with self._lock:
            self._manifests[key] = copy.deepcopy(resource)
        try:
            resource_id = self.provider.create(resource, run.metadata.uid)
        except Exception:
            # a failed create never registers the key in _resources, so
            # _forget would never fire for it — drop the cached manifest
            # here or repeatedly failing submissions accumulate deep
            # copies forever (ROADMAP open item)
            with self._lock:
                self._manifests.pop(key, None)
            raise
        started = time.time()
        with self._lock:
            self._resources[key] = (
                resource_id, run.metadata.project, started)
        self._persist(key, run.metadata.project, resource_id, started)
        self.db.update_run(
            {"status.state": RunStates.running,
             "status.start_time": now_iso()},
            run.metadata.uid, run.metadata.project, iter=iteration)
        logger.info("runtime resource created", kind=self.kind,
                    resource=resource_id, uid=run.metadata.uid,
                    iteration=iteration)
        return {"resource_id": resource_id}

    # -- durable state ------------------------------------------------------
    def _persist(self, uid: str, project: str, resource_id: str,
                 started: float):
        store = getattr(self.db, "store_runtime_resource", None)
        if store:
            try:
                store(uid, project, self.kind, resource_id, started)
            except Exception as exc:  # noqa: BLE001 - tracking best-effort
                logger.warning("runtime resource persist failed",
                               error=str(exc))

    def _forget(self, uid: str, project: str):
        with self._lock:
            self._resources.pop(uid, None)
            self._manifests.pop(uid, None)
            self._retry_at.pop(uid, None)
            self._probe_failures.pop(uid, None)
        # series lifecycle: a finished run's per-run goodput label sets
        # are queued for retirement (kept scrapeable for the most recent
        # N finished runs so the terminal attribution survives until the
        # federation loop reads it) — only once no sibling iteration is
        # still tracked, since hyper children share the parent uid
        bare_uid = self._split_key(uid)[0]
        with self._lock:
            siblings = any(self._split_key(key)[0] == bare_uid
                           for key in self._resources)
        if not siblings:
            from ..obs.goodput import release_run

            release_run(bare_uid)
        drop = getattr(self.db, "del_runtime_resource", None)
        if drop:
            try:
                drop(uid, project)
            except Exception as exc:  # noqa: BLE001
                logger.warning("runtime resource forget failed",
                               error=str(exc))

    def recover_resources(self):
        """Rebuild the resource map after a service restart: DB rows first,
        then provider label discovery for resources the DB missed."""
        lister = getattr(self.db, "list_runtime_resources", None)
        recovered = 0
        if lister:
            for row in lister(kind=self.kind):
                with self._lock:
                    if row["uid"] not in self._resources:
                        self._resources[row["uid"]] = (
                            row["resource_id"], row["project"],
                            float(row["started"] or time.time()))
                        recovered += 1
        discover = getattr(self.provider, "list_resources", None)
        if discover:
            try:
                for resource_id, uid, project in discover(self.kind):
                    with self._lock:
                        if uid not in self._resources:
                            self._resources[uid] = (
                                resource_id, project, time.time())
                            recovered += 1
                            self._persist(uid, project, resource_id,
                                          time.time())
            except Exception as exc:  # noqa: BLE001 - discovery best-effort
                logger.warning("provider resource discovery failed",
                               kind=self.kind, error=str(exc))
        if recovered:
            logger.info("recovered runtime resources", kind=self.kind,
                        count=recovered)

    # -- monitoring (reference base.py:189 monitor_runs) ---------------------
    def monitor_runs(self):
        with self._lock:
            snapshot = list(self._resources.items())
        for key, (resource_id, project, started) in snapshot:
            try:
                self._monitor_one(key, resource_id, project, started)
            except Exception as exc:  # noqa: BLE001 - one bad resource must
                # not wedge monitoring for every other run of this kind
                logger.warning("monitoring resource failed", uid=key,
                               resource=resource_id, error=str(exc))

    def _monitor_one(self, key: str, resource_id: str, project: str,
                     started: float):
        uid, iteration = self._split_key(key)
        probe_error = None
        try:
            phase = self.provider.state(resource_id)
            with self._lock:
                self._probe_failures.pop(key, None)
        except Exception as exc:  # noqa: BLE001 - e.g. k8s 404 after the
            # resource was GC'd while the service was down
            # 404 is definitive (the resource is gone); anything else may
            # be an apiserver blip — require consecutive failures before
            # declaring the resource dead, or a transient 5xx would
            # trigger a resubmission against a still-running resource
            definitive = getattr(exc, "status", None) == 404 \
                or "404" in str(exc)
            if not definitive:
                with self._lock:
                    failures = self._probe_failures.get(key, 0) + 1
                    self._probe_failures[key] = failures
                if failures < 2:
                    logger.warning("resource state probe failed — "
                                   "waiting for the next tick",
                                   uid=uid, resource=resource_id,
                                   error=str(exc))
                    return
            logger.warning("resource state probe failed — treating as gone",
                           uid=uid, resource=resource_id, error=str(exc))
            probe_error = str(exc)
            phase = PodPhases.failed
        run_state = PodPhases.to_run_state(phase)
        run = self.db.read_run(uid, project, iter=iteration)
        if run is None:
            self._delete_quietly(resource_id)
            self._forget(key, project)
            return
        current = get_in(run, "status.state")
        if current in (RunStates.aborting,):
            self._delete_quietly(resource_id)
            self.db.update_run({"status.state": RunStates.aborted},
                               uid, project, iter=iteration)
            self._forget(key, project)
            return
        failure_class = None
        if run_state == RunStates.error:
            # the fault-tolerance core (reference base.py has no retry at
            # all — SURVEY §5.3): classify, then resubmit retryable infra
            # failures within policy instead of failing the run
            failure_class = classify_failure(
                probe_error=probe_error,
                run_error=get_in(run, "status.error"),
                run_reported_terminal=current in RunStates.terminal_states())
            if self._maybe_retry(key, resource_id, project, run,
                                 failure_class):
                return
        if run_state in RunStates.terminal_states():
            updates = {"status.last_update": now_iso()}
            # the in-run process writes richer state; only force error
            # when the resource failed but the run never reported it
            if run_state == RunStates.error and current not in \
                    RunStates.terminal_states():
                updates["status.state"] = RunStates.error
                updates["status.error"] = (
                    get_in(run, "status.error")
                    or "execution resource failed")
            elif current not in RunStates.terminal_states():
                updates["status.state"] = run_state
            # record the class only on runs that actually FAILED — a
            # completed run whose finished resource was GC'd before this
            # tick must not be labeled a user-code failure
            final_state = updates.get("status.state", current)
            if failure_class and final_state in RunStates.error_states() \
                    and not get_in(run, "status.failure_class"):
                updates["status.failure_class"] = failure_class
            self.db.update_run(updates, uid, project, iter=iteration)
            self._forget(key, project)
            self._push_notifications(uid, project, run)
            return
        if run_state == RunStates.running:
            # the resource is healthy again: a retry scheduled off a
            # transient blip must not linger and fire with zero backoff
            # at the NEXT genuine failure
            with self._lock:
                self._retry_at.pop(key, None)
            if current == RunStates.pending and get_in(
                    run, "status.failure_class"):
                # undo the blip's pending-for-retry parking
                self.db.update_run({"status.state": RunStates.running},
                                   uid, project, iter=iteration)
            # elastic multi-slice path: ONE slice gone while the job
            # stays alive is not a failure — submit only a replacement
            # slice; the in-run trainer reshards onto the survivors
            if self._check_slices(key, resource_id, project, run):
                return
            # heartbeat watchdog: a resource that still reports running
            # but whose run went silent is stalled (hung collective,
            # wedged host)
            if self._check_stalled(key, resource_id, project, run, started):
                return
        # stuck-state thresholds (reference base.py:518)
        threshold = self._state_threshold(run, run_state)
        if threshold > 0 and time.time() - started > threshold:
            logger.warning("aborting stuck run", uid=uid,
                           state=run_state, threshold=threshold)
            flight_record("run.stuck_abort", uid=uid, state=run_state,
                          threshold_s=threshold)
            self._delete_quietly(resource_id)
            self.db.update_run(
                {"status.state": RunStates.aborted,
                 "status.status_text":
                 f"stuck in state {run_state} over {threshold}s"},
                uid, project, iter=iteration)
            self._forget(key, project)

    # -- retry / resubmission (the fault-tolerance subsystem) ----------------
    def _maybe_retry(self, key: str, resource_id: str, project: str,
                     run: dict, failure_class: str) -> bool:
        """Decide whether a failed resource is resubmitted. True → the
        failure was fully handled here (scheduled or resubmitted); False →
        fall through to the terminal-state path."""
        uid, iteration = self._split_key(key)
        policy = resolve_retry_policy(get_in(run, "spec.retry_policy"))
        retry_count = int(get_in(run, "status.retry_count", 0) or 0)
        if failure_class not in policy.retry_on or \
                not policy.retries_left(retry_count):
            return False
        with self._lock:
            retry_at = self._retry_at.get(key)
        if retry_at is None:
            delay = compute_backoff(retry_count, policy, seed=key)
            if delay > 0:
                with self._lock:
                    self._retry_at[key] = time.time() + delay
                self.db.update_run(
                    {"status.state": RunStates.pending,
                     "status.failure_class": failure_class,
                     "status.status_text":
                     f"{failure_class}: retry "
                     f"{retry_count + 1}/{policy.max_retries} "
                     f"in {delay:.1f}s"},
                    uid, project, iter=iteration)
                # goodput accounting: the scheduled backoff is wall time
                # this run spends NOT training — preemption downtime or a
                # generic resubmit gap, attributed out-of-band because
                # the run process is dead for its duration
                record_badput(
                    "preemption_downtime"
                    if failure_class == FailureClass.preemption
                    else "resubmit_gap", delay, run=uid)
                flight_record("run.retry_scheduled", uid=uid,
                              failure_class=failure_class,
                              delay_s=round(delay, 3),
                              attempt=retry_count + 1)
                logger.info("scheduled run retry", uid=uid,
                            failure_class=failure_class, delay=delay,
                            attempt=retry_count + 1)
                return True
        elif time.time() < retry_at:
            return True
        with self._lock:
            self._retry_at.pop(key, None)
        return self._resubmit(key, resource_id, project, run,
                              retry_count + 1, failure_class)

    def _resubmit(self, key: str, old_resource_id: str, project: str,
                  run: dict, attempt: int, failure_class: str) -> bool:
        uid, iteration = self._split_key(key)
        self._delete_quietly(old_resource_id)
        try:
            manifest = self._build_retry_manifest(key, project, run, attempt,
                                                  failure_class)
        except Exception as exc:  # noqa: BLE001 - unresolvable function etc.
            logger.warning("cannot rebuild resource for retry", uid=uid,
                           error=str(exc))
            manifest = None
        if manifest is None:
            self.db.update_run(
                {"status.state": RunStates.error,
                 "status.failure_class": failure_class,
                 "status.error": get_in(run, "status.error")
                 or f"execution resource failed ({failure_class}); "
                 "resource could not be rebuilt for retry"},
                uid, project, iter=iteration)
            self._forget(key, project)
            self._push_notifications(uid, project, run)
            return True
        try:
            new_id = self.provider.create(manifest, uid)
        except Exception as exc:  # noqa: BLE001 - cluster rejected the retry
            logger.warning("resubmission failed", uid=uid, error=str(exc))
            self.db.update_run(
                {"status.state": RunStates.error,
                 "status.failure_class": failure_class,
                 "status.error": f"resubmission failed: {exc}"},
                uid, project, iter=iteration)
            self._forget(key, project)
            self._push_notifications(uid, project, run)
            return True
        started = time.time()
        with self._lock:
            self._resources[key] = (new_id, project, started)
        self._persist(key, project, new_id, started)
        self.db.update_run(
            {"status.state": RunStates.running,
             "status.retry_count": attempt,
             "status.failure_class": failure_class,
             "status.status_text":
             f"resubmitted after {failure_class} (attempt {attempt})"},
            uid, project, iter=iteration)
        RUN_RETRIES.inc(failure_class=failure_class)
        # joins the run.submit span on the uid-derived lifecycle trace
        get_tracer().emit(
            "run.retry", trace_id_for(uid),
            attrs={"uid": uid, "failure_class": failure_class,
                   "attempt": attempt, "resource": new_id})
        flight_record("run.resubmit", uid=uid,
                      failure_class=failure_class, attempt=attempt,
                      resource=new_id)
        logger.info("resubmitted run", uid=uid, resource=new_id,
                    failure_class=failure_class, attempt=attempt,
                    trace_id=trace_id_for(uid))
        return True

    def _build_retry_manifest(self, key: str, project: str, run: dict,
                              attempt: int,
                              failure_class: str = "") -> dict | None:
        """Fresh manifest for a retry: the pristine manifest cached at
        submission (or rebuilt from the stored function after a service
        restart), renamed per attempt so an async-deleting cluster can't
        409 the replacement, then handler-customized (resume env)."""
        with self._lock:
            manifest = self._manifests.get(key)
        if manifest is None:
            manifest = self._rebuild_from_function(
                self._split_key(key)[0], project, run)
            if manifest is None:
                return None
            with self._lock:
                self._manifests[key] = copy.deepcopy(manifest)
        manifest = copy.deepcopy(manifest)
        name = manifest.get("metadata", {}).get("name")
        if name:
            manifest["metadata"]["name"] = f"{name}-r{attempt}"
        # the baked exec config predates the failure — refresh it so the
        # retried process knows its retry status (and latest checkpoint)
        # and its full-doc store_run doesn't erase them
        run_doc = copy.deepcopy(run)
        run_doc.setdefault("status", {})["retry_count"] = attempt
        if failure_class:
            run_doc["status"]["failure_class"] = failure_class
        _rewrite_exec_config(manifest, json.dumps(run_doc, default=str))
        self._customize_retry_manifest(manifest, run, attempt)
        return manifest

    def _rebuild_from_function(self, uid: str, project: str,
                               run: dict) -> dict | None:
        """Post-restart fallback: rebuild the runtime from the function
        stored in the DB (spec.function 'project/name:tag') and run
        build_resource again."""
        getter = getattr(self.db, "get_function", None)
        uri = get_in(run, "spec.function", "") or ""
        if not getter or "/" not in uri:
            return None
        func_project, _, rest = uri.partition("/")
        name, _, tag = rest.partition(":")
        tag, _, _hash = tag.partition("@")
        struct = getter(name, func_project or project, tag=tag or "latest")
        if not struct:
            return None
        from .launcher import rebuild_function

        runtime = rebuild_function(struct)
        resource = self.build_resource(runtime, RunObject.from_dict(run))
        self._apply_secret_projection(resource, project)
        return resource

    def _customize_retry_manifest(self, manifest: dict, run: dict,
                                  attempt: int):
        """Handler hook: adjust the renamed manifest before resubmission
        (TpuJobHandler wires checkpoint-resume env here)."""

    def _check_slices(self, key: str, resource_id: str, project: str,
                      run: dict) -> bool:
        """Handler hook: per-slice health of a still-running resource.
        True → a slice-level event was handled this tick (the monitor
        skips the stall check — a just-degraded run is mid-reshard, not
        stalled). Base handlers have no slice structure."""
        return False

    # -- stall watchdog ------------------------------------------------------
    def _check_stalled(self, key: str, resource_id: str, project: str,
                       run: dict, started: float) -> bool:
        """Escalate runs whose heartbeat went silent past the policy
        threshold: abort, or resubmit within the retry budget."""
        uid, iteration = self._split_key(key)
        policy = resolve_retry_policy(get_in(run, "spec.retry_policy"))
        if policy.stall_timeout is None or policy.stall_timeout <= 0:
            return False
        # floor at the CURRENT resource's start: a just-resubmitted
        # replacement hasn't heartbeat yet, and judging it by the previous
        # attempt's stale heartbeat would burn the retry budget one
        # monitor tick at a time
        heartbeat = max(_epoch(get_in(run, "status.last_heartbeat")) or 0.0,
                        started)
        silent = time.time() - heartbeat
        if silent <= policy.stall_timeout:
            return False
        retry_count = int(get_in(run, "status.retry_count", 0) or 0)
        logger.warning("run stalled — no heartbeat", uid=uid,
                       silent_seconds=round(silent, 1),
                       threshold=policy.stall_timeout,
                       escalation=policy.on_stall)
        # flight + goodput: the silent window is badput, and the
        # detection event opens the post-mortem sequence the artifact
        # below must carry (stall detection -> retry decision)
        flight_record("run.stall_detected", uid=uid,
                      silent_s=round(silent, 1),
                      threshold_s=policy.stall_timeout,
                      escalation=policy.on_stall)
        record_badput("stall", silent, run=uid)
        # on_stall is the explicit directive — it is NOT gated on
        # retry_on (a run retrying only preemptions but asking for stall
        # resubmission means exactly that); only the budget limits it
        if policy.on_stall == "resubmit" and \
                policy.retries_left(retry_count):
            handled = self._resubmit(key, resource_id, project, run,
                                     retry_count + 1, FailureClass.stalled)
            get_flight_recorder().dump("stall-resubmit",
                                       extra={"run": uid})
            return handled
        self._delete_quietly(resource_id)
        self.db.update_run(
            {"status.state": RunStates.aborted,
             "status.failure_class": FailureClass.stalled,
             "status.status_text":
             f"stalled: no heartbeat for {silent:.0f}s "
             f"(threshold {policy.stall_timeout:.0f}s)"},
            uid, project, iter=iteration)
        RUN_STALL_ABORTS.inc()
        get_tracer().emit(
            "run.stall_abort", trace_id_for(uid),
            attrs={"uid": uid, "silent_s": round(silent, 1),
                   "threshold_s": policy.stall_timeout})
        flight_record("run.stall_abort", uid=uid,
                      silent_s=round(silent, 1))
        # the black-box artifact a stall-aborted run leaves behind: the
        # event sequence into the abort (detection, prior retries, chaos
        # fires) — ISSUE 10 acceptance
        get_flight_recorder().dump("stall-abort", extra={"run": uid})
        self._forget(key, project)
        self._push_notifications(uid, project, run)
        return True

    def _push_notifications(self, uid: str, project: str, run: dict):
        """Server-side push when the monitor retires a terminal resource —
        the only place masked (secret-backed) notification params can be
        resolved (reference RunNotificationPusher). ``run`` is the dict the
        monitor already read; statuses are re-read so an in-run push that
        landed after the monitor's read is not repeated."""
        if not get_in(run, "spec.notifications"):
            return
        run = self.db.read_run(uid, project) or run
        specs = run.get("spec", {}).get("notifications") or []
        # the in-run process already pushed what it could (unmasked specs);
        # the server covers masked ones and anything not yet sent
        pending = [s for s in specs if isinstance(s, dict)
                   and s.get("status") != "sent"]
        if not pending:
            return
        from ..utils.notifications import NotificationPusher
        from .secrets import NOTIFICATION_SECRET_PREFIX, \
            resolve_notification_params

        run = dict(run)
        run["spec"] = dict(run["spec"])
        run["spec"]["notifications"] = pending
        try:
            NotificationPusher(
                [run],
                secret_resolver=lambda proj, params:
                resolve_notification_params(self.db, proj, params)).push()
            # pending entries are the same dict objects as in specs, so
            # their pushed statuses are visible in the full list
            self.db.update_run({"spec.notifications": specs}, uid, project)
        except Exception as exc:  # noqa: BLE001 - notification is best-effort
            logger.warning("server-side notification push failed", uid=uid,
                           error=str(exc))
        # per-run notification secrets are single-use — drop them so the
        # store (and any projected k8s Secret) does not grow unboundedly
        drop = getattr(self.db, "delete_project_secrets", None)
        if drop:
            used = [s.get("params", {}).get("secret") for s in specs
                    if isinstance(s, dict)
                    and (s.get("params") or {}).get("secret", "").startswith(
                        NOTIFICATION_SECRET_PREFIX)]
            if used:
                try:
                    drop(project, keys=[k for k in used if k])
                except Exception as exc:  # noqa: BLE001
                    logger.warning("notification secret cleanup failed",
                                   error=str(exc))

    def _delete_quietly(self, resource_id: str):
        try:
            self.provider.delete(resource_id)
        except Exception as exc:  # noqa: BLE001 - already-gone is fine
            logger.warning("resource delete failed", resource=resource_id,
                           error=str(exc))

    @staticmethod
    def _state_threshold(run: dict, state: str) -> float:
        thresholds = dict(mlconf.runs.state_thresholds.to_dict()
                          if hasattr(mlconf.runs.state_thresholds, "to_dict")
                          else {})
        thresholds.update(get_in(run, "spec.state_thresholds", {}) or {})
        if state == RunStates.pending:
            return float(thresholds.get("pending_scheduled", 3600))
        if state == RunStates.running:
            return float(thresholds.get("executing", -1))
        return -1

    def _secret_env(self, project: str) -> dict:
        """Project secrets as MLT_SECRET_* env for the resource. With a
        kubernetes provider the values ride a k8s Secret + envFrom instead
        (``_apply_secret_projection``) so they never appear in the pod
        manifest; the local provider carries them as plain subprocess env."""
        if hasattr(self.provider, "ensure_project_secret"):
            return {}
        from .secrets import project_secret_env

        return project_secret_env(self.db, project)

    def _apply_secret_projection(self, resource: dict, project: str):
        """Project the project-secret store into the pod spec via a k8s
        Secret object + envFrom secretRef (reference pod.py secret mounts)."""
        ensure = getattr(self.provider, "ensure_project_secret", None)
        if ensure is None:
            return
        from .secrets import project_secret_env

        secrets = project_secret_env(self.db, project)
        if not secrets:
            return
        secret_name = ensure(project, secrets)
        ref = {"secretRef": {"name": secret_name}}
        if resource.get("kind") == "SparkApplication":
            # spark-operator takes envFrom on the driver/executor specs,
            # not a containers list — without this branch spark runs got
            # NO project secrets at all
            for role in ("driver", "executor"):
                section = resource["spec"].setdefault(role, {})
                section.setdefault("envFrom", []).append(dict(ref))
            return
        pod_spec = _extract_pod_spec(resource)
        for container in pod_spec.get("containers", []):
            container.setdefault("envFrom", []).append(dict(ref))

    def delete_resources(self, uid: str, project: str = "",
                         resource_id: str = ""):
        """Delete a run's cluster resource + both tracking layers (the
        in-memory map and the DB row). ``project``/``resource_id`` serve as
        a fallback for rows that were never adopted in-memory (e.g. listed
        straight from the DB after a restart)."""
        with self._lock:
            entry = self._resources.get(uid)
        if entry:
            self.provider.delete(entry[0])
            self._forget(uid, entry[1])
        elif resource_id:
            self.provider.delete(resource_id)
            self._forget(uid, project)

    def abort_run(self, uid: str, project: str):
        self.db.update_run({"status.state": RunStates.aborting}, uid, project)
        with self._lock:
            entry = self._resources.get(uid)
        if entry:
            self.provider.delete(entry[0])
            self._forget(uid, project)
        self.db.update_run({"status.state": RunStates.aborted}, uid, project)


class KubeJobHandler(BaseRuntimeHandler):
    """Single-pod batch job (reference kubejob.py:45)."""

    kind = RuntimeKinds.job

    def build_resource(self, runtime, run: RunObject) -> dict:
        env = {
            mlconf.exec_config_env: json.dumps(run.to_dict(), default=str),
            "MLT_DBPATH": mlconf.get("dbpath", "")
            or f"http://127.0.0.1:{mlconf.httpdb.port}",
        }
        env.update(self._secret_env(run.metadata.project))
        build = runtime.spec.build
        if build and build.functionSourceCode:
            env[mlconf.exec_code_env] = build.functionSourceCode
        command = ["mlrun-tpu", "run", "--from-env"]
        handler = run.spec.handler_name
        if handler:
            command += ["--handler", handler]
        command = _wrap_with_bootstrap(runtime, command)
        pod_spec = runtime.to_pod_spec(command=command, extra_env=env)
        return {
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": {
                "name": f"{run.metadata.name}-{run.metadata.uid[:8]}",
                "namespace": mlconf.namespace,
                "labels": {
                    "mlrun-tpu/project": run.metadata.project,
                    "mlrun-tpu/uid": run.metadata.uid,
                    "mlrun-tpu/class": self.kind,
                },
            },
            "spec": pod_spec,
        }


class TpuJobHandler(BaseRuntimeHandler):
    """TPU pod-slice JobSet (replaces MpiV1RuntimeHandler, mpijob/v1.py:49)."""

    kind = RuntimeKinds.tpujob

    def build_resource(self, runtime, run: RunObject) -> dict:
        env = {
            "MLT_DBPATH": mlconf.get("dbpath", "")
            or f"http://127.0.0.1:{mlconf.httpdb.port}",
        }
        cache_dir = self._compile_cache_dir()
        if cache_dir:
            # persistent XLA compile cache (utils/compile_cache.py): the
            # first attempt populates it, a preemption-resume restarts warm
            env[COMPILE_CACHE_ENV] = cache_dir
        env.update(self._secret_env(run.metadata.project))
        build = runtime.spec.build
        if build and build.functionSourceCode:
            env[mlconf.exec_code_env] = build.functionSourceCode
        command = ["mlrun-tpu", "run", "--from-env"]
        handler = run.spec.handler_name
        if handler:
            command += ["--handler", handler]
        command = _wrap_with_bootstrap(runtime, command)
        return runtime.generate_jobset(run, extra_env=env, command=command)

    @staticmethod
    def _compile_cache_dir() -> str:
        training = mlconf.get("training")
        if training is None:
            return ""
        return str(training.get("compile_cache_dir", "") or "")

    def _check_slices(self, key: str, resource_id: str, project: str,
                      run: dict) -> bool:
        """Elastic multi-slice handling (docs/fault_tolerance.md
        "Elastic training"): a failed slice of a LIVE JobSet gets only a
        replacement slice Job — the survivors keep training at reduced
        world size (the in-run trainer reshards; ``ElasticGuard``) —
        instead of the whole run being resubmitted. Re-entry is warm:
        the replacement's template is refreshed with the latest
        ``status.checkpoint`` resume env and the persistent compile
        cache before the child Job is recreated. Budgeted like retries
        (``status.slice_replacements`` against ``max_retries``), gated
        on ``slice_preempted`` being a retried class."""
        slice_status = getattr(self.provider, "slice_status", None)
        if slice_status is None:
            return False
        try:
            status = slice_status(resource_id) or {}
        except Exception:  # noqa: BLE001 - a probe blip never escalates
            return False   # here; the state probe owns liveness
        if not status.get("elastic"):
            # elasticity is an OPT-IN (with_elastic(), the
            # mlrun-tpu/elastic annotation): a non-elastic run's failed
            # slice means its survivors are wedged in dead DCN
            # collectives with no reshard machinery — the job-level
            # failure/full-resubmit path is the right medicine there
            return False
        failed = sorted(int(s) for s in status.get("failed_slices") or [])
        uid, iteration = self._split_key(key)
        degraded = [int(s) for s in
                    get_in(run, "status.degraded_slices", []) or []]
        if not failed:
            if degraded:
                # the replacement came up: the run is whole again —
                # grow-back is the trainer's job, this is bookkeeping
                self.db.update_run(
                    {"status.degraded_slices": [],
                     "status.status_text":
                     "replacement slice joined — full world size "
                     "restored"},
                    uid, project, iter=iteration)
                flight_record("run.slice_rejoined", uid=uid,
                              slices=degraded)
                logger.info("slice replacement joined", uid=uid,
                            slices=degraded)
            return False
        replicas = int(status.get("replicas") or 0)
        if replicas and len(failed) >= replicas:
            # EVERY slice is gone: that is a dead job, not a degraded
            # one — fall through to the state probe / full-resubmit path
            return False
        policy = resolve_retry_policy(get_in(run, "spec.retry_policy"))
        replaced = int(get_in(run, "status.slice_replacements", 0) or 0)
        fresh = [s for s in failed if s not in degraded]
        if FailureClass.slice_preempted not in policy.retry_on:
            return False
        if not fresh:
            # replacements pending — survivors keep running, and the
            # stall watchdog must KEEP watching them (a wedged survivor
            # set during a capacity shortage still needs the escalation
            # path), so this is deliberately not "handled"
            return False
        if not policy.retries_left(replaced):
            logger.warning("slice replacement budget exhausted", uid=uid,
                           slices=fresh, budget=policy.max_retries)
            return False
        checkpoint = get_in(run, "status.checkpoint", {}) or {}
        resume_env = {}
        if checkpoint.get("path"):
            resume_env[RESUME_CHECKPOINT_ENV] = str(checkpoint["path"])
            if checkpoint.get("step") is not None:
                resume_env[RESUME_STEP_ENV] = str(checkpoint["step"])
        cache_dir = self._compile_cache_dir()
        if cache_dir:
            resume_env[COMPILE_CACHE_ENV] = cache_dir
        flight_record("run.slice_preempted", uid=uid, slices=fresh,
                      survivors=(replicas - len(failed)) if replicas
                      else None)
        submitted = []
        for slice_index in fresh:
            if not policy.retries_left(replaced):
                # re-checked per slice: several slices failing in one
                # tick must not overrun the budget together
                logger.warning("slice replacement budget exhausted",
                               uid=uid, slice=slice_index,
                               budget=policy.max_retries)
                break
            try:
                child = self.provider.replace_slice(
                    resource_id, slice_index, extra_env=resume_env)
            except Exception as exc:  # noqa: BLE001 - a failed slice
                # replacement degrades to the full-resubmit safety net
                # on a later tick (the slice stays listed as failed)
                logger.warning("slice replacement failed", uid=uid,
                               slice=slice_index, error=str(exc))
                continue
            submitted.append(slice_index)
            replaced += 1
            RUN_RETRIES.inc(failure_class=FailureClass.slice_preempted)
            flight_record("run.slice_replacement", uid=uid,
                          slice=slice_index, resource=child,
                          attempt=replaced)
        if not submitted:
            return False
        self.db.update_run(
            {"status.degraded_slices": sorted(set(degraded)
                                              | set(submitted)),
             "status.slice_replacements": replaced,
             "status.status_text":
             f"slice(s) {submitted} preempted — replacement submitted, "
             "survivors continue resharded"},
            uid, project, iter=iteration)
        get_tracer().emit(
            "run.slice_replacement", trace_id_for(uid),
            attrs={"uid": uid, "slices": submitted,
                   "resource": resource_id})
        logger.info("submitted slice replacement", uid=uid,
                    slices=submitted, resource=resource_id)
        return True

    def _customize_retry_manifest(self, manifest: dict, run: dict,
                                  attempt: int):
        """Rescheduled pod-slices resume instead of restarting: fix the
        JobSet's name-derived wiring (headless-service subdomain, the
        MEGASCALE coordinator address) for the renamed manifest, inject
        the latest checkpoint path + step recorded on
        ``status.checkpoint`` so training/train.py restores before the
        first step, and thread the persistent compile-cache dir so the
        replacement skips XLA recompilation (warm restart)."""
        new_name = manifest.get("metadata", {}).get("name", "")
        checkpoint = get_in(run, "status.checkpoint", {}) or {}
        resume_env = []
        if checkpoint.get("path"):
            resume_env.append({"name": RESUME_CHECKPOINT_ENV,
                               "value": str(checkpoint["path"])})
            if checkpoint.get("step") is not None:
                resume_env.append({"name": RESUME_STEP_ENV,
                                   "value": str(checkpoint["step"])})
        cache_dir = self._compile_cache_dir()
        if cache_dir:
            resume_env.append({"name": COMPILE_CACHE_ENV,
                               "value": cache_dir})
        for job in get_in(manifest, "spec.replicatedJobs", []) or []:
            pod_spec = get_in(job, "template.spec.template.spec", {}) or {}
            if pod_spec.get("subdomain") and new_name:
                pod_spec["subdomain"] = new_name
            for container in pod_spec.get("containers", []):
                env = container.setdefault("env", [])
                for item in env:
                    if item.get("name") == "MEGASCALE_COORDINATOR_ADDRESS" \
                            and new_name:
                        item["value"] = f"{new_name}-slice-0-0.{new_name}"
                # upsert: the pristine manifest may already carry the
                # cache env (build_resource) — overwrite in place rather
                # than appending a duplicate name
                for item in resume_env:
                    for existing in env:
                        if existing.get("name") == item["name"]:
                            existing["value"] = item["value"]
                            break
                    else:
                        env.append(copy.deepcopy(item))


class DaskHandler(KubeJobHandler):
    kind = RuntimeKinds.dask


class SparkHandler(BaseRuntimeHandler):
    """SparkApplication CRD (reference sparkjob handler). Requires the
    kubernetes provider — a local process cannot materialize a spark
    cluster."""

    kind = RuntimeKinds.spark

    def build_resource(self, runtime, run: RunObject) -> dict:
        if isinstance(self.provider, LocalProcessProvider):
            raise ValueError(
                "the spark runtime needs a kubernetes provider with the "
                "spark-operator installed; run with local=True for a local "
                "SparkSession instead")
        return runtime.generate_spark_application(run)


def get_runtime_handler(kind: str, db, provider: Provider
                        ) -> BaseRuntimeHandler:
    cls = {
        RuntimeKinds.job: KubeJobHandler,
        RuntimeKinds.tpujob: TpuJobHandler,
        RuntimeKinds.dask: DaskHandler,
        RuntimeKinds.spark: SparkHandler,
    }.get(kind)
    if cls is None:
        raise ValueError(f"no runtime handler for kind '{kind}'")
    return cls(db, provider)
