"""Server-side launcher (reference analog: server/api/launcher.py:40
ServerSideLauncher — enrich → store function → ctx → generator →
runtime_handler.run() :160-168)."""

from __future__ import annotations

import threading
from typing import Optional

from ..common.runtimes_constants import RunStates, RuntimeKinds
from ..config import mlconf
from ..launcher.base import BaseLauncher
from ..model import RunObject
from ..obs import RUN_SUBMITS, get_tracer, trace_id_for
from ..runtimes import get_runtime_class
from ..utils import generate_uid, logger, now_iso
from .runtime_handlers import Provider, get_runtime_handler


class ServerSideLauncher(BaseLauncher):
    def __init__(self, db, provider: Provider):
        self.db = db
        self.provider = provider
        self._handlers: dict[str, object] = {}

    def handler_for(self, kind: str):
        if kind not in self._handlers:
            self._handlers[kind] = get_runtime_handler(
                kind, self.db, self.provider)
        return self._handlers[kind]

    def recover(self):
        """Rebuild handler resource maps after a service restart (reference
        base.py:65 lists cluster resources by label; here DB rows + provider
        discovery)."""
        kinds: set[str] = set()
        lister = getattr(self.db, "list_runtime_resources", None)
        if lister:
            try:
                kinds = {row["kind"] for row in lister() if row.get("kind")}
            except Exception as exc:  # noqa: BLE001
                logger.warning("resource recovery listing failed",
                               error=str(exc))
        if hasattr(self.provider, "list_resources"):
            # provider label discovery must run even for kinds with zero DB
            # rows (lost/fresh DB with live cluster resources)
            kinds |= set(RuntimeKinds.handled_kinds())
        for kind in kinds:
            try:
                self.handler_for(kind).recover_resources()
            except Exception as exc:  # noqa: BLE001 - recover what we can
                logger.warning("resource recovery failed", kind=kind,
                               error=str(exc))

    def monitor_all(self):
        for handler in self._handlers.values():
            try:
                handler.monitor_runs()
            except Exception as exc:  # noqa: BLE001 - keep the loop alive
                logger.warning("monitor_runs failed", error=str(exc))

    def launch(self, runtime, task: RunObject, schedule=None, watch=False,
               auto_build=False, **kwargs) -> RunObject:
        self.enrich_runtime(runtime)
        run = self._enrich_run(runtime, task)
        self._validate_run(run)
        run.status.state = RunStates.pending
        struct = run.to_dict()
        struct["status"]["state"] = RunStates.pending
        self.db.store_run(struct, run.metadata.uid, run.metadata.project)

        if run.spec.is_hyper_job():
            # hyper sweeps fan out as independent child resources; the
            # parent aggregation runs in a service thread
            thread = threading.Thread(
                target=self._run_hyper, args=(runtime, run), daemon=True)
            thread.start()
            return run

        handler = self.handler_for(runtime.kind)
        RUN_SUBMITS.inc(kind=runtime.kind)
        # run-lifecycle trace: every span of this run (submit here,
        # retry/resume/stall in the monitor) shares the uid-derived trace
        # id, so one timeline covers submit → schedule → running → retry
        with get_tracer().span(
                "run.submit", trace_id=trace_id_for(run.metadata.uid),
                attrs={"uid": run.metadata.uid, "kind": runtime.kind,
                       "project": run.metadata.project}):
            try:
                handler.run(runtime, run)
            except Exception as exc:  # noqa: BLE001 - record the failure
                self.db.update_run(
                    {"status.state": RunStates.error,
                     "status.error": str(exc)},
                    run.metadata.uid, run.metadata.project)
                raise
        return run

    def _run_hyper(self, runtime, run: RunObject):
        """Aggregate hyper-param children (executed inline server-side via
        the local provider contract — each iteration is its own resource)."""
        from ..execution import MLClientCtx

        execution = MLClientCtx.from_dict(
            run.to_dict(), rundb=self.db, store_run=False)
        try:
            # the iteration bodies execute through the runtime handler's
            # resource; for the sweep itself we reuse the shared hyper loop
            # with a runtime that launches and waits per child
            wrapper = _HandlerBackedRuntime(self, runtime)
            result = self._run_with_hyperparams(wrapper, run, execution)
        except Exception as exc:  # noqa: BLE001
            self.db.update_run(
                {"status.state": RunStates.error, "status.error": str(exc)},
                run.metadata.uid, run.metadata.project)


class _HandlerBackedRuntime:
    """Adapter giving the hyper loop a `_run(task, ctx)` that launches a
    child resource through the handler and waits for completion."""

    def __init__(self, launcher: ServerSideLauncher, runtime):
        self.launcher = launcher
        self.runtime = runtime

    def _run(self, task: RunObject, execution) -> dict:
        import time

        db = self.launcher.db
        task.metadata.uid = task.metadata.uid or generate_uid()
        db.store_run(task.to_dict(), task.metadata.uid, task.metadata.project,
                     iter=task.metadata.iteration)
        handler = self.launcher.handler_for(self.runtime.kind)
        handler.run(self.runtime, task)
        deadline = time.monotonic() + 24 * 3600
        while time.monotonic() < deadline:
            handler.monitor_runs()
            run = db.read_run(task.metadata.uid, task.metadata.project,
                              iter=task.metadata.iteration) or {}
            state = run.get("status", {}).get("state")
            if state in RunStates.terminal_states():
                return run
            time.sleep(0.5)
        raise TimeoutError("hyper-param iteration timed out")


def rebuild_function(struct: dict):
    """Rebuild a runtime object from its stored dict."""
    kind = struct.get("kind", RuntimeKinds.job)
    runtime = get_runtime_class(kind).from_dict(struct)
    runtime.kind = kind
    return runtime
