"""Shared HTTP helpers for the service's API modules (the service-side
analog of the reference's server/api/api/utils.py response helpers)."""

from __future__ import annotations

import json

from aiohttp import web

from ..config import mlconf

API = mlconf.api_base_path.rstrip("/")


def json_response(data, status: int = 200):
    return web.json_response(data, status=status, dumps=lambda d: json.dumps(
        d, default=str))


def error_response(message: str, status: int = 400):
    return web.json_response({"detail": message}, status=status)


def paginate(items: list, request) -> list:
    """limit/offset slicing for list endpoints (reference pagination
    analog — token-based pagination cache is R2)."""
    try:
        offset = int(request.query.get("offset", 0))
        limit = int(request.query.get("limit", 0))
    except ValueError:
        return items
    if offset:
        items = items[offset:]
    if limit:
        items = items[:limit]
    return items


def token_paginated_response(state, request, method: str, key: str,
                             filters: dict):
    """Token-pagination branch shared by list endpoints: parse page
    params, delegate to the DB pagination cache, shape the response."""
    from ..db.base import RunDBError

    q = request.query
    try:
        items, token = state.db.paginated_list(
            method, page_size=int(q.get("page_size", 20)),
            page_token=q.get("page_token", ""), **filters)
    except (RunDBError, ValueError) as exc:
        return error_response(str(exc), 400)
    return json_response({key: items,
                          "pagination": {"page_token": token}})
