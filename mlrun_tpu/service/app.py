"""The metadata/orchestration service — aiohttp REST app.

Reference analog: server/api/main.py:93 FastAPI `app` + the 37 routers in
server/api/api/api.py, reduced to the same REST contract the SDK's HTTPRunDB
speaks. FastAPI/SQLAlchemy are replaced by aiohttp + the embedded SQLite DB.
Periodic tasks mirror main.py:608 (runs monitoring) and the APScheduler-based
Scheduler (utils/scheduler.py) is replaced by service/cron.py.
"""

from __future__ import annotations

import asyncio
import json
import os
import threading
import time
from datetime import datetime, timezone
from typing import Optional

from aiohttp import web

from .. import __version__
from ..common.runtimes_constants import RunStates, RuntimeKinds
from ..config import mlconf
from ..db.sqlitedb import SQLiteRunDB
from ..model import RunObject
from ..utils import generate_uid, get_in, logger, now_iso, update_in
from .cron import CronSchedule
from .launcher import ServerSideLauncher, rebuild_function
from .runtime_handlers import LocalProcessProvider

API = mlconf.api_base_path.rstrip("/")


def token_paginated_response(state, request, method: str, key: str,
                             filters: dict):
    """Token-pagination branch shared by list endpoints: parse page
    params, delegate to the DB pagination cache, shape the response."""
    from ..db.base import RunDBError

    q = request.query
    try:
        items, token = state.db.paginated_list(
            method, page_size=int(q.get("page_size", 20)),
            page_token=q.get("page_token", ""), **filters)
    except (RunDBError, ValueError) as exc:
        return error_response(str(exc), 400)
    return json_response({key: items,
                          "pagination": {"page_token": token}})


def paginate(items: list, request) -> list:
    """limit/offset slicing for list endpoints (reference pagination
    analog — token-based pagination cache is R2)."""
    try:
        offset = int(request.query.get("offset", 0))
        limit = int(request.query.get("limit", 0))
    except ValueError:
        return items
    if offset:
        items = items[offset:]
    if limit:
        items = items[:limit]
    return items


def json_response(data, status: int = 200):
    return web.json_response(data, status=status, dumps=lambda d: json.dumps(
        d, default=str))


def error_response(message: str, status: int = 400):
    return web.json_response({"detail": message}, status=status)


class ServiceState:
    def __init__(self, db: SQLiteRunDB | None = None, provider=None):
        from .deployments import DeploymentManager

        self.db = db or SQLiteRunDB()
        self.provider = provider or LocalProcessProvider(self.db)
        self.launcher = ServerSideLauncher(self.db, self.provider)
        self.launcher.recover()  # re-adopt resources from before a restart
        self.deployments = DeploymentManager(self.db, self.provider)
        from .builder import FunctionBuilder

        self.builder = FunctionBuilder(self.db, self.provider)
        from .projects_sync import ProjectsFollower

        self.projects_follower = ProjectsFollower(self.db)
        self.background_tasks: dict[str, dict] = {}
        self.workflows: dict[str, dict] = {}
        self.started = time.time()


def auth_middleware():
    """Bearer-token auth for the whole API when a service token is
    configured (mlconf.httpdb.auth_token / MLT_SERVICE_TOKEN). healthz
    stays open for probes. Without a token the service is open — matching
    the reference's default in-cluster posture."""

    @web.middleware
    async def middleware(request, handler):
        required = mlconf.httpdb.auth_token or os.environ.get(
            "MLT_SERVICE_TOKEN", "")
        healthz = mlconf.api_base_path.rstrip("/") + "/healthz"
        if required and request.path.rstrip("/") != healthz:
            header = request.headers.get("Authorization", "")
            if header != f"Bearer {required}":
                return error_response("unauthorized", 401)
        return await handler(request)

    return middleware


def build_app(state: ServiceState | None = None) -> web.Application:
    from .clusterization import clusterization_middleware, is_chief

    state = state or ServiceState()
    app = web.Application(client_max_size=64 * 1024 * 1024,
                          middlewares=[auth_middleware(),
                                       clusterization_middleware()])
    app["state"] = state
    app["is_chief"] = is_chief()

    r = web.RouteTableDef()

    # -- health / spec ------------------------------------------------------
    @r.get(f"{API}/healthz")
    async def healthz(request):
        return json_response({"status": "ok", "version": __version__})

    @r.get(f"{API}/client-spec")
    async def client_spec(request):
        return json_response({
            "version": __version__,
            "namespace": mlconf.namespace,
            "default_project": mlconf.default_project,
            "tpu_defaults": mlconf.tpu.to_dict(),
            "config_overrides": {},
        })

    # -- runs ----------------------------------------------------------------
    @r.post(API + "/projects/{project}/runs/{uid}")
    async def store_run(request):
        body = await request.json()
        state.db.store_run(body, request.match_info["uid"],
                           request.match_info["project"],
                           iter=int(request.query.get("iter", 0)))
        return json_response({"ok": True})

    @r.patch(API + "/projects/{project}/runs/{uid}")
    async def update_run(request):
        body = await request.json()
        state.db.update_run(body, request.match_info["uid"],
                            request.match_info["project"],
                            iter=int(request.query.get("iter", 0)))
        return json_response({"ok": True})

    @r.get(API + "/projects/{project}/runs/{uid}")
    async def read_run(request):
        run = state.db.read_run(request.match_info["uid"],
                                request.match_info["project"],
                                iter=int(request.query.get("iter", 0)))
        if run is None:
            return error_response("run not found", 404)
        return json_response({"data": run})

    @r.get(API + "/projects/{project}/runs")
    async def list_runs(request):
        q = request.query
        filters = dict(
            name=q.get("name", ""), project=request.match_info["project"],
            state=q.get("state", ""), labels=q.getall("label", None),
            last=int(q.get("last", 0)), iter=bool(int(q.get("iter", 0))),
            uid=q.getall("uid", None))
        if "page_size" in q or "page_token" in q:
            return token_paginated_response(state, request, "list_runs",
                                            "runs", filters)
        runs = state.db.list_runs(**filters)
        return json_response({"runs": paginate(runs, request)})

    @r.delete(API + "/projects/{project}/runs/{uid}")
    async def del_run(request):
        state.db.del_run(request.match_info["uid"],
                         request.match_info["project"],
                         iter=int(request.query.get("iter", 0)))
        return json_response({"ok": True})

    @r.post(API + "/projects/{project}/runs/{uid}/abort")
    async def abort_run(request):
        uid = request.match_info["uid"]
        project = request.match_info["project"]
        run = state.db.read_run(uid, project)
        if run is None:
            return error_response("run not found", 404)
        kind = get_in(run, "metadata.labels.kind", "job")
        try:
            handler = state.launcher.handler_for(kind)
            handler.abort_run(uid, project)
        except ValueError:
            state.db.abort_run(uid, project)
        state.db.emit_event("run_aborted", {"uid": uid}, project)
        return json_response({"ok": True})

    # -- logs ----------------------------------------------------------------
    @r.post(API + "/projects/{project}/logs/{uid}")
    async def store_log(request):
        body = await request.read()
        state.db.store_log(request.match_info["uid"],
                           request.match_info["project"], body,
                           append=bool(int(request.query.get("append", 1))))
        return json_response({"ok": True})

    @r.get(API + "/projects/{project}/logs/{uid}")
    async def get_log(request):
        log_state, data = state.db.get_log(
            request.match_info["uid"], request.match_info["project"],
            offset=int(request.query.get("offset", 0)),
            size=int(request.query.get("size", -1)))
        return web.Response(body=data, headers={
            "x-mlt-run-state": log_state or "unknown"})

    @r.get(API + "/projects/{project}/logs/{uid}/size")
    async def get_log_size(request):
        size = state.db.get_log_size(request.match_info["uid"],
                                     request.match_info["project"])
        return json_response({"size": size})

    # -- artifacts ------------------------------------------------------------
    @r.post(API + "/projects/{project}/artifacts/{key}")
    async def store_artifact(request):
        body = await request.json()
        q = request.query
        state.db.store_artifact(
            request.match_info["key"], body, uid=q.get("uid"),
            iter=int(q.get("iter") or 0), tag=q.get("tag", ""),
            project=request.match_info["project"], tree=q.get("tree"))
        return json_response({"ok": True})

    @r.get(API + "/projects/{project}/artifacts/{key}")
    async def read_artifact(request):
        from ..db.base import RunDBError

        q = request.query
        try:
            artifact = state.db.read_artifact(
                request.match_info["key"], tag=q.get("tag"),
                iter=int(q.get("iter") or 0) if q.get("iter") else None,
                project=request.match_info["project"], tree=q.get("tree"),
                uid=q.get("uid"))
        except RunDBError as exc:
            return error_response(str(exc), 404)
        return json_response({"data": artifact})

    @r.get(API + "/projects/{project}/artifacts")
    async def list_artifacts(request):
        q = request.query
        filters = dict(
            name=q.get("name", ""), project=request.match_info["project"],
            tag=q.get("tag"), labels=q.getall("label", None),
            kind=q.get("kind"), tree=q.get("tree"))
        if "page_size" in q or "page_token" in q:
            return token_paginated_response(
                state, request, "list_artifacts", "artifacts", filters)
        artifacts = state.db.list_artifacts(**filters)
        return json_response(
            {"artifacts": paginate(artifacts, request)})

    @r.delete(API + "/projects/{project}/artifacts/{key}")
    async def del_artifact(request):
        state.db.del_artifact(
            request.match_info["key"], tag=request.query.get("tag"),
            project=request.match_info["project"],
            uid=request.query.get("uid"))
        return json_response({"ok": True})

    # -- functions -------------------------------------------------------------
    @r.post(API + "/projects/{project}/functions/{name}")
    async def store_function(request):
        body = await request.json()
        hash_key = state.db.store_function(
            body, request.match_info["name"], request.match_info["project"],
            tag=request.query.get("tag", ""),
            versioned=bool(int(request.query.get("versioned", 0))))
        return json_response({"hash_key": hash_key})

    @r.get(API + "/projects/{project}/functions/{name}")
    async def get_function(request):
        from ..db.base import RunDBError

        try:
            func = state.db.get_function(
                request.match_info["name"], request.match_info["project"],
                tag=request.query.get("tag", ""),
                hash_key=request.query.get("hash_key", ""))
        except RunDBError as exc:
            return error_response(str(exc), 404)
        return json_response({"func": func})

    @r.get(API + "/projects/{project}/functions")
    async def list_functions(request):
        funcs = state.db.list_functions(
            name=request.query.get("name", ""),
            project=request.match_info["project"],
            tag=request.query.get("tag", ""),
            labels=request.query.getall("label", None))
        return json_response({"funcs": paginate(funcs, request)})

    @r.delete(API + "/projects/{project}/functions/{name}")
    async def delete_function(request):
        # a live gateway dies with its function
        loop = asyncio.get_event_loop()
        await loop.run_in_executor(
            None, lambda: state.deployments.teardown(
                request.match_info["name"], request.match_info["project"],
                store_state=False))
        state.db.delete_function(request.match_info["name"],
                                 request.match_info["project"])
        return json_response({"ok": True})

    @r.post(API + "/projects/{project}/functions/{name}/deploy")
    async def deploy_function(request):
        """Deploy = a RUNNING, addressable gateway (reference nuclio
        function.py:551; serving.py:580). The deployment manager spawns an
        ASGI graph-server process (local provider) or a Deployment+Service
        (kubernetes) and answers once it's invocable."""
        body = await request.json()
        function = body.get("function", {})
        update_in(function, "metadata.name", request.match_info["name"])
        update_in(function, "metadata.project",
                  request.match_info["project"])
        kind = function.get("kind", "")
        if kind not in (RuntimeKinds.serving, RuntimeKinds.remote,
                        RuntimeKinds.application):
            # batch kinds have nothing to run until submitted — deploy just
            # resolves the image + readiness (the build path)
            update_in(function, "status.state", "ready")
            state.db.store_function(
                function, request.match_info["name"],
                request.match_info["project"],
                tag=function.get("metadata", {}).get("tag", "latest"))
            return json_response({"data": {"state": "ready",
                                           "address": ""}})
        loop = asyncio.get_event_loop()
        info = await loop.run_in_executor(
            None, lambda: state.deployments.deploy(function))
        if info["state"] == "error":
            return error_response(
                f"function deploy failed: {info.get('error', '')}", 400)
        return json_response({"data": info})

    @r.delete(API + "/projects/{project}/functions/{name}/deploy")
    async def undeploy_function(request):
        loop = asyncio.get_event_loop()
        removed = await loop.run_in_executor(
            None, lambda: state.deployments.teardown(
                request.match_info["name"], request.match_info["project"]))
        return json_response({"removed": removed})

    # -- build ------------------------------------------------------------------
    @r.post(API + "/build/function")
    async def build_function(request):
        """Real build path (reference server/api/utils/builder.py:39,144 +
        endpoints/functions.py:272): prebuilt image + code-in-env stays a
        no-op, but requirements/commands now trigger an actual build — a
        venv-cache pre-warm (local provider) or a Kaniko pod (kubernetes),
        tracked as a background task with a retrievable log."""
        body = await request.json()
        function = body.get("function", {})
        with_tpu = body.get("with_tpu", False)
        loop = asyncio.get_event_loop()
        status = await loop.run_in_executor(
            None, lambda: state.builder.build(function, with_tpu=with_tpu))
        return json_response({"data": {"status": status}})

    @r.get(API + "/build/status")
    async def build_status(request):
        """Build state + incremental log (reference get_builder_status)."""
        status = state.builder.status(
            request.query.get("name", ""),
            request.query.get("project", "") or mlconf.default_project,
            tag=request.query.get("tag", "latest"),
            offset=int(request.query.get("offset", 0) or 0))
        if status["state"] == "not_found":
            return error_response("function not found", 404)
        return json_response({"data": status})

    # -- submit ------------------------------------------------------------------
    @r.post(API + "/submit_job")
    async def submit_job(request):
        """The core submission path (reference endpoints/submit.py:40 →
        api/utils.py:207 submit_run)."""
        body = await request.json()
        function_dict = body.get("function")
        task = body.get("task") or {"metadata": body.get("metadata", {}),
                                    "spec": body.get("spec", {})}
        schedule = body.get("schedule")
        if not function_dict:
            # resolve from the db via task.spec.function uri
            uri = get_in(task, "spec.function", "")
            if not uri:
                return error_response("missing function")
            project_part, _, rest = uri.partition("/")
            name, _, tag = rest.partition(":")
            tag, _, hash_key = tag.partition("@")
            function_dict = state.db.get_function(
                name, project_part, tag=tag or "latest")

        run = RunObject.from_dict(
            {"metadata": task.get("metadata", {}),
             "spec": task.get("spec", {})})
        run.metadata.uid = run.metadata.uid or generate_uid()
        run.metadata.project = (run.metadata.project
                                or mlconf.default_project)
        runtime = rebuild_function(function_dict)
        run.metadata.labels.setdefault("kind", runtime.kind)
        # notification secret-params never reach the stored run or the
        # resource env (reference api/utils.py:221 mask_notification_params)
        from .secrets import mask_notification_params

        mask_notification_params(state.db, run)

        if schedule:
            record = {
                "name": run.metadata.name, "project": run.metadata.project,
                "kind": "job", "cron_trigger": schedule,
                "scheduled_object": {"function": function_dict,
                                     "task": run.to_dict()},
                "creation_time": now_iso(),
            }
            try:
                cron = CronSchedule(schedule)
            except ValueError as exc:
                return error_response(f"bad schedule: {exc}")
            if cron.min_interval_seconds() < \
                    mlconf.scheduler.min_allowed_interval_seconds:
                return error_response("schedule interval below minimum")
            record["next_run_time"] = str(
                cron.next_after(datetime.now(timezone.utc)))
            state.db.store_schedule(run.metadata.project, run.metadata.name,
                                    record)
            return json_response({"data": {"schedule": schedule,
                                           "metadata":
                                           run.to_dict()["metadata"]}})

        loop = asyncio.get_event_loop()
        try:
            await loop.run_in_executor(
                None, lambda: state.launcher.launch(runtime, run))
        except Exception as exc:  # noqa: BLE001
            return error_response(f"launch failed: {exc}", 500)
        return json_response({"data": run.to_dict()})

    # -- schedules -----------------------------------------------------------------
    @r.post(API + "/projects/{project}/schedules/{name}")
    async def store_schedule(request):
        body = await request.json()
        try:
            CronSchedule(body.get("cron_trigger", ""))
        except ValueError as exc:
            return error_response(f"bad cron: {exc}")
        state.db.store_schedule(request.match_info["project"],
                                request.match_info["name"], body)
        return json_response({"ok": True})

    @r.get(API + "/projects/{project}/schedules/{name}")
    async def get_schedule(request):
        from ..db.base import RunDBError

        try:
            schedule = state.db.get_schedule(request.match_info["project"],
                                             request.match_info["name"])
        except RunDBError as exc:
            return error_response(str(exc), 404)
        return json_response({"data": schedule})

    @r.get(API + "/projects/{project}/schedules")
    async def list_schedules(request):
        return json_response({"schedules": state.db.list_schedules(
            request.match_info["project"])})

    @r.delete(API + "/projects/{project}/schedules/{name}")
    async def delete_schedule(request):
        state.db.delete_schedule(request.match_info["project"],
                                 request.match_info["name"])
        return json_response({"ok": True})

    # -- projects ---------------------------------------------------------------------
    @r.post(API + "/projects/{name}")
    async def store_project(request):
        body = await request.json()
        name = request.match_info["name"]
        if state.projects_follower.enabled:
            # leader-first (reference follower.py create/store flow)
            loop = asyncio.get_event_loop()
            try:
                stored = await loop.run_in_executor(
                    None,
                    lambda: state.projects_follower.forward_store(name,
                                                                  body))
            except Exception as exc:  # noqa: BLE001
                return error_response(f"project leader rejected: {exc}",
                                      502)
            return json_response({"data": stored})
        stored = state.db.store_project(name, body)
        return json_response({"data": stored})

    @r.get(API + "/projects/{name}")
    async def get_project(request):
        project = state.db.get_project(request.match_info["name"])
        if project is None:
            return error_response("project not found", 404)
        return json_response({"data": project})

    @r.get(API + "/projects")
    async def list_projects(request):
        return json_response({"projects": state.db.list_projects(
            state=request.query.get("state"))})

    @r.delete(API + "/projects/{name}")
    async def delete_project(request):
        from ..db.base import RunDBError

        name = request.match_info["name"]
        strategy = request.query.get("deletion_strategy", "restricted")
        try:
            if state.projects_follower.enabled:
                loop = asyncio.get_event_loop()
                await loop.run_in_executor(
                    None,
                    lambda: state.projects_follower.forward_delete(
                        name, deletion_strategy=strategy))
            else:
                state.db.delete_project(name, deletion_strategy=strategy)
        except RunDBError as exc:
            return error_response(str(exc), 412)
        return json_response({"ok": True})

    # -- feature store -------------------------------------------------------------------
    def _fs_routes(kind: str, store, get, list_, delete):
        @r.post(API + "/projects/{project}/" + kind + "/{name}")
        async def _store(request):
            body = await request.json()
            uid = store(body, name=request.match_info["name"],
                        project=request.match_info["project"],
                        tag=request.query.get("tag"),
                        uid=request.query.get("uid"))
            return json_response({"uid": uid})

        @r.get(API + "/projects/{project}/" + kind + "/{name}")
        async def _get(request):
            from ..db.base import RunDBError

            try:
                obj = get(request.match_info["name"],
                          project=request.match_info["project"],
                          tag=request.query.get("tag"),
                          uid=request.query.get("uid"))
            except RunDBError as exc:
                return error_response(str(exc), 404)
            return json_response({"data": obj})

        @r.get(API + "/projects/{project}/" + kind)
        async def _list(request):
            objs = list_(project=request.match_info["project"],
                         name=request.query.get("name", ""),
                         tag=request.query.get("tag"))
            return json_response({kind.replace("-", "_"): objs})

        @r.delete(API + "/projects/{project}/" + kind + "/{name}")
        async def _delete(request):
            delete(request.match_info["name"],
                   project=request.match_info["project"])
            return json_response({"ok": True})

    _fs_routes("feature-sets", state.db.store_feature_set,
               state.db.get_feature_set, state.db.list_feature_sets,
               state.db.delete_feature_set)
    _fs_routes("feature-vectors", state.db.store_feature_vector,
               state.db.get_feature_vector, state.db.list_feature_vectors,
               state.db.delete_feature_vector)

    # -- model endpoints --------------------------------------------------------------------
    @r.post(API + "/projects/{project}/model-endpoints/{uid}")
    async def store_endpoint(request):
        body = await request.json()
        state.db.store_model_endpoint(request.match_info["project"],
                                      request.match_info["uid"], body)
        return json_response({"ok": True})

    @r.get(API + "/projects/{project}/model-endpoints/{uid}")
    async def get_endpoint(request):
        from ..db.base import RunDBError

        try:
            endpoint = state.db.get_model_endpoint(
                request.match_info["project"], request.match_info["uid"])
        except RunDBError as exc:
            return error_response(str(exc), 404)
        return json_response({"data": endpoint})

    @r.get(API + "/projects/{project}/model-endpoints")
    async def list_endpoints(request):
        endpoints = state.db.list_model_endpoints(
            request.match_info["project"],
            model=request.query.get("model", ""),
            function=request.query.get("function", ""),
            state=request.query.get("state", ""))
        return json_response({"endpoints": endpoints})

    @r.delete(API + "/projects/{project}/model-endpoints/{uid}")
    async def delete_endpoint(request):
        state.db.delete_model_endpoint(request.match_info["project"],
                                       request.match_info["uid"])
        return json_response({"ok": True})

    @r.get(API + "/projects/{project}/model-endpoints/{uid}/metrics")
    async def endpoint_metrics(request):
        """Metric time-series with time-range + downsampling (reference:
        model-endpoint metric values API over the TSDB layer)."""
        from ..model_monitoring.tsdb import get_metrics_tsdb

        q = request.query
        try:
            start = float(q.get("start", 0) or 0)
            end = float(q["end"]) if q.get("end") else None
            max_points = int(q.get("max_points", 1000))
        except ValueError:
            return error_response("bad time range", 400)
        tsdb = get_metrics_tsdb()
        project = request.match_info["project"]
        uid = request.match_info["uid"]
        if q.get("names_only") in ("true", "1"):
            return json_response(
                {"metrics": tsdb.list_metrics(project, uid)})
        return json_response({"series": tsdb.query(
            project, uid, metric=q.get("name", ""), start=start, end=end,
            max_points=max_points)})

    # -- alerts / events -------------------------------------------------------------------
    @r.post(API + "/projects/{project}/alerts/{name}")
    async def store_alert(request):
        body = await request.json()
        state.db.store_alert_config(request.match_info["name"], body,
                                    request.match_info["project"])
        return json_response({"ok": True})

    @r.get(API + "/projects/{project}/alerts/{name}")
    async def get_alert(request):
        from ..db.base import RunDBError

        try:
            alert = state.db.get_alert_config(request.match_info["name"],
                                              request.match_info["project"])
        except RunDBError as exc:
            return error_response(str(exc), 404)
        return json_response({"data": alert})

    @r.get(API + "/projects/{project}/alerts")
    async def list_alerts(request):
        return json_response({"alerts": state.db.list_alert_configs(
            request.match_info["project"])})

    @r.post(API + "/projects/{project}/alerts/{name}/silence")
    async def silence_alert(request):
        """Open (or clear) a silencing window on an alert config: body
        {"minutes": N} silences for N minutes; {"minutes": 0} clears."""
        from datetime import datetime, timedelta, timezone

        project = request.match_info["project"]
        name = request.match_info["name"]
        body = await request.json()
        try:
            alert = state.db.get_alert_config(name, project)
        except Exception:
            return error_response(f"alert {name} not found", 404)
        minutes = float(body.get("minutes", 0))
        if minutes > 0:
            until = datetime.now(timezone.utc) + timedelta(minutes=minutes)
            alert["silence_until"] = until.isoformat()
        else:
            alert["silence_until"] = ""
        state.db.store_alert_config(name, alert, project)
        return json_response({"data": alert})

    @r.delete(API + "/projects/{project}/alerts/{name}")
    async def delete_alert(request):
        state.db.delete_alert_config(request.match_info["name"],
                                     request.match_info["project"])
        return json_response({"ok": True})

    @r.post(API + "/projects/{project}/events/{kind}")
    async def emit_event(request):
        body = await request.json()
        project = request.match_info["project"]
        kind = request.match_info["kind"]
        state.db.emit_event(kind, body, project)
        from .alerts import process_event

        fired = process_event(state.db, project, kind, body)
        return json_response({"ok": True, "alerts_fired": fired})

    # -- workflows -----------------------------------------------------------------------
    @r.post(API + "/projects/{project}/workflows/submit")
    async def submit_workflow(request):
        body = await request.json()
        workflow_id = generate_uid()
        project = request.match_info["project"]
        state.workflows[workflow_id] = {
            "id": workflow_id, "project": project,
            "state": RunStates.running, "spec": body, "started": now_iso(),
        }

        def run_workflow():
            try:
                from ..projects.pipelines import load_and_run

                # workflow spec carries the project source + workflow path
                pipeline = body.get("pipeline", {})
                from ..projects import load_project

                proj = load_project(
                    context=pipeline.get("context", "./"),
                    name=project, save=False)
                status = proj.run(
                    name=pipeline.get("name", ""),
                    workflow_path=pipeline.get("path", ""),
                    arguments=body.get("arguments"),
                    artifact_path=body.get("artifact_path", ""),
                    engine="local")
                state.workflows[workflow_id]["state"] = status.state
            except Exception as exc:  # noqa: BLE001
                state.workflows[workflow_id]["state"] = RunStates.error
                state.workflows[workflow_id]["error"] = str(exc)

        threading.Thread(target=run_workflow, daemon=True).start()
        return json_response({"id": workflow_id})

    @r.get(API + "/projects/{project}/workflows/{workflow_id}")
    async def workflow_status(request):
        workflow = state.workflows.get(request.match_info["workflow_id"])
        if workflow is None:
            return error_response("workflow not found", 404)
        return json_response({"state": workflow["state"],
                              "error": workflow.get("error")})

    # -- api gateways (stored as api-gateway kind function objects) -------------
    @r.post(API + "/projects/{project}/api-gateways/{name}")
    async def store_api_gateway(request):
        body = await request.json()
        gateway = body.get("data", body)
        gateway["kind"] = "api-gateway"
        state.db.store_function(gateway, request.match_info["name"],
                                request.match_info["project"],
                                tag="latest")
        return json_response({"ok": True})

    @r.get(API + "/projects/{project}/api-gateways/{name}")
    async def get_api_gateway(request):
        from ..db.base import RunDBError

        try:
            gateway = state.db.get_function(
                request.match_info["name"], request.match_info["project"])
        except RunDBError as exc:
            return error_response(str(exc), 404)
        return json_response({"data": gateway})

    @r.get(API + "/projects/{project}/api-gateways")
    async def list_api_gateways(request):
        funcs = state.db.list_functions(
            project=request.match_info["project"])
        return json_response({"api_gateways": [
            f for f in funcs if f.get("kind") == "api-gateway"]})

    # -- project secrets (reference: server/api/api/endpoints/secrets.py;
    # values are write/delete-only over REST — the list surface returns
    # keys alone) ----------------------------------------------------------
    @r.post(API + "/projects/{project}/secrets")
    async def store_project_secrets(request):
        body = await request.json()
        provider = body.get("provider", "kubernetes")
        secrets = body.get("secrets") or {}
        if not isinstance(secrets, dict):
            return error_response("secrets must be a mapping")
        state.db.store_project_secrets(
            request.match_info["project"], secrets, provider=provider)
        return json_response({"ok": True})

    @r.get(API + "/projects/{project}/secret-keys")
    async def list_project_secret_keys(request):
        provider = request.query.get("provider", "kubernetes")
        keys = state.db.list_project_secret_keys(
            request.match_info["project"], provider=provider)
        return json_response({"secret_keys": keys})

    @r.delete(API + "/projects/{project}/secrets")
    async def delete_project_secrets(request):
        provider = request.query.get("provider", "kubernetes")
        keys = request.query.getall("secret", []) or None
        project = request.match_info["project"]
        state.db.delete_project_secrets(project, keys=keys,
                                        provider=provider)
        if keys is None and provider == "kubernetes":
            # full wipe: also remove the projected k8s Secret (best-effort;
            # the provider is gated on the kubernetes package)
            try:
                from .runtime_handlers import KubernetesProvider

                KubernetesProvider().delete_project_secret(project)
            except Exception:  # noqa: BLE001 - no cluster / not deployed
                pass
        return json_response({"ok": True})

    # -- datastore profiles (reference: server-side datastore_profile
    # endpoints; private fields go to the project-secret store and are
    # never returned) ------------------------------------------------------
    @r.put(API + "/projects/{project}/datastore-profiles/{name}")
    async def store_datastore_profile(request):
        body = await request.json()
        profile = body.get("profile") or {}
        profile["name"] = request.match_info["name"]
        state.db.store_datastore_profile(
            profile, request.match_info["project"],
            private=body.get("private") or None)
        return json_response({"ok": True})

    @r.get(API + "/projects/{project}/datastore-profiles/{name}")
    async def get_datastore_profile(request):
        profile = state.db.get_datastore_profile(
            request.match_info["name"], request.match_info["project"])
        if profile is None:
            return error_response("datastore profile not found", 404)
        return json_response({"data": profile})

    @r.get(API + "/projects/{project}/datastore-profiles")
    async def list_datastore_profiles(request):
        return json_response({"datastore_profiles":
                              state.db.list_datastore_profiles(
                                  request.match_info["project"])})

    @r.delete(API + "/projects/{project}/datastore-profiles/{name}")
    async def delete_datastore_profile(request):
        state.db.delete_datastore_profile(
            request.match_info["name"], request.match_info["project"])
        return json_response({"ok": True})

    # -- operations / introspection ---------------------------------------------
    # -- tags (reference server/api/api/endpoints/tags.py) -----------------
    @r.post(API + "/projects/{project}/tags/{tag}")
    async def overwrite_tag(request):
        body = await request.json()
        if body.get("kind", "artifact") != "artifact":
            return error_response("only artifact tagging is supported", 400)
        tagged = state.db.tag_artifacts(
            request.match_info["project"], request.match_info["tag"],
            body.get("identifiers") or [])
        return json_response({"tagged": tagged})

    @r.delete(API + "/projects/{project}/tags/{tag}")
    async def delete_tag(request):
        body = await request.json()
        if body.get("kind", "artifact") != "artifact":
            return error_response("only artifact tagging is supported", 400)
        removed = state.db.untag_artifacts(
            request.match_info["project"], request.match_info["tag"],
            body.get("identifiers") or [])
        return json_response({"removed": removed})

    def _file_access_denied(path: str) -> str | None:
        """Service internals are never readable through /files (the
        sqlite DB holds project secret values); an optional allowlist
        (mlconf.httpdb.files_allowed_paths) restricts everything else.
        Local paths (bare or file://) are compared by realpath; remote
        URLs (s3:// etc.) by raw prefix."""
        scheme, _, rest = path.partition("://")
        local = not rest or scheme == "file"
        local_path = (rest if scheme == "file" else path) if local else None
        allowed = [p.strip() for p in str(
            mlconf.httpdb.files_allowed_paths or "").split(",") if p.strip()]
        if local:
            real = os.path.realpath(local_path)
            dsn = os.path.realpath(getattr(state.db, "dsn", "") or "")
            if dsn and real in (dsn, dsn + "-wal", dsn + "-shm"):
                return "service database is not readable through /files"
            if allowed and not any(
                    (not a.partition("://")[1])
                    and (real.startswith(os.path.realpath(a) + os.sep)
                         or real == os.path.realpath(a))
                    for a in allowed):
                return "path is outside files_allowed_paths"
            return None
        if allowed and not any(path.startswith(a) for a in allowed):
            return "path is outside files_allowed_paths"
        return None

    # -- files (reference server/api/api/endpoints/files.py) ---------------
    @r.get(API + "/projects/{project}/files")
    async def get_file(request):
        from aiohttp import web as aioweb

        path = request.query.get("path", "")
        if not path:
            return error_response("path query parameter is required", 400)
        denied = _file_access_denied(path)
        if denied:
            return error_response(denied, 403)
        try:
            from ..datastore import store_manager

            size = int(request.query.get("size", 0)) or None
            offset = int(request.query.get("offset", 0))
            body = store_manager.object(url=path).get(size=size,
                                                      offset=offset)
        except FileNotFoundError:
            return error_response(f"file not found: {path}", 404)
        except Exception as exc:  # noqa: BLE001
            return error_response(f"failed to read {path}: {exc}", 400)
        if isinstance(body, str):
            body = body.encode()
        return aioweb.Response(body=body,
                               content_type="application/octet-stream")

    @r.get(API + "/projects/{project}/filestat")
    async def get_filestat(request):
        path = request.query.get("path", "")
        if not path:
            return error_response("path query parameter is required", 400)
        denied = _file_access_denied(path)
        if denied:
            return error_response(denied, 403)
        try:
            from ..datastore import store_manager

            stats = store_manager.object(url=path).stat()
        except FileNotFoundError:
            return error_response(f"file not found: {path}", 404)
        except Exception as exc:  # noqa: BLE001
            return error_response(f"failed to stat {path}: {exc}", 400)
        return json_response({"size": stats.size, "modified": stats.modified,
                              "content_type": getattr(stats, "content_type",
                                                      None)})

    # -- hub admin (reference server/api/api/endpoints/hub.py) -------------
    def _hub_source_path(name: str):
        if name == "default":
            from ..hub import builtin_hub_path

            return builtin_hub_path()
        source = state.db.get_hub_source(name)
        return (source or {}).get("path")

    @r.put(API + "/hub/sources/{name}")
    async def store_hub_source(request):
        body = await request.json()
        name = request.match_info["name"]
        if name == "default":
            return error_response("the default source is built-in", 400)
        state.db.store_hub_source(name, body.get("source") or body,
                                  order=int(body.get("order", -1)))
        return json_response({"data": state.db.get_hub_source(name)})

    @r.get(API + "/hub/sources")
    async def list_hub_sources(request):
        sources = [{"name": "default", "builtin": True}]
        sources.extend(state.db.list_hub_sources())
        return json_response({"sources": sources})

    @r.get(API + "/hub/sources/{name}")
    async def get_hub_source(request):
        name = request.match_info["name"]
        if name == "default":
            return json_response({"data": {"name": "default",
                                           "builtin": True}})
        source = state.db.get_hub_source(name)
        if source is None:
            return error_response(f"hub source {name} not found", 404)
        return json_response({"data": source})

    @r.delete(API + "/hub/sources/{name}")
    async def delete_hub_source(request):
        state.db.delete_hub_source(request.match_info["name"])
        return json_response({"ok": True})

    @r.get(API + "/hub/sources/{name}/items")
    async def hub_catalog(request):
        path = _hub_source_path(request.match_info["name"])
        if not path or not os.path.isdir(path):
            return error_response("hub source has no readable path", 404)
        items = []
        for entry in sorted(os.listdir(path)):
            fn_yaml = os.path.join(path, entry, "function.yaml")
            if os.path.isfile(fn_yaml):
                items.append({"name": entry})
        return json_response({"catalog": items})

    @r.get(API + "/hub/sources/{name}/items/{item}")
    async def hub_item(request):
        import yaml

        path = _hub_source_path(request.match_info["name"])
        item = request.match_info["item"]
        if ".." in item or "/" in item or os.sep in item:
            return error_response("invalid hub item name", 400)
        fn_yaml = os.path.join(path or "", item, "function.yaml")
        if not path or not os.path.isfile(fn_yaml):
            return error_response(f"hub item {item} not found", 404)
        with open(fn_yaml) as f:
            return json_response({"data": yaml.safe_load(f)})

    @r.get(API + "/operations/memory-report")
    async def memory_report(request):
        """reference analog: server/api/utils/memory_reports.py (objgraph) —
        here host RSS + device HBM via the profiler util."""
        from ..utils.profiler import memory_report as report

        return json_response({"data": report()})

    @r.get(API + "/frontend-spec")
    async def frontend_spec(request):
        from ..common.runtimes_constants import RuntimeKinds

        return json_response({
            "feature_flags": {"tpujob": True, "serving": True,
                              "feature_store": True,
                              "model_monitoring": True},
            "default_artifact_path": mlconf.resolve_artifact_path(
                "{project}"),
            "runtime_kinds": RuntimeKinds.all(),
        })

    # -- grafana proxy (reference: server/api/api/endpoints/grafana_proxy.py,
    # crud/model_monitoring/grafana.py — simpleJSON datasource contract) ----
    @r.get(API + "/grafana-proxy/model-endpoints")
    async def grafana_health(request):
        return json_response({"status": "ok"})

    @r.post(API + "/grafana-proxy/model-endpoints/search")
    async def grafana_search(request):
        body = await request.json() if request.can_read_body else {}
        project = (body.get("target") or "").split(":")[0] \
            or mlconf.default_project
        endpoints = state.db.list_model_endpoints(project)
        return json_response([e.get("uid") for e in endpoints])

    @r.post(API + "/grafana-proxy/model-endpoints/query")
    async def grafana_query(request):
        body = await request.json()
        rows = []
        columns = [{"text": "endpoint_id", "type": "string"},
                   {"text": "model", "type": "string"},
                   {"text": "requests", "type": "number"},
                   {"text": "avg_latency_microsec", "type": "number"},
                   {"text": "drift_status", "type": "string"}]
        for target in body.get("targets", [{}]):
            spec = (target.get("target") or "")
            project = spec.split(":")[0] or mlconf.default_project
            for endpoint in state.db.list_model_endpoints(project):
                metrics = endpoint.get("metrics", {})
                rows.append([
                    endpoint.get("uid"), endpoint.get("name"),
                    metrics.get("requests", 0),
                    metrics.get("avg_latency_microsec", 0),
                    endpoint.get("drift_status", "")])
        return json_response([{"type": "table", "columns": columns,
                               "rows": rows}])

    # -- background tasks --------------------------------------------------------------------
    @r.get(API + "/projects/{project}/background-tasks")
    async def list_background_tasks(request):
        return json_response({"background_tasks": state.db.list_background_tasks(
            request.match_info["project"])})

    @r.get(API + "/projects/{project}/background-tasks/{name}")
    async def get_background_task(request):
        task = state.db.get_background_task(
            request.match_info["name"], request.match_info["project"])
        if task is None:
            return error_response("background task not found", 404)
        return json_response({"data": task})

    # -- runtime resources (reference: server/api/api/endpoints/
    # runtime_resources.py — grouped listing + filtered deletion of the
    # cluster resources a run created) -------------------------------------
    @r.get(API + "/projects/{project}/runtime-resources")
    async def list_runtime_resources(request):
        project = request.match_info["project"]
        kind = request.query.get("kind", "")
        rows = state.db.list_runtime_resources(kind)
        if project not in ("*", ""):
            rows = [row for row in rows if row["project"] == project]
        grouped: dict = {}
        for row in rows:
            handler = state.launcher.handler_for(row["kind"])
            try:
                live_state = handler.provider.state(row["resource_id"])
            except Exception:  # noqa: BLE001 - provider may be gone
                live_state = "unknown"
            grouped.setdefault(row["kind"], []).append({
                **row, "state": live_state})
        return json_response({"runtime_resources": [
            {"kind": kind_, "resources": res}
            for kind_, res in sorted(grouped.items())]})

    @r.delete(API + "/projects/{project}/runtime-resources")
    async def delete_runtime_resources(request):
        project = request.match_info["project"]
        kind = request.query.get("kind", "")
        object_id = request.query.get("object-id", "")
        force = request.query.get("force", "") in ("true", "1")
        deleted = []
        for row in state.db.list_runtime_resources(kind):
            if project not in ("*", "") and row["project"] != project:
                continue
            if object_id and row["resource_id"] != object_id:
                continue
            run = state.db.read_run(row["uid"], row["project"])
            run_state = get_in(run or {}, "status.state", "")
            if not force and run_state not in RunStates.terminal_states():
                continue  # reference refuses to delete live runs w/o force
            handler = state.launcher.handler_for(row["kind"])
            try:
                # goes through the handler so the in-memory resource map is
                # also dropped — otherwise the next monitor tick would probe
                # the deleted resource and mark the run failed
                handler.delete_resources(row["uid"], row["project"],
                                         row["resource_id"])
            except Exception:  # noqa: BLE001 - provider may be gone; keep
                # the mapping so a later retry can still find the resource
                continue
            deleted.append(row)
        return json_response({"deleted": deleted})

    # -- pipelines (reference: server/api/api/endpoints/pipelines.py — a
    # KFP proxy; here the native workflow runner doubles as the pipeline
    # backend, and a kfp client is proxied only when installed) ------------
    @r.get(API + "/projects/{project}/pipelines")
    async def list_pipelines(request):
        project = request.match_info["project"]
        runs = [w for w in state.workflows.values()
                if project in ("*", "") or w.get("project") == project]
        return json_response({"runs": sorted(
            runs, key=lambda w: w.get("started", ""), reverse=True),
            "total_size": len(runs)})

    @r.get(API + "/projects/{project}/pipelines/{run_id}")
    async def get_pipeline(request):
        workflow = state.workflows.get(request.match_info["run_id"])
        if workflow is None:
            return error_response("pipeline run not found", 404)
        return json_response({"run": workflow})

    app.add_routes(r)
    app.on_startup.append(_start_periodic)
    app.on_cleanup.append(_stop_periodic)
    return app


async def _start_periodic(app: web.Application):
    state: ServiceState = app["state"]
    if not app.get("is_chief", True):
        # workers proxy mutating ops; only the chief monitors + schedules
        app["_periodic"] = []
        return

    async def monitor_loop():
        while True:
            await asyncio.sleep(
                min(float(mlconf.runs.monitoring_interval), 5.0))
            await asyncio.get_event_loop().run_in_executor(
                None, state.launcher.monitor_all)

    async def gateway_monitor_loop():
        # dead gateways flip their function status to error
        # (service/deployments.py monitor)
        while True:
            await asyncio.sleep(
                min(float(mlconf.runs.monitoring_interval), 5.0))
            try:
                await asyncio.get_event_loop().run_in_executor(
                    None, state.deployments.monitor)
            except Exception as exc:  # noqa: BLE001 - keep the loop alive
                logger.warning("gateway monitor failed", error=str(exc))

    async def scheduler_loop():
        fired: dict[tuple, str] = {}
        while True:
            await asyncio.sleep(float(mlconf.scheduler.tick_seconds))
            now = datetime.now(timezone.utc)
            minute_key = now.strftime("%Y%m%d%H%M")
            for schedule in state.db.list_schedules("*"):
                try:
                    cron = CronSchedule(schedule.get("cron_trigger", ""))
                except ValueError:
                    continue
                key = (schedule.get("project"), schedule.get("name"))
                if cron.matches(now) and fired.get(key) != minute_key:
                    fired[key] = minute_key
                    await _fire_schedule(state, schedule)

    app["_periodic"] = [
        asyncio.create_task(monitor_loop()),
        asyncio.create_task(gateway_monitor_loop()),
        asyncio.create_task(scheduler_loop()),
    ]

    if state.projects_follower.enabled:
        async def projects_sync_loop():
            while True:
                await asyncio.get_event_loop().run_in_executor(
                    None, state.projects_follower.sync_safe)
                await asyncio.sleep(
                    float(mlconf.projects.sync_interval))

        app["_periodic"].append(asyncio.create_task(projects_sync_loop()))


async def _fire_schedule(state: ServiceState, schedule: dict):
    """reference analog: scheduler.py:991 submit_run_wrapper."""
    try:
        obj = schedule.get("scheduled_object", {})
        runtime = rebuild_function(obj.get("function", {}))
        task = RunObject.from_dict(obj.get("task", {}))
        task.metadata.uid = generate_uid()
        loop = asyncio.get_event_loop()
        await loop.run_in_executor(
            None, lambda: state.launcher.launch(runtime, task))
        schedule["last_run_uri"] = (
            f"{task.metadata.project}/{task.metadata.uid}")
        state.db.store_schedule(schedule.get("project", ""),
                                schedule.get("name", ""), schedule)
        logger.info("schedule fired", name=schedule.get("name"))
    except Exception as exc:  # noqa: BLE001
        logger.error("schedule firing failed", name=schedule.get("name"),
                     error=str(exc))


async def _stop_periodic(app: web.Application):
    for task in app.get("_periodic", []):
        task.cancel()


def run_app(host: str = "", port: int = 0):
    host = host or mlconf.httpdb.host
    port = port or mlconf.httpdb.port
    # make the advertised port consistent for spawned run resources
    mlconf.httpdb.port = port
    logger.info("starting mlrun-tpu service", host=host, port=port,
                version=__version__)
    web.run_app(build_app(), host=host, port=port, print=None)
