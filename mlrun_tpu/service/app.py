"""The metadata/orchestration service — aiohttp REST app.

Reference analog: server/api/main.py:93 FastAPI `app` + the 37 routers in
server/api/api/api.py, reduced to the same REST contract the SDK's HTTPRunDB
speaks. FastAPI/SQLAlchemy are replaced by aiohttp + the embedded SQLite DB.
Periodic tasks mirror main.py:608 (runs monitoring) and the APScheduler-based
Scheduler (utils/scheduler.py) is replaced by service/cron.py.

This module keeps only the app assembly: ServiceState, middleware, the
periodic loops, and run_app. Every route lives in a per-resource module
under ``service/api/`` (the reference's endpoints/+crud/ layout).
"""

from __future__ import annotations

import asyncio
import os
import time
from datetime import datetime, timezone

from aiohttp import web

from .. import __version__
from ..config import mlconf
from ..db.sqlitedb import SQLiteRunDB
from ..model import RunObject
from ..utils import generate_uid, logger
from .cron import CronSchedule
from .http_utils import (  # noqa: F401 - re-exported for compat
    API,
    error_response,
    json_response,
    paginate,
    token_paginated_response,
)
from .launcher import ServerSideLauncher, rebuild_function
from .runtime_handlers import LocalProcessProvider


class ServiceState:
    def __init__(self, db: SQLiteRunDB | None = None, provider=None):
        from .deployments import DeploymentManager

        if db is None:
            from ..db.base import sql_dialect_for_dsn

            dsn = str(mlconf.httpdb.dsn or "")
            if sql_dialect_for_dsn(dsn):
                from ..db.sqldb import SQLServerRunDB

                db = SQLServerRunDB(dsn)
            else:
                db = SQLiteRunDB()
        self.db = db
        self.provider = provider or LocalProcessProvider(self.db)
        self.launcher = ServerSideLauncher(self.db, self.provider)
        self.launcher.recover()  # re-adopt resources from before a restart
        self.deployments = DeploymentManager(self.db, self.provider)
        from .builder import FunctionBuilder

        self.builder = FunctionBuilder(self.db, self.provider)
        from .projects_sync import ProjectsFollower

        self.projects_follower = ProjectsFollower(self.db)
        self.background_tasks: dict[str, dict] = {}
        self.workflows: dict[str, dict] = {}
        self.started = time.time()


def auth_middleware():
    """Bearer-token auth for the whole API when a service token is
    configured (mlconf.httpdb.auth_token / MLT_SERVICE_TOKEN). healthz
    stays open for probes. Without a token the service is open — matching
    the reference's default in-cluster posture."""

    @web.middleware
    async def middleware(request, handler):
        required = mlconf.httpdb.auth_token or os.environ.get(
            "MLT_SERVICE_TOKEN", "")
        # probes and scrapers stay open: healthz for the orchestrator,
        # /metrics for Prometheus (exposition carries no secrets)
        open_paths = {mlconf.api_base_path.rstrip("/") + "/healthz",
                      "/metrics"}
        if required and request.path.rstrip("/") not in open_paths:
            header = request.headers.get("Authorization", "")
            if header != f"Bearer {required}":
                return error_response("unauthorized", 401)
        return await handler(request)

    return middleware


def build_app(state: ServiceState | None = None) -> web.Application:
    from .api import REGISTRARS
    from .clusterization import clusterization_middleware, is_chief

    state = state or ServiceState()
    app = web.Application(client_max_size=64 * 1024 * 1024,
                          middlewares=[auth_middleware(),
                                       clusterization_middleware()])
    app["state"] = state
    app["is_chief"] = is_chief()

    r = web.RouteTableDef()
    for register in REGISTRARS:
        register(r, state)
    app.add_routes(r)
    app.on_startup.append(_start_periodic)
    app.on_cleanup.append(_stop_periodic)
    return app


async def _start_periodic(app: web.Application):
    state: ServiceState = app["state"]
    if not app.get("is_chief", True):
        # workers proxy mutating ops; only the chief monitors + schedules
        app["_periodic"] = []
        return

    async def monitor_loop():
        while True:
            await asyncio.sleep(
                min(float(mlconf.runs.monitoring_interval), 5.0))
            await asyncio.get_event_loop().run_in_executor(
                None, state.launcher.monitor_all)

    async def gateway_monitor_loop():
        # dead gateways flip their function status to error
        # (service/deployments.py monitor)
        while True:
            await asyncio.sleep(
                min(float(mlconf.runs.monitoring_interval), 5.0))
            try:
                await asyncio.get_event_loop().run_in_executor(
                    None, state.deployments.monitor)
            except Exception as exc:  # noqa: BLE001 - keep the loop alive
                logger.warning("gateway monitor failed", error=str(exc))

    async def scheduler_loop():
        fired: dict[tuple, str] = {}
        while True:
            await asyncio.sleep(float(mlconf.scheduler.tick_seconds))
            now = datetime.now(timezone.utc)
            minute_key = now.strftime("%Y%m%d%H%M")
            for schedule in state.db.list_schedules("*"):
                try:
                    cron = CronSchedule(schedule.get("cron_trigger", ""))
                except ValueError:
                    continue
                key = (schedule.get("project"), schedule.get("name"))
                if cron.matches(now) and fired.get(key) != minute_key:
                    fired[key] = minute_key
                    await _fire_schedule(state, schedule)

    app["_periodic"] = [
        asyncio.create_task(monitor_loop()),
        asyncio.create_task(gateway_monitor_loop()),
        asyncio.create_task(scheduler_loop()),
    ]

    slo_conf = mlconf.observability.slo
    if bool(mlconf.observability.metrics_enabled):
        # scrape→store(→burn-rate) loop over the service's own registry
        # (docs/observability.md "Federation" / "SLOs & burn rates").
        # The store ingestion always runs — it backs the grafana
        # /grafana-proxy/metrics datasource — while SLO evaluation only
        # runs when objectives are declared; fleet processes run their
        # own evaluator next to the autoscaler
        async def obs_loop():
            from ..obs import REGISTRY, MetricsAggregator, SLOEvaluator
            from ..obs.timeseries import get_store

            aggregator = MetricsAggregator.from_mlconf()
            evaluator = None
            if bool(slo_conf.enabled) and list(slo_conf.objectives or []):
                evaluator = SLOEvaluator.from_mlconf(
                    get_store(), project=mlconf.default_project)
            state.slo_evaluator = evaluator

            def evaluate():
                now = time.time()
                aggregator.ingest_text("service", REGISTRY.render(),
                                       at=now)
                aggregator.snapshot_to(get_store(), now)
                if evaluator is not None:
                    evaluator.process(state.db, now)

            while True:
                await asyncio.sleep(float(slo_conf.evaluation_interval_s))
                try:
                    await asyncio.get_event_loop().run_in_executor(
                        None, evaluate)
                except Exception as exc:  # noqa: BLE001 - keep the loop
                    logger.warning("obs ingest/slo evaluation failed",
                                   error=str(exc))

        app["_periodic"].append(asyncio.create_task(obs_loop()))

    if state.projects_follower.enabled:
        async def projects_sync_loop():
            while True:
                await asyncio.get_event_loop().run_in_executor(
                    None, state.projects_follower.sync_safe)
                await asyncio.sleep(
                    float(mlconf.projects.sync_interval))

        app["_periodic"].append(asyncio.create_task(projects_sync_loop()))


async def _fire_schedule(state: ServiceState, schedule: dict):
    """reference analog: scheduler.py:991 submit_run_wrapper."""
    try:
        obj = schedule.get("scheduled_object", {})
        runtime = rebuild_function(obj.get("function", {}))
        task = RunObject.from_dict(obj.get("task", {}))
        task.metadata.uid = generate_uid()
        loop = asyncio.get_event_loop()
        await loop.run_in_executor(
            None, lambda: state.launcher.launch(runtime, task))
        schedule["last_run_uri"] = (
            f"{task.metadata.project}/{task.metadata.uid}")
        state.db.store_schedule(schedule.get("project", ""),
                                schedule.get("name", ""), schedule)
        logger.info("schedule fired", name=schedule.get("name"))
    except Exception as exc:  # noqa: BLE001
        logger.error("schedule firing failed", name=schedule.get("name"),
                     error=str(exc))


async def _stop_periodic(app: web.Application):
    for task in app.get("_periodic", []):
        task.cancel()


def run_app(host: str = "", port: int = 0):
    host = host or mlconf.httpdb.host
    port = port or mlconf.httpdb.port
    # make the advertised port consistent for spawned run resources
    mlconf.httpdb.port = port
    from ..obs import configure_from_mlconf

    configure_from_mlconf()  # span JSONL path / ring for this service
    logger.info("starting mlrun-tpu service", host=host, port=port,
                version=__version__)
    web.run_app(build_app(), host=host, port=port, print=None)
