"""Metrics-driven fleet autoscaler (docs/observability.md "Autoscaler").

Closes the ROADMAP's scrape→scale loop: a recommendation loop over the
federated signals — per-replica load (queued + active work), KV-page
headroom, fleet p95 TTFT, dispatch failures — driving
``EngineFleet.add_replica`` / drain-and-remove. TPU serving economics
hinge on keeping the pod-slice fleet sized to traffic (idle replicas
burn accelerator-hours; an undersized fleet burns the latency SLO), so
the loop is deliberately conservative:

- **hysteresis** — a condition must hold for ``hysteresis_ticks``
  consecutive ticks before it becomes a recommendation;
- **cooldowns** — per-direction minimum spacing between applied
  actions (scale-down cools longer than scale-up: adding capacity is
  cheap, thrash is not);
- **bounds** — ``min_replicas``/``max_replicas`` clamp the worker pool;
- **drain-first scale-down** — the victim is drained (no new routing,
  ring keys move to neighbors) and only removed once its in-flight work
  hits zero or ``drain_grace_s`` expires; the engine then retires its
  own ``replica``-labeled series, so scale-down leaks nothing;
- **dry-run** — the default mode evaluates everything and records only
  ``mlt_autoscaler_recommendations_total{action,reason}``; flip
  ``dry_run=False`` to act.

Every tick fires the ``obs.autoscale`` chaos point with a mutable
``box``: a test's ``action()`` can overwrite ``box["action"]`` /
``box["reason"]`` and set ``box["force"]=True`` to bypass hysteresis and
cooldown — deterministic scale-event injection with no wall-clock
sleeps. Time is an explicit ``now`` argument to :meth:`tick` for the
same reason.
"""

from __future__ import annotations

import threading
from typing import Optional

from ..chaos import FaultPoints, fire
from ..common.journal import open_journal
from ..config import mlconf
from ..obs import (
    AUTOSCALER_ACTIONS,
    AUTOSCALER_DESIRED,
    AUTOSCALER_RECOMMENDATIONS,
    JOURNAL_WRITES,
    RECONCILE_ACTIONS,
)
from ..obs.flight import record as flight_record
from ..utils import logger

_WORKER_ROLES = ("unified", "decode")


class FleetAutoscaler:
    """One autoscaler per :class:`~mlrun_tpu.serving.fleet.EngineFleet`.

    ``store`` (an ``obs.TimeSeriesStore``) upgrades the p95-TTFT signal
    from the fleet's in-process sample ring to the federated windowed
    quantile; ``aggregator`` (an ``obs.MetricsAggregator``) upgrades
    queue depth / page headroom to the merged multi-source view. Both
    are optional — without them the loop runs off ``fleet.stats`` alone,
    so a single-process fleet needs no federation plumbing.
    """

    def __init__(self, fleet, store=None, aggregator=None,
                 slo=None, ttft_window: float = 60.0, pods=None,
                 journal=None, scorer=None, **overrides):
        conf = mlconf.serving.autoscale
        def knob(name, cast=float):
            if name in overrides:
                return cast(overrides.pop(name))
            return cast(getattr(conf, name))

        self.fleet = fleet
        self.store = store
        self.aggregator = aggregator
        # fail-slow detection (obs/health.py ReplicaHealthScorer): when
        # set, the scorer ticks on this loop's clock, probated replicas
        # are preferred scale-down victims, and persistent probation
        # triggers a drain-and-replace through the normal lifecycle
        self.scorer = scorer
        # cross-process elasticity (serving/podfleet.ServingPodFleet):
        # when set, scale actions submit/drain serving JobSets instead
        # of building in-process replicas, and every tick advances the
        # pod lifecycle state machine
        self.pods = pods
        self.dry_run = knob("dry_run", bool)
        self.min_replicas = knob("min_replicas", int)
        self.max_replicas = knob("max_replicas", int)
        self.hysteresis_ticks = knob("hysteresis_ticks", int)
        self.cooldown_up_s = knob("cooldown_up_s")
        self.cooldown_down_s = knob("cooldown_down_s")
        self.drain_grace_s = knob("drain_grace_s")
        self.queue_high = knob("queue_high")
        self.queue_low = knob("queue_low")
        self.free_page_frac_low = knob("free_page_frac_low")
        self.failure_rate_high = knob("failure_rate_high")
        ttft_high = knob("ttft_p95_high_s")
        if ttft_high <= 0 and slo is not None:
            ttft_high = float(slo.target)
        self.ttft_p95_high_s = ttft_high  # <= 0 disables the signal
        self.ttft_window = float(ttft_window)
        if overrides:
            raise ValueError(
                f"unknown autoscaler knobs: {sorted(overrides)}")
        if not 1 <= self.min_replicas <= self.max_replicas:
            raise ValueError(
                f"need 1 <= min_replicas <= max_replicas, got "
                f"{self.min_replicas}..{self.max_replicas}")
        self._lock = threading.Lock()
        self._up_streak = 0
        self._down_streak = 0
        self._last_action_at: Optional[float] = None
        self._draining: dict[str, float] = {}   # replica id -> drain t0
        self._last_dispatch_counts: Optional[dict] = None
        # durable journal + conservative restart (docs/fault_tolerance.md
        # "Control-plane crash recovery"): a prior incarnation's journal
        # arms BOTH cooldowns on the first tick, so a reboot can never
        # cause a scale flap. Streaks restart at zero above; the
        # below_min floor repair stays forced, so it is never delayed.
        self._journal = journal if journal is not None \
            else open_journal("autoscaler")
        self._boot_cooldown_pending = False
        if self._journal is not None:
            prior = [r for r in self._journal.replay()
                     if r.get("kind") == "autoscaler"]
            if prior:
                self._boot_cooldown_pending = True
                last_mode = next(
                    (bool(r["dry_run"]) for r in reversed(prior)
                     if "dry_run" in r), None)
                if last_mode is not None and last_mode != self.dry_run:
                    logger.warning(
                        "autoscaler dry-run mode changed across restart",
                        was_dry_run=last_mode, now_dry_run=self.dry_run)
            # one boot record per incarnation is all recovery needs —
            # compact the applied-action history away at boot
            self._journal.compact([{"kind": "autoscaler", "op": "boot",
                                    "dry_run": self.dry_run}])

    # -- signal plane --------------------------------------------------------
    def _workers(self):
        return [r for r in self.fleet.replicas
                if r.role in _WORKER_ROLES and not r.draining]

    def _worker_role(self) -> str:
        return "decode" if any(r.role == "prefill"
                               for r in self.fleet.replicas) else "unified"

    def signals(self, now: float, advance: bool = False) -> dict:
        """The aggregated decision inputs at ``now`` (also the chaos
        context, so an injected action can assert what the loop saw).
        The dispatch-failure rate is a delta since the last baseline;
        only :meth:`tick` passes ``advance=True`` to move it — an
        out-of-band status read must not zero the next tick's window."""
        stats = self.fleet.stats
        # fleet.stats already walked every replica's load() (and eats a
        # dying replica's errors) — read it, don't walk the engines a
        # second time per tick
        worker_stats = [per for per in
                        (stats.get("per_replica") or {}).values()
                        if per.get("role") in _WORKER_ROLES
                        and not per.get("draining")]
        count = len(worker_stats)
        loads = [per.get("load") or 0 for per in worker_stats]
        if self.aggregator is not None:
            # merged multi-source view, minus the local replicas that
            # are NOT scale targets (prefill pool, draining victims) —
            # their gauges must not inflate the per-worker load or pin
            # an exhausted page pool into the min (the fallback branch
            # below filters by role/draining the same way; remote
            # replicas' series pass through untouched)
            excluded = {r.id for r in self.fleet.replicas
                        if r.draining or r.role not in _WORKER_ROLES}
            queue_total = 0.0
            contributing = set()
            for labels, value in self.aggregator.family(
                    "mlt_llm_queue_depth", now).items():
                rid = dict(labels).get("replica")
                if rid in excluded:
                    continue
                queue_total += value
                contributing.add(rid)
            fracs = [value for labels, value in self.aggregator.family(
                "mlt_llm_free_page_frac", now).items()
                if dict(labels).get("replica") not in excluded]
            free_frac = min(fracs) if fracs else None
            load_total = max(float(sum(loads)), queue_total)
            # the federated queue total may include REMOTE replicas'
            # series — per-replica load divides by every replica that
            # contributed, not just the local workers, or remote load
            # reads as local overload
            serving = max(count, len(contributing))
        else:
            load_total = float(sum(loads))
            serving = count
            fracs = [per["free_page_frac"]
                     for per in stats.get("per_replica", {}).values()
                     if per.get("free_page_frac") is not None
                     and per.get("role") in _WORKER_ROLES
                     and not per.get("draining")]
            free_frac = min(fracs) if fracs else None
        ttft_p95 = None
        if self.store is not None:
            ttft_p95 = self.store.quantile(
                "mlt_llm_ttft_seconds", 0.95, self.ttft_window, now)
        if ttft_p95 is None:
            ttft_p95 = stats.get("ttft_p95_s")
        counts = {key: stats.get(key, 0)
                  for key in ("dispatches", "redispatches", "failed",
                              "no_replica")}
        last = self._last_dispatch_counts or counts
        if advance:
            self._last_dispatch_counts = counts
        bad = max(0, (counts["failed"] - last["failed"])
                  + (counts["no_replica"] - last["no_replica"]))
        total = max(0, sum(counts.values()) - sum(last.values()))
        out = {
            "replicas": count,
            "draining": len(self._draining),
            "load_total": load_total,
            "load_per_replica": load_total / serving if serving else 0.0,
            "free_page_frac_min": free_frac,
            "ttft_p95_s": ttft_p95,
            "dispatch_failure_rate": bad / total if total else 0.0,
        }
        if self.pods is not None:
            # capacity already on its way into the ring — a pod takes
            # ticks to warm and join, and the loop must not stack
            # scale-ups while one is in flight
            out["pods_pending"] = self.pods.pending_count()
        return out

    # -- decision loop -------------------------------------------------------
    def _evaluate(self, sig: dict) -> tuple[str, str]:
        """Raw (action, reason) from thresholds — before hysteresis,
        cooldown, and bounds."""
        # capacity repair: a preempted pod dropped the fleet below its
        # floor — replace it regardless of load (tick() treats this as
        # forced: hysteresis and cooldown are for demand decisions, not
        # for repairing paid-for minimum capacity)
        if sig["replicas"] + sig.get("pods_pending", 0) \
                < self.min_replicas:
            return "up", "below_min"
        reasons = []
        if sig["load_per_replica"] > self.queue_high:
            reasons.append("queue_depth")
        frac = sig["free_page_frac_min"]
        if frac is not None and frac < self.free_page_frac_low:
            reasons.append("kv_pressure")
        ttft = sig["ttft_p95_s"]
        if self.ttft_p95_high_s > 0 and ttft is not None \
                and ttft > self.ttft_p95_high_s:
            reasons.append("ttft_slo")
        if sig["dispatch_failure_rate"] > self.failure_rate_high:
            reasons.append("dispatch_failures")
        if reasons:
            return "up", "+".join(reasons)
        # scale-down keys on live load only: the p95 signal is
        # backward-looking (windowed or ring history), and an empty
        # queue means nothing is currently suffering — hysteresis plus
        # the down-cooldown damp any flap
        if sig["load_per_replica"] < self.queue_low \
                and not sig["draining"]:
            return "down", "idle"
        return "hold", ""

    def _cooled(self, action: str, now: float) -> bool:
        if self._last_action_at is None:
            return True
        cooldown = (self.cooldown_up_s if action == "up"
                    else self.cooldown_down_s)
        return now - self._last_action_at >= cooldown

    def tick(self, now: float) -> dict:
        """One evaluation: gather signals, decide, (maybe) act, and
        advance draining replicas toward removal. Deterministic — no
        internal clock reads, no sleeps."""
        with self._lock:
            if self._boot_cooldown_pending:
                # conservative-restart contract: cooldowns are assumed
                # ACTIVE at boot and anchor to the first post-restart
                # tick (the clock arrives here, not in __init__)
                self._boot_cooldown_pending = False
                self._last_action_at = now
                RECONCILE_ACTIONS.inc(controller="autoscaler",
                                      action="cooldown_armed")
                flight_record("reconcile.autoscaler",
                              action="cooldown_armed", at=now)
            if self.pods is not None:
                # advance the pod lifecycle FIRST so the signals below
                # see fresh ring membership (a preempted pod is already
                # out, a warmed pod already joined)
                self.pods.tick(now)
                # level-triggered drain adoption: the draining set is
                # re-derived from the pod fleet every tick, so a
                # restarted autoscaler resumes interrupted drains
                # through its normal sweep instead of replaying them
                for rid in self.pods.draining_rids():
                    if rid not in self._draining:
                        self._draining[rid] = now
                        RECONCILE_ACTIONS.inc(controller="autoscaler",
                                              action="adopt_drain")
            if self.scorer is not None:
                # score BEFORE signals: a probated replica's ring weight
                # drops here, so this tick's routing already shifts
                self.scorer.tick(now)
            sig = self.signals(now, advance=True)
            action, reason = self._evaluate(sig)
            box = {"action": action, "reason": reason, "force": False}
            fire(FaultPoints.obs_autoscale, box=box, signals=sig, now=now)
            action, reason = box["action"], box["reason"]
            forced = bool(box["force"]) or reason == "below_min"

            if action == "up":
                self._up_streak += 1
                self._down_streak = 0
            elif action == "down":
                self._down_streak += 1
                self._up_streak = 0
            else:
                self._up_streak = self._down_streak = 0

            current = sig["replicas"]
            pending = sig.get("pods_pending", 0)
            streak = (self._up_streak if action == "up"
                      else self._down_streak)
            recommended = action != "hold" and (
                forced or streak >= self.hysteresis_ticks)
            bounded = recommended and (
                (action == "up"
                 and current + pending < self.max_replicas)
                or (action == "down" and current > self.min_replicas))
            desired = current
            if bounded:
                desired = current + (1 if action == "up" else -1)
            if recommended:
                AUTOSCALER_RECOMMENDATIONS.inc(
                    action=action if bounded
                    else f"{action}_at_bound", reason=reason)
            AUTOSCALER_DESIRED.set(desired)

            acted = None
            if bounded and not self.dry_run and (
                    forced or self._cooled(action, now)):
                acted = self._act(action, now)
            removed = self._sweep_draining(now)
            if acted is None:
                replaced = self._replace_degraded(now)
                if replaced is not None:
                    acted = replaced
        return {"action": action, "reason": reason, "recommended":
                recommended, "desired": desired, "current": current,
                "acted": acted, "removed": removed, "forced": forced,
                "signals": sig, "dry_run": self.dry_run}

    def _replace_degraded(self, now: float) -> Optional[dict]:
        """Drain one persistently-probated replica (fail-slow
        replacement, obs/health.py). Deliberately a *repair*, not a
        demand decision: it runs regardless of cooldown, one replica at
        a time, and never while another drain is in flight. Removal
        drops the fleet to (or below) its floor momentarily — the
        forced ``below_min`` path resubmits the replacement capacity on
        the next tick, which pre-warm makes cheap."""
        if self.scorer is None or self.dry_run or self._draining:
            return None
        rid = self.scorer.pop_replace_due()
        if rid is None:
            return None
        if not any(r.id == rid for r in self.fleet.replicas):
            return None  # probated replica already left the fleet
        # the decision is recorded BEFORE the drain so the flight chain
        # reads causally: health.probation -> health.replace -> pod.drain
        flight_record("health.replace", replica=rid, at=now)
        if self.pods is not None and self.pods.owns(rid):
            self.pods.drain(rid, now)
        else:
            self.fleet.drain_replica(rid)
        self._draining[rid] = now
        AUTOSCALER_ACTIONS.inc(action="drain")
        self._journal_append(op="act", action="replace_degraded",
                             replica=rid, at=now)
        logger.warning("autoscaler replacing degraded replica",
                       replica=rid)
        return {"action": "replace_degraded", "replica": rid}

    def _act(self, action: str, now: float) -> Optional[dict]:
        if action == "up":
            if self.pods is not None:
                # cross-process: submit a serving JobSet; the pod joins
                # the ring ticks later, after pre-warm + readiness
                pod = self.pods.scale_up(self._worker_role(), now)
                AUTOSCALER_ACTIONS.inc(action="add")
                self._last_action_at = now
                self._up_streak = 0
                self._journal_append(op="act", action="add", pod=pod,
                                     at=now)
                logger.info("autoscaler submitted serving pod", pod=pod)
                return {"action": "add", "pod": pod}
            rid = self.fleet.add_replica(self._worker_role())
            AUTOSCALER_ACTIONS.inc(action="add")
            self._last_action_at = now
            self._up_streak = 0
            self._journal_append(op="act", action="add", replica=rid,
                                 at=now)
            logger.info("autoscaler added replica", replica=rid)
            return {"action": "add", "replica": rid}
        victim = self._scale_down_victim()
        if victim is None:
            return None
        if self.pods is not None and self.pods.owns(victim.id):
            # drain-before-delete through the pod's /__drain__ path;
            # the sweep deletes the JobSet once in-flight work drains
            self.pods.drain(victim.id, now)
        else:
            self.fleet.drain_replica(victim.id)
        self._draining[victim.id] = now
        AUTOSCALER_ACTIONS.inc(action="drain")
        self._last_action_at = now
        self._down_streak = 0
        self._journal_append(op="act", action="drain",
                             replica=victim.id, at=now)
        logger.info("autoscaler draining replica", replica=victim.id)
        return {"action": "drain", "replica": victim.id}

    def _journal_append(self, **fields):
        if self._journal is None:
            return
        ok = self._journal.append("autoscaler", **fields)
        JOURNAL_WRITES.inc(journal="autoscaler",
                           outcome="ok" if ok else "failed")

    def _scale_down_victim(self):
        """Least-loaded non-draining worker — the cheapest replica to
        take out of rotation (its keyspace moves to ring neighbors; its
        few in-flight requests finish during the drain). A probated
        (fail-slow) replica is preferred over ANY load ordering: if the
        fleet is shedding capacity anyway, shed the sick capacity."""
        workers = self._workers()
        if len(workers) <= self.min_replicas:
            return None

        def load_of(replica):
            try:
                return replica.load()
            except Exception:  # noqa: BLE001
                return 0

        def probated(replica):
            return getattr(replica, "health_state",
                           "healthy") == "probation"

        return min(workers, key=lambda r: (0 if probated(r) else 1,
                                           load_of(r), r.id))

    def _sweep_draining(self, now: float) -> list[str]:
        """Remove drained replicas whose in-flight work hit zero (or
        whose grace expired). The engine stop retires its own
        ``replica``-labeled series — asserted in tests; see
        serving/fleet.py remove_replica."""
        removed = []
        for rid, since in list(self._draining.items()):
            replica = next((r for r in self.fleet.replicas
                            if r.id == rid), None)
            if replica is None:
                self._draining.pop(rid)
                continue
            try:
                busy = replica.load() > 0
            except Exception:  # noqa: BLE001
                busy = False
            if busy and now - since < self.drain_grace_s:
                continue
            self.fleet.remove_replica(rid)
            if self.pods is not None:
                # delete the drained pod's JobSet + retire its series
                self.pods.on_replica_removed(rid)
            if self.store is not None:
                # the engine retires its registry series on stop; the
                # windowed store keeps its own rings, so retire the
                # removed replica's series here too — a churning fleet
                # must not fill the store's series budget with dead ids
                self.store.drop_series(labels={"replica": rid})
            AUTOSCALER_ACTIONS.inc(action="remove")
            self._draining.pop(rid)
            removed.append(rid)
            logger.info("autoscaler removed drained replica", replica=rid)
        return removed
