"""Server-side image/requirements builder.

Reference analog: `server/api/utils/builder.py:39` (make_dockerfile) and
`:144` (make_kaniko_pod) — the reference bakes a new image per function
with Kaniko. Here the same two artifacts exist for kubernetes clusters,
plus a registry-less LOCAL build path: the service pre-warms the
requirements overlay cache (`utils/bootstrap.py`) as a background task whose
pip output is the retrievable build log, and runs of the function
bootstrap onto that overlay at pod start.
"""

from __future__ import annotations

import io
import threading
import time

from ..config import mlconf
from ..utils import get_in, logger, update_in
from ..utils.bootstrap import ensure_overlay, requirements_hash

BUILD_UID_PREFIX = "build-"


def _strip_image_tag(image: str) -> str:
    """Drop the tag (and any ``@sha256:...`` digest) from an image ref —
    but only a real tag: a ':' in ``registry:5000/repo`` belongs to the
    registry port, not a tag. Digest-pinned refs like ``repo@sha256:abc``
    or ``repo:tag@sha256:abc`` reduce to plain ``repo``."""
    head, _, last = image.rpartition("/")
    last = last.split("@", 1)[0]
    if ":" in last:
        last = last.rsplit(":", 1)[0]
    return f"{head}/{last}" if head else last


def make_dockerfile(base_image: str, requirements: list[str] | None = None,
                    commands: list[str] | None = None,
                    source: str = "", workdir: str = "/app") -> str:
    """Dockerfile text for a function image (reference builder.py:39 —
    re-designed: TPU images layer python deps over the prebuilt jax base,
    no conda/horovod stages)."""
    lines = [f"FROM {base_image}"]
    if source:
        lines += [f"WORKDIR {workdir}", f"ADD {source} {workdir}"]
    for command in commands or []:
        lines.append(f"RUN {command}")
    if requirements:
        lines.append("COPY requirements.txt /tmp/mlt-requirements.txt")
        lines.append(
            "RUN python -m pip install --no-cache-dir "
            "-r /tmp/mlt-requirements.txt")
    return "\n".join(lines) + "\n"


def make_kaniko_pod(project: str, name: str, dockerfile: str,
                    dest_image: str, context_path: str = "",
                    registry_secret: str = "") -> dict:
    """Kaniko builder pod manifest (reference builder.py:144). The
    dockerfile rides a config-map-free inline init container write so the
    manifest is self-contained."""
    build_name = f"mlt-build-{project}-{name}-{int(time.time())}"[:63]
    kaniko_args = [
        "--dockerfile=/workspace/Dockerfile",
        f"--destination={dest_image}",
        "--context=dir:///workspace",
    ]
    volumes = [{"name": "workspace", "emptyDir": {}}]
    volume_mounts = [{"name": "workspace", "mountPath": "/workspace"}]
    if registry_secret:
        volumes.append({"name": "registry-creds", "secret": {
            "secretName": registry_secret}})
        volume_mounts.append({"name": "registry-creds",
                              "mountPath": "/kaniko/.docker"})
    # the dockerfile is written by an init container from an env var, so
    # no ConfigMap round-trip is needed
    init = {
        "name": "write-dockerfile",
        "image": "busybox",
        "command": ["sh", "-c",
                    "printf '%s' \"$DOCKERFILE\" > /workspace/Dockerfile; "
                    "printf '%s' \"$REQUIREMENTS\" > "
                    "/workspace/requirements.txt"],
        "env": [{"name": "DOCKERFILE", "value": dockerfile},
                {"name": "REQUIREMENTS", "value": ""}],
        "volumeMounts": [{"name": "workspace", "mountPath": "/workspace"}],
    }
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {
            "name": build_name,
            "namespace": mlconf.namespace,
            "labels": {"mlrun-tpu/class": "build",
                       "mlrun-tpu/project": project,
                       "mlrun-tpu/function": name},
        },
        "spec": {
            "initContainers": [init],
            "containers": [{
                "name": "kaniko",
                "image": "gcr.io/kaniko-project/executor:latest",
                "args": kaniko_args,
                "volumeMounts": volume_mounts,
            }],
            "volumes": volumes,
            "restartPolicy": "Never",
        },
    }


class _DbLogWriter(io.TextIOBase):
    """File-like adapter that appends lines to the run-log store so the
    build log is retrievable over `/build/status`."""

    def __init__(self, db, uid: str, project: str):
        self._db = db
        self._uid = uid
        self._project = project

    def write(self, text: str):  # type: ignore[override]
        if text:
            self._db.store_log(self._uid, self._project, text.encode())
        return len(text)

    def flush(self):
        pass


class FunctionBuilder:
    """Runs function builds and tracks them as background tasks."""

    def __init__(self, db, provider):
        self.db = db
        self.provider = provider

    def build(self, function: dict, with_tpu: bool = False) -> dict:
        """Resolve the image and, when the build spec asks for more than a
        prebuilt image (requirements/commands), run the build. Returns the
        function status dict; long builds continue in a background task."""
        name = get_in(function, "metadata.name", "fn")
        project = get_in(function, "metadata.project",
                         mlconf.default_project)
        tag = get_in(function, "metadata.tag", "latest") or "latest"
        requirements = list(get_in(function, "spec.build.requirements",
                                   []) or [])
        commands = list(get_in(function, "spec.build.commands", []) or [])
        base_image = get_in(function, "spec.build.base_image", "") or (
            mlconf.function.tpu_image if with_tpu
            else mlconf.function.default_image)
        image = get_in(function, "spec.image", "") or \
            get_in(function, "spec.build.image", "") or base_image

        update_in(function, "spec.image", image)
        if not requirements and not commands:
            # prebuilt image + code-in-env: nothing to bake
            update_in(function, "status.state", "ready")
            self.db.store_function(function, name, project, tag=tag)
            return {"state": "ready", "image": image,
                    "background_task": ""}

        task_name = f"{BUILD_UID_PREFIX}{name}-{int(time.time())}"
        log_uid = f"{BUILD_UID_PREFIX}{name}"
        update_in(function, "status.state", "deploying")
        update_in(function, "status.build_log_uid", log_uid)
        self.db.store_function(function, name, project, tag=tag)
        self.db.store_background_task(task_name, "running", project)

        if self.provider.kind == "kubernetes":
            target = self._build_kaniko
            # a kaniko build produces a NEW image the runs must use
            dest = get_in(function, "spec.build.image", "") or \
                f"{_strip_image_tag(image)}-{name}:{tag}"
            update_in(function, "spec.image", dest)
            args = (function, name, project, tag, task_name, log_uid,
                    base_image, requirements, commands, dest)
        else:
            target = self._build_overlay
            args = (function, name, project, tag, task_name, log_uid,
                    requirements, commands)
        thread = threading.Thread(target=target, args=args, daemon=True)
        thread.start()
        return {"state": "deploying", "image":
                get_in(function, "spec.image", image),
                "background_task": task_name}

    # -- local: pre-warm the bootstrap overlay cache -----------------------
    def _build_overlay(self, function: dict, name: str, project: str,
                    tag: str, task_name: str, log_uid: str,
                    requirements: list, commands: list):
        log = _DbLogWriter(self.db, log_uid, project)
        error = ""
        try:
            if commands:
                # the overlay path cannot honor docker RUN commands — a
                # build that silently drops them would "succeed" while
                # producing an image missing what the user asked for, so
                # it FAILS loudly instead (use the kubernetes provider's
                # kaniko path for command-bearing builds)
                raise RuntimeError(
                    "build commands require an image build; the local "
                    "provider's overlay path installs requirements only. "
                    f"unsupported commands: {commands}")
            ensure_overlay(requirements, log_fp=log)
            state = "ready"
            log.write("build completed\n")
        except Exception as exc:  # noqa: BLE001
            state = "error"
            error = str(exc)
            log.write(f"build failed: {exc}\n")
            logger.warning("function build failed", function=name,
                           error=str(exc))
        self._finish(function, name, project, tag, task_name, state,
                     error=error)

    # -- kubernetes: kaniko pod --------------------------------------------
    def _build_kaniko(self, function: dict, name: str, project: str,
                      tag: str, task_name: str, log_uid: str,
                      base_image: str, requirements: list, commands: list,
                      dest_image: str):
        log = _DbLogWriter(self.db, log_uid, project)
        error = ""
        try:
            dockerfile = make_dockerfile(base_image, requirements, commands)
            pod = make_kaniko_pod(project, name, dockerfile, dest_image)
            pod["spec"]["initContainers"][0]["env"][1]["value"] = \
                "\n".join(requirements)
            resource_id = self.provider.create(pod, f"build-{name}")
            log.write(f"kaniko pod created: {resource_id}\n")
            deadline = time.time() + 1800
            state = "error"
            error = "kaniko build timed out"
            while time.time() < deadline:
                phase = self.provider.state(resource_id)
                if phase == "Succeeded":
                    state, error = "ready", ""
                    break
                if phase == "Failed":
                    error = "kaniko pod failed"
                    break
                time.sleep(2.0)
            log.write(f"kaniko pod finished: {state}\n")
            try:
                self.provider.delete(resource_id)
            except Exception:  # noqa: BLE001
                pass
        except Exception as exc:  # noqa: BLE001
            state = "error"
            error = str(exc)
            log.write(f"build failed: {exc}\n")
        self._finish(function, name, project, tag, task_name, state,
                     error=error)

    def _finish(self, function: dict, name: str, project: str, tag: str,
                task_name: str, state: str, error: str = ""):
        update_in(function, "status.state", state)
        if error:
            update_in(function, "status.error", error)
        self.db.store_function(function, name, project, tag=tag)
        self.db.store_background_task(
            task_name, "succeeded" if state == "ready" else "failed",
            project)

    # -- status ------------------------------------------------------------
    def status(self, name: str, project: str, tag: str = "latest",
               offset: int = 0) -> dict:
        function = self.db.get_function(name, project, tag=tag or "latest")
        if not function:
            return {"state": "not_found", "log": "", "offset": offset}
        state = get_in(function, "status.state", "unknown")
        log_uid = get_in(function, "status.build_log_uid", "")
        text, nbytes = "", 0
        if log_uid:
            try:
                _, data = self.db.get_log(log_uid, project, offset=offset)
                nbytes = len(data)  # offsets are BYTE positions — advance
                # by the raw length, not the decoded char count, or
                # multi-byte pip output re-reads and tears codepoints
                text = data.decode(errors="replace")
            except Exception:  # noqa: BLE001
                text, nbytes = "", 0
        return {"state": state, "log": text, "offset": offset + nbytes,
                "image": get_in(function, "spec.image", "")}
