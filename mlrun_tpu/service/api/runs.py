"""Runs + logs + the submit path (reference: endpoints/submit.py:40 →
api/utils.py:207 submit_run; crud/runs.py; crud/logs.py)."""

from __future__ import annotations

import asyncio
from datetime import datetime, timezone

from aiohttp import web

from ...config import mlconf
from ...model import RunObject
from ...utils import generate_uid, get_in, logger, now_iso
from ..cron import CronSchedule
from ..http_utils import (
    API,
    error_response,
    json_response,
    paginate,
    token_paginated_response,
)
from ..launcher import rebuild_function


def register(r: web.RouteTableDef, state):
    @r.post(API + "/projects/{project}/runs/{uid}")
    async def store_run(request):
        body = await request.json()
        state.db.store_run(body, request.match_info["uid"],
                           request.match_info["project"],
                           iter=int(request.query.get("iter", 0)))
        return json_response({"ok": True})

    @r.patch(API + "/projects/{project}/runs/{uid}")
    async def update_run(request):
        body = await request.json()
        state.db.update_run(body, request.match_info["uid"],
                            request.match_info["project"],
                            iter=int(request.query.get("iter", 0)))
        return json_response({"ok": True})

    @r.get(API + "/projects/{project}/runs/{uid}")
    async def read_run(request):
        run = state.db.read_run(request.match_info["uid"],
                                request.match_info["project"],
                                iter=int(request.query.get("iter", 0)))
        if run is None:
            return error_response("run not found", 404)
        return json_response({"data": run})

    @r.get(API + "/projects/{project}/runs")
    async def list_runs(request):
        q = request.query
        filters = dict(
            name=q.get("name", ""), project=request.match_info["project"],
            state=q.get("state", ""), labels=q.getall("label", None),
            last=int(q.get("last", 0)), iter=bool(int(q.get("iter", 0))),
            uid=q.getall("uid", None))
        if "page_size" in q or "page_token" in q:
            return token_paginated_response(state, request, "list_runs",
                                            "runs", filters)
        runs = state.db.list_runs(**filters)
        return json_response({"runs": paginate(runs, request)})

    @r.delete(API + "/projects/{project}/runs/{uid}")
    async def del_run(request):
        state.db.del_run(request.match_info["uid"],
                         request.match_info["project"],
                         iter=int(request.query.get("iter", 0)))
        return json_response({"ok": True})

    @r.post(API + "/projects/{project}/runs/{uid}/abort")
    async def abort_run(request):
        uid = request.match_info["uid"]
        project = request.match_info["project"]
        run = state.db.read_run(uid, project)
        if run is None:
            return error_response("run not found", 404)
        kind = get_in(run, "metadata.labels.kind", "job")
        try:
            handler = state.launcher.handler_for(kind)
            handler.abort_run(uid, project)
        except ValueError:
            state.db.abort_run(uid, project)
        state.db.emit_event("run_aborted", {"uid": uid}, project)
        return json_response({"ok": True})

    # -- logs ---------------------------------------------------------------
    @r.post(API + "/projects/{project}/logs/{uid}")
    async def store_log(request):
        body = await request.read()
        state.db.store_log(request.match_info["uid"],
                           request.match_info["project"], body,
                           append=bool(int(request.query.get("append", 1))))
        return json_response({"ok": True})

    @r.get(API + "/projects/{project}/logs/{uid}")
    async def get_log(request):
        log_state, data = state.db.get_log(
            request.match_info["uid"], request.match_info["project"],
            offset=int(request.query.get("offset", 0)),
            size=int(request.query.get("size", -1)))
        return web.Response(body=data, headers={
            "x-mlt-run-state": log_state or "unknown"})

    @r.get(API + "/projects/{project}/logs/{uid}/size")
    async def get_log_size(request):
        size = state.db.get_log_size(request.match_info["uid"],
                                     request.match_info["project"])
        return json_response({"size": size})

    # -- submit -------------------------------------------------------------
    @r.post(API + "/submit_job")
    async def submit_job(request):
        """The core submission path (reference endpoints/submit.py:40 →
        api/utils.py:207 submit_run)."""
        body = await request.json()
        function_dict = body.get("function")
        task = body.get("task") or {"metadata": body.get("metadata", {}),
                                    "spec": body.get("spec", {})}
        schedule = body.get("schedule")
        if not function_dict:
            # resolve from the db via task.spec.function uri
            uri = get_in(task, "spec.function", "")
            if not uri:
                return error_response("missing function")
            project_part, _, rest = uri.partition("/")
            name, _, tag = rest.partition(":")
            tag, _, hash_key = tag.partition("@")
            function_dict = state.db.get_function(
                name, project_part, tag=tag or "latest")

        retry_spec = get_in(task, "spec.retry_policy")
        if retry_spec:
            # reject typo'd policies at the door — a misspelled key or
            # failure class would otherwise silently disable retries
            from ...common.schemas.run import RetryPolicy

            try:
                RetryPolicy(**retry_spec)
            except Exception as exc:  # noqa: BLE001 - pydantic details vary
                return error_response(f"bad retry_policy: {exc}")
        run = RunObject.from_dict(
            {"metadata": task.get("metadata", {}),
             "spec": task.get("spec", {})})
        run.metadata.uid = run.metadata.uid or generate_uid()
        run.metadata.project = (run.metadata.project
                                or mlconf.default_project)
        runtime = rebuild_function(function_dict)
        run.metadata.labels.setdefault("kind", runtime.kind)
        # persist the (possibly inline) function: retries after a service
        # restart rebuild the resource from the stored function via
        # spec.function (runtime_handlers._rebuild_from_function), and the
        # reference stores every submitted function the same way
        try:
            state.db.store_function(
                function_dict, runtime.metadata.name,
                runtime.metadata.project or run.metadata.project,
                tag=runtime.metadata.tag or "latest")
        except Exception as exc:  # noqa: BLE001 - submission still valid
            logger.warning("could not persist submitted function",
                           error=str(exc))
        # notification secret-params never reach the stored run or the
        # resource env (reference api/utils.py:221 mask_notification_params)
        from ..secrets import mask_notification_params

        mask_notification_params(state.db, run)

        if schedule:
            record = {
                "name": run.metadata.name, "project": run.metadata.project,
                "kind": "job", "cron_trigger": schedule,
                "scheduled_object": {"function": function_dict,
                                     "task": run.to_dict()},
                "creation_time": now_iso(),
            }
            try:
                cron = CronSchedule(schedule)
            except ValueError as exc:
                return error_response(f"bad schedule: {exc}")
            if cron.min_interval_seconds() < \
                    mlconf.scheduler.min_allowed_interval_seconds:
                return error_response("schedule interval below minimum")
            record["next_run_time"] = str(
                cron.next_after(datetime.now(timezone.utc)))
            state.db.store_schedule(run.metadata.project, run.metadata.name,
                                    record)
            return json_response({"data": {"schedule": schedule,
                                           "metadata":
                                           run.to_dict()["metadata"]}})

        loop = asyncio.get_event_loop()
        try:
            await loop.run_in_executor(
                None, lambda: state.launcher.launch(runtime, run))
        except Exception as exc:  # noqa: BLE001
            return error_response(f"launch failed: {exc}", 500)
        return json_response({"data": run.to_dict()})
