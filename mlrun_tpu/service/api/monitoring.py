"""Model endpoints, metric time-series, and the grafana proxy
(reference: crud/model_monitoring/; endpoints/grafana_proxy.py —
simpleJSON datasource contract)."""

from __future__ import annotations

from aiohttp import web

from ...config import mlconf
from ..http_utils import API, error_response, json_response


def register(r: web.RouteTableDef, state):
    @r.post(API + "/projects/{project}/model-endpoints/{uid}")
    async def store_endpoint(request):
        body = await request.json()
        state.db.store_model_endpoint(request.match_info["project"],
                                      request.match_info["uid"], body)
        return json_response({"ok": True})

    @r.get(API + "/projects/{project}/model-endpoints/{uid}")
    async def get_endpoint(request):
        from ...db.base import RunDBError

        try:
            endpoint = state.db.get_model_endpoint(
                request.match_info["project"], request.match_info["uid"])
        except RunDBError as exc:
            return error_response(str(exc), 404)
        return json_response({"data": endpoint})

    @r.get(API + "/projects/{project}/model-endpoints")
    async def list_endpoints(request):
        endpoints = state.db.list_model_endpoints(
            request.match_info["project"],
            model=request.query.get("model", ""),
            function=request.query.get("function", ""),
            state=request.query.get("state", ""))
        return json_response({"endpoints": endpoints})

    @r.delete(API + "/projects/{project}/model-endpoints/{uid}")
    async def delete_endpoint(request):
        state.db.delete_model_endpoint(request.match_info["project"],
                                       request.match_info["uid"])
        return json_response({"ok": True})

    @r.get(API + "/projects/{project}/model-endpoints/{uid}/metrics")
    async def endpoint_metrics(request):
        """Metric time-series with time-range + downsampling (reference:
        model-endpoint metric values API over the TSDB layer)."""
        from ...model_monitoring.tsdb import get_metrics_tsdb

        q = request.query
        try:
            start = float(q.get("start", 0) or 0)
            end = float(q["end"]) if q.get("end") else None
            max_points = int(q.get("max_points", 1000))
        except ValueError:
            return error_response("bad time range", 400)
        tsdb = get_metrics_tsdb()
        project = request.match_info["project"]
        uid = request.match_info["uid"]
        if q.get("names_only") in ("true", "1"):
            return json_response(
                {"metrics": tsdb.list_metrics(project, uid)})
        return json_response({"series": tsdb.query(
            project, uid, metric=q.get("name", ""), start=start, end=end,
            max_points=max_points)})

    # -- grafana proxy ------------------------------------------------------
    @r.get(API + "/grafana-proxy/model-endpoints")
    async def grafana_health(request):
        return json_response({"status": "ok"})

    @r.post(API + "/grafana-proxy/model-endpoints/search")
    async def grafana_search(request):
        body = await request.json() if request.can_read_body else {}
        project = (body.get("target") or "").split(":")[0] \
            or mlconf.default_project
        endpoints = state.db.list_model_endpoints(project)
        return json_response([e.get("uid") for e in endpoints])

    @r.post(API + "/grafana-proxy/model-endpoints/query")
    async def grafana_query(request):
        body = await request.json()
        rows = []
        columns = [{"text": "endpoint_id", "type": "string"},
                   {"text": "model", "type": "string"},
                   {"text": "requests", "type": "number"},
                   {"text": "avg_latency_microsec", "type": "number"},
                   {"text": "drift_status", "type": "string"}]
        for target in body.get("targets", [{}]):
            spec = (target.get("target") or "")
            project = spec.split(":")[0] or mlconf.default_project
            for endpoint in state.db.list_model_endpoints(project):
                metrics = endpoint.get("metrics", {})
                rows.append([
                    endpoint.get("uid"), endpoint.get("name"),
                    metrics.get("requests", 0),
                    metrics.get("avg_latency_microsec", 0),
                    endpoint.get("drift_status", "")])
        return json_response([{"type": "table", "columns": columns,
                               "rows": rows}])
