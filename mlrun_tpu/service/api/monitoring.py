"""Model endpoints, metric time-series, and the grafana proxy
(reference: crud/model_monitoring/; endpoints/grafana_proxy.py —
simpleJSON datasource contract).

Two grafana datasources live here: ``grafana-proxy/model-endpoints``
(table-shaped, over the model-monitoring DB) and
``grafana-proxy/metrics`` (timeserie-shaped, over the federated
``obs.TimeSeriesStore`` — the fleet-wide series the SLO evaluator and
autoscaler read; docs/observability.md "Federation")."""

from __future__ import annotations

from datetime import datetime, timezone

from aiohttp import web

from ...config import mlconf
from ..http_utils import API, error_response, json_response


def _parse_range_ts(value) -> float:
    """Grafana sends ISO-8601 range bounds; accept epoch numbers too
    (epoch milliseconds are detected and converted — a millis bound
    read as seconds would put the range ~50k years out)."""
    if isinstance(value, (int, float)):
        value = float(value)
        return value / 1000.0 if value > 1e11 else value
    parsed = datetime.fromisoformat(str(value).replace("Z", "+00:00"))
    if parsed.tzinfo is None:
        parsed = parsed.replace(tzinfo=timezone.utc)
    return parsed.timestamp()


def register(r: web.RouteTableDef, state):
    @r.post(API + "/projects/{project}/model-endpoints/{uid}")
    async def store_endpoint(request):
        body = await request.json()
        state.db.store_model_endpoint(request.match_info["project"],
                                      request.match_info["uid"], body)
        return json_response({"ok": True})

    @r.get(API + "/projects/{project}/model-endpoints/{uid}")
    async def get_endpoint(request):
        from ...db.base import RunDBError

        try:
            endpoint = state.db.get_model_endpoint(
                request.match_info["project"], request.match_info["uid"])
        except RunDBError as exc:
            return error_response(str(exc), 404)
        return json_response({"data": endpoint})

    @r.get(API + "/projects/{project}/model-endpoints")
    async def list_endpoints(request):
        endpoints = state.db.list_model_endpoints(
            request.match_info["project"],
            model=request.query.get("model", ""),
            function=request.query.get("function", ""),
            state=request.query.get("state", ""))
        return json_response({"endpoints": endpoints})

    @r.delete(API + "/projects/{project}/model-endpoints/{uid}")
    async def delete_endpoint(request):
        state.db.delete_model_endpoint(request.match_info["project"],
                                       request.match_info["uid"])
        return json_response({"ok": True})

    @r.get(API + "/projects/{project}/model-endpoints/{uid}/metrics")
    async def endpoint_metrics(request):
        """Metric time-series with time-range + downsampling (reference:
        model-endpoint metric values API over the TSDB layer)."""
        from ...model_monitoring.tsdb import get_metrics_tsdb

        q = request.query
        try:
            start = float(q.get("start", 0) or 0)
            end = float(q["end"]) if q.get("end") else None
            max_points = int(q.get("max_points", 1000))
        except ValueError:
            return error_response("bad time range", 400)
        tsdb = get_metrics_tsdb()
        project = request.match_info["project"]
        uid = request.match_info["uid"]
        if q.get("names_only") in ("true", "1"):
            return json_response(
                {"metrics": tsdb.list_metrics(project, uid)})
        return json_response({"series": tsdb.query(
            project, uid, metric=q.get("name", ""), start=start, end=end,
            max_points=max_points)})

    # -- grafana proxy ------------------------------------------------------
    @r.get(API + "/grafana-proxy/model-endpoints")
    async def grafana_health(request):
        return json_response({"status": "ok"})

    @r.post(API + "/grafana-proxy/model-endpoints/search")
    async def grafana_search(request):
        body = await request.json() if request.can_read_body else {}
        project = (body.get("target") or "").split(":")[0] \
            or mlconf.default_project
        endpoints = state.db.list_model_endpoints(project)
        return json_response([e.get("uid") for e in endpoints])

    @r.post(API + "/grafana-proxy/model-endpoints/query")
    async def grafana_query(request):
        body = await request.json()
        rows = []
        columns = [{"text": "endpoint_id", "type": "string"},
                   {"text": "model", "type": "string"},
                   {"text": "requests", "type": "number"},
                   {"text": "avg_latency_microsec", "type": "number"},
                   {"text": "drift_status", "type": "string"}]
        for target in body.get("targets", [{}]):
            spec = (target.get("target") or "")
            project = spec.split(":")[0] or mlconf.default_project
            for endpoint in state.db.list_model_endpoints(project):
                metrics = endpoint.get("metrics", {})
                rows.append([
                    endpoint.get("uid"), endpoint.get("name"),
                    metrics.get("requests", 0),
                    metrics.get("avg_latency_microsec", 0),
                    endpoint.get("drift_status", "")])
        return json_response([{"type": "table", "columns": columns,
                               "rows": rows}])

    # -- grafana proxy: federated metrics time series ------------------------
    @r.get(API + "/grafana-proxy/metrics")
    async def grafana_metrics_health(request):
        return json_response({"status": "ok"})

    @r.post(API + "/grafana-proxy/metrics/search")
    async def grafana_metrics_search(request):
        from ...obs.timeseries import get_store

        body = await request.json() if request.can_read_body else {}
        return json_response(
            get_store().search(str(body.get("target") or "")))

    @r.post(API + "/grafana-proxy/metrics/query")
    async def grafana_metrics_query(request):
        """simpleJSON ``timeserie`` query over the aggregated store.
        Targets: ``name{label="v"}``, ``rate(name)[60]``,
        ``p95(histogram_family)[60]`` (obs/timeseries.parse_target)."""
        from ...obs.timeseries import get_store, grafana_query

        body = await request.json()
        try:
            start = _parse_range_ts((body.get("range") or {})
                                    .get("from", 0))
            end = _parse_range_ts((body.get("range") or {}).get("to", 0))
        except ValueError:
            return error_response("bad time range", 400)
        store = get_store()

        def run_queries():
            # per-bucket rate/quantile evaluation over a wide dashboard
            # range is real CPU — keep it off the service event loop
            out = []
            for target in body.get("targets", []):
                spec = (target.get("target") or "").strip()
                if not spec:
                    continue
                try:
                    out.append(grafana_query(store, spec, start, end))
                except (ValueError, KeyError) as exc:
                    raise web.HTTPBadRequest(
                        reason=f"bad target {spec!r}: {exc}")
            return out

        import asyncio

        try:
            out = await asyncio.get_event_loop().run_in_executor(
                None, run_queries)
        except web.HTTPBadRequest as exc:
            return error_response(exc.reason, 400)
        return json_response(out)
