"""Projects, project secrets, and datastore profiles (reference:
crud/projects.py + follower leader-first flow;
endpoints/secrets.py — values are write/delete-only over REST;
server-side datastore_profile endpoints — private fields go to the
project-secret store and are never returned)."""

from __future__ import annotations

import asyncio

from aiohttp import web

from ..http_utils import API, error_response, json_response


def register(r: web.RouteTableDef, state):
    @r.post(API + "/projects/{name}")
    async def store_project(request):
        body = await request.json()
        name = request.match_info["name"]
        if state.projects_follower.enabled:
            # leader-first (reference follower.py create/store flow)
            loop = asyncio.get_event_loop()
            try:
                stored = await loop.run_in_executor(
                    None,
                    lambda: state.projects_follower.forward_store(name,
                                                                  body))
            except Exception as exc:  # noqa: BLE001
                return error_response(f"project leader rejected: {exc}",
                                      502)
            return json_response({"data": stored})
        stored = state.db.store_project(name, body)
        return json_response({"data": stored})

    @r.get(API + "/projects/{name}")
    async def get_project(request):
        project = state.db.get_project(request.match_info["name"])
        if project is None:
            return error_response("project not found", 404)
        return json_response({"data": project})

    @r.get(API + "/projects")
    async def list_projects(request):
        return json_response({"projects": state.db.list_projects(
            state=request.query.get("state"))})

    @r.delete(API + "/projects/{name}")
    async def delete_project(request):
        from ...db.base import RunDBError

        name = request.match_info["name"]
        strategy = request.query.get("deletion_strategy", "restricted")
        try:
            if state.projects_follower.enabled:
                loop = asyncio.get_event_loop()
                await loop.run_in_executor(
                    None,
                    lambda: state.projects_follower.forward_delete(
                        name, deletion_strategy=strategy))
            else:
                state.db.delete_project(name, deletion_strategy=strategy)
        except RunDBError as exc:
            return error_response(str(exc), 412)
        return json_response({"ok": True})

    # -- project secrets ----------------------------------------------------
    @r.post(API + "/projects/{project}/secrets")
    async def store_project_secrets(request):
        body = await request.json()
        provider = body.get("provider", "kubernetes")
        secrets = body.get("secrets") or {}
        if not isinstance(secrets, dict):
            return error_response("secrets must be a mapping")
        state.db.store_project_secrets(
            request.match_info["project"], secrets, provider=provider)
        return json_response({"ok": True})

    @r.get(API + "/projects/{project}/secret-keys")
    async def list_project_secret_keys(request):
        provider = request.query.get("provider", "kubernetes")
        keys = state.db.list_project_secret_keys(
            request.match_info["project"], provider=provider)
        return json_response({"secret_keys": keys})

    @r.delete(API + "/projects/{project}/secrets")
    async def delete_project_secrets(request):
        provider = request.query.get("provider", "kubernetes")
        keys = request.query.getall("secret", []) or None
        project = request.match_info["project"]
        state.db.delete_project_secrets(project, keys=keys,
                                        provider=provider)
        if keys is None and provider == "kubernetes":
            # full wipe: also remove the projected k8s Secret (best-effort;
            # the provider is gated on the kubernetes package)
            try:
                from ..runtime_handlers import KubernetesProvider

                KubernetesProvider().delete_project_secret(project)
            except Exception:  # noqa: BLE001 - no cluster / not deployed
                pass
        return json_response({"ok": True})

    # -- datastore profiles -------------------------------------------------
    @r.put(API + "/projects/{project}/datastore-profiles/{name}")
    async def store_datastore_profile(request):
        body = await request.json()
        profile = body.get("profile") or {}
        profile["name"] = request.match_info["name"]
        state.db.store_datastore_profile(
            profile, request.match_info["project"],
            private=body.get("private") or None)
        return json_response({"ok": True})

    @r.get(API + "/projects/{project}/datastore-profiles/{name}")
    async def get_datastore_profile(request):
        profile = state.db.get_datastore_profile(
            request.match_info["name"], request.match_info["project"])
        if profile is None:
            return error_response("datastore profile not found", 404)
        return json_response({"data": profile})

    @r.get(API + "/projects/{project}/datastore-profiles")
    async def list_datastore_profiles(request):
        return json_response({"datastore_profiles":
                              state.db.list_datastore_profiles(
                                  request.match_info["project"])})

    @r.delete(API + "/projects/{project}/datastore-profiles/{name}")
    async def delete_datastore_profile(request):
        state.db.delete_datastore_profile(
            request.match_info["name"], request.match_info["project"])
        return json_response({"ok": True})
