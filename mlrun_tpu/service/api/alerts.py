"""Alert configs, silencing windows, and event emission (reference:
crud/alerts.py + events; silencing is the TPU-native addition)."""

from __future__ import annotations

from aiohttp import web

from ..http_utils import API, error_response, json_response


def register(r: web.RouteTableDef, state):
    @r.post(API + "/projects/{project}/alerts/{name}")
    async def store_alert(request):
        body = await request.json()
        state.db.store_alert_config(request.match_info["name"], body,
                                    request.match_info["project"])
        return json_response({"ok": True})

    @r.get(API + "/projects/{project}/alerts/{name}")
    async def get_alert(request):
        from ...db.base import RunDBError

        try:
            alert = state.db.get_alert_config(request.match_info["name"],
                                              request.match_info["project"])
        except RunDBError as exc:
            return error_response(str(exc), 404)
        return json_response({"data": alert})

    @r.get(API + "/projects/{project}/alerts")
    async def list_alerts(request):
        return json_response({"alerts": state.db.list_alert_configs(
            request.match_info["project"])})

    @r.post(API + "/projects/{project}/alerts/{name}/silence")
    async def silence_alert(request):
        """Open (or clear) a silencing window on an alert config: body
        {"minutes": N} silences for N minutes; {"minutes": 0} clears."""
        from datetime import datetime, timedelta, timezone

        project = request.match_info["project"]
        name = request.match_info["name"]
        body = await request.json()
        try:
            alert = state.db.get_alert_config(name, project)
        except Exception:
            return error_response(f"alert {name} not found", 404)
        minutes = float(body.get("minutes", 0))
        if minutes > 0:
            until = datetime.now(timezone.utc) + timedelta(minutes=minutes)
            alert["silence_until"] = until.isoformat()
        else:
            alert["silence_until"] = ""
        state.db.store_alert_config(name, alert, project)
        return json_response({"data": alert})

    @r.delete(API + "/projects/{project}/alerts/{name}")
    async def delete_alert(request):
        state.db.delete_alert_config(request.match_info["name"],
                                     request.match_info["project"])
        return json_response({"ok": True})

    @r.post(API + "/projects/{project}/events/{kind}")
    async def emit_event(request):
        body = await request.json()
        project = request.match_info["project"]
        kind = request.match_info["kind"]
        state.db.emit_event(kind, body, project)
        from ..alerts import process_event

        fired = process_event(state.db, project, kind, body)
        return json_response({"ok": True, "alerts_fired": fired})
