"""Health, specs, introspection, background tasks, and runtime
resources (reference: endpoints/healthz.py, client_spec,
frontend_spec, background_tasks.py, runtime_resources.py,
utils/memory_reports.py)."""

from __future__ import annotations

from aiohttp import web

from ... import __version__
from ...common.runtimes_constants import RunStates
from ...config import mlconf
from ...utils import get_in
from ..http_utils import API, error_response, json_response


def register(r: web.RouteTableDef, state):
    @r.get(f"{API}/healthz")
    async def healthz(request):
        return json_response({"status": "ok", "version": __version__})

    @r.get("/metrics")
    async def metrics(request):
        """Prometheus text exposition of the process-wide registry
        (docs/observability.md): run-lifecycle counters (submits, retries
        by failure class, stall aborts), chaos fire counts, and — when
        this process also serves — the serving/engine series. Root path
        (not under the API base) per scraper convention; left open by the
        auth middleware like healthz. Accept:
        application/openmetrics-text negotiates exemplar-carrying
        OpenMetrics output (default stays Prometheus text 0.0.4)."""
        from ...obs import (
            CONTENT_TYPE,
            OPENMETRICS_CONTENT_TYPE,
            PROBE_REQUESTS,
            REGISTRY,
            wants_openmetrics,
        )

        PROBE_REQUESTS.inc(path="/metrics")
        if not bool(mlconf.observability.metrics_enabled):
            return web.Response(status=404, text="metrics exposition is "
                                "disabled (mlconf.observability)")
        om = wants_openmetrics(request.headers.get("Accept"))
        return web.Response(
            body=REGISTRY.render(openmetrics=om).encode(),
            headers={"Content-Type": (OPENMETRICS_CONTENT_TYPE if om
                                      else CONTENT_TYPE)})

    # -- debug endpoints (docs/observability.md "Flight recorder & debug
    # endpoints"); root paths like /metrics, but NOT middleware-open —
    # the flight ring and trace arming stay behind the service token
    @r.get("/debug/flight")
    async def debug_flight(request):
        """Live read of the black-box flight ring: run-lifecycle
        decisions (retries, stall detection), chaos fires, breaker
        trips, engine scheduler events — the same sequence a
        crash/stall post-mortem artifact carries. Handler core shared
        with the serving gateway (obs/debug.py)."""
        import json as _json

        from ...obs.debug import flight_snapshot

        try:
            payload = flight_snapshot(request.query.get("kind", ""),
                                      request.query.get("limit", 0))
        except ValueError as exc:
            return error_response(str(exc), 400)
        return web.json_response(
            payload, dumps=lambda d: _json.dumps(d, default=str))

    @r.get("/debug/trace/{trace_id}")
    async def debug_trace(request):
        """Assembled cross-replica waterfall + blocking critical path
        for one trace id (docs/observability.md "Request attribution,
        exemplars & trace assembly"). Handler core shared with the
        serving gateway (obs/debug.py); like the other /debug routes it
        stays behind the service auth token."""
        import asyncio as _asyncio
        import json as _json

        from ...obs.debug import trace_snapshot

        local_only = request.query.get("local", "") in ("1", "true")
        loop = _asyncio.get_event_loop()
        try:
            payload = await loop.run_in_executor(None, lambda: (
                trace_snapshot(request.match_info["trace_id"],
                               local_only=local_only)))
        except ValueError as exc:
            return error_response(str(exc), 400)
        return web.json_response(
            payload, dumps=lambda d: _json.dumps(d, default=str))

    @r.get("/debug/profile")
    async def debug_profile_get(request):
        from ...utils.profiler import profile_status

        return json_response(profile_status())

    @r.post("/debug/profile")
    async def debug_profile_post(request):
        """Arm ``utils/profiler`` for the next N steps/seconds on a live
        trainer or engine in this process (hot loops tick the armed
        capture; the XLA trace artifact registers on stop) — profile a
        production hot loop without a restart."""
        from ...obs.debug import profile_request

        body = {}
        if request.can_read_body:
            try:
                body = await request.json()
            except ValueError:
                return error_response("body must be JSON", 400)
        try:
            out = profile_request(body)
        except ValueError as exc:
            return error_response(str(exc), 400)
        return json_response(out)

    @r.get(f"{API}/client-spec")
    async def client_spec(request):
        return json_response({
            "version": __version__,
            "namespace": mlconf.namespace,
            "default_project": mlconf.default_project,
            "tpu_defaults": mlconf.tpu.to_dict(),
            "config_overrides": {},
        })

    @r.get(API + "/frontend-spec")
    async def frontend_spec(request):
        from ...common.runtimes_constants import RuntimeKinds

        return json_response({
            "feature_flags": {"tpujob": True, "serving": True,
                              "feature_store": True,
                              "model_monitoring": True},
            "default_artifact_path": mlconf.resolve_artifact_path(
                "{project}"),
            "runtime_kinds": RuntimeKinds.all(),
        })

    @r.get(API + "/operations/memory-report")
    async def memory_report(request):
        """reference analog: server/api/utils/memory_reports.py (objgraph) —
        here host RSS + device HBM via the profiler util."""
        from ...utils.profiler import memory_report as report

        return json_response({"data": report()})

    # -- background tasks ---------------------------------------------------
    @r.get(API + "/projects/{project}/background-tasks")
    async def list_background_tasks(request):
        return json_response(
            {"background_tasks": state.db.list_background_tasks(
                request.match_info["project"])})

    @r.get(API + "/projects/{project}/background-tasks/{name}")
    async def get_background_task(request):
        task = state.db.get_background_task(
            request.match_info["name"], request.match_info["project"])
        if task is None:
            return error_response("background task not found", 404)
        return json_response({"data": task})

    # -- runtime resources (reference: endpoints/runtime_resources.py —
    # grouped listing + filtered deletion of the cluster resources a run
    # created) --------------------------------------------------------------
    @r.get(API + "/projects/{project}/runtime-resources")
    async def list_runtime_resources(request):
        project = request.match_info["project"]
        kind = request.query.get("kind", "")
        rows = state.db.list_runtime_resources(kind)
        if project not in ("*", ""):
            rows = [row for row in rows if row["project"] == project]
        grouped: dict = {}
        for row in rows:
            handler = state.launcher.handler_for(row["kind"])
            try:
                live_state = handler.provider.state(row["resource_id"])
            except Exception:  # noqa: BLE001 - provider may be gone
                live_state = "unknown"
            grouped.setdefault(row["kind"], []).append({
                **row, "state": live_state})
        return json_response({"runtime_resources": [
            {"kind": kind_, "resources": res}
            for kind_, res in sorted(grouped.items())]})

    @r.delete(API + "/projects/{project}/runtime-resources")
    async def delete_runtime_resources(request):
        project = request.match_info["project"]
        kind = request.query.get("kind", "")
        object_id = request.query.get("object-id", "")
        force = request.query.get("force", "") in ("true", "1")
        deleted = []
        for row in state.db.list_runtime_resources(kind):
            if project not in ("*", "") and row["project"] != project:
                continue
            if object_id and row["resource_id"] != object_id:
                continue
            run = state.db.read_run(row["uid"], row["project"])
            run_state = get_in(run or {}, "status.state", "")
            if not force and run_state not in RunStates.terminal_states():
                continue  # reference refuses to delete live runs w/o force
            handler = state.launcher.handler_for(row["kind"])
            try:
                # goes through the handler so the in-memory resource map is
                # also dropped — otherwise the next monitor tick would probe
                # the deleted resource and mark the run failed
                handler.delete_resources(row["uid"], row["project"],
                                         row["resource_id"])
            except Exception:  # noqa: BLE001 - provider may be gone; keep
                # the mapping so a later retry can still find the resource
                continue
            deleted.append(row)
        return json_response({"deleted": deleted})
