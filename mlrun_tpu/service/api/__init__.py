"""Per-resource API modules (reference analog: the router-per-resource
layout of server/api/api/endpoints/ + server/api/crud/ — each module
registers its routes on the shared route table; app.py keeps only
routing, middleware, state, and the periodic loops)."""

from . import (  # noqa: F401
    alerts,
    artifacts,
    feature_store,
    files,
    functions,
    hub,
    monitoring,
    operations,
    projects,
    runs,
    schedules,
    workflows,
)

REGISTRARS = [
    operations.register,
    runs.register,
    artifacts.register,
    files.register,
    functions.register,
    schedules.register,
    projects.register,
    feature_store.register,
    monitoring.register,
    alerts.register,
    workflows.register,
    hub.register,
]
