"""Remote file access (reference server/api/api/endpoints/files.py)."""

from __future__ import annotations

import os

from aiohttp import web

from ...config import mlconf
from ..http_utils import API, error_response, json_response


def _file_access_denied(state, path: str) -> str | None:
    """Service internals are never readable through /files (the
    sqlite DB holds project secret values); an optional allowlist
    (mlconf.httpdb.files_allowed_paths) restricts everything else.
    Local paths (bare or file://) are compared by realpath; remote
    URLs (s3:// etc.) by raw prefix."""
    scheme, _, rest = path.partition("://")
    local = not rest or scheme == "file"
    local_path = (rest if scheme == "file" else path) if local else None
    allowed = [p.strip() for p in str(
        mlconf.httpdb.files_allowed_paths or "").split(",") if p.strip()]
    if local:
        real = os.path.realpath(local_path)
        dsn = os.path.realpath(getattr(state.db, "dsn", "") or "")
        if dsn and real in (dsn, dsn + "-wal", dsn + "-shm"):
            return "service database is not readable through /files"
        if allowed and not any(
                (not a.partition("://")[1])
                and (real.startswith(os.path.realpath(a) + os.sep)
                     or real == os.path.realpath(a))
                for a in allowed):
            return "path is outside files_allowed_paths"
        return None
    if allowed and not any(path.startswith(a) for a in allowed):
        return "path is outside files_allowed_paths"
    return None


def register(r: web.RouteTableDef, state):
    @r.get(API + "/projects/{project}/files")
    async def get_file(request):
        path = request.query.get("path", "")
        if not path:
            return error_response("path query parameter is required", 400)
        denied = _file_access_denied(state, path)
        if denied:
            return error_response(denied, 403)
        try:
            from ...datastore import store_manager

            size = int(request.query.get("size", 0)) or None
            offset = int(request.query.get("offset", 0))
            body = store_manager.object(url=path).get(size=size,
                                                      offset=offset)
        except FileNotFoundError:
            return error_response(f"file not found: {path}", 404)
        except Exception as exc:  # noqa: BLE001
            return error_response(f"failed to read {path}: {exc}", 400)
        if isinstance(body, str):
            body = body.encode()
        return web.Response(body=body,
                            content_type="application/octet-stream")

    @r.get(API + "/projects/{project}/filestat")
    async def get_filestat(request):
        path = request.query.get("path", "")
        if not path:
            return error_response("path query parameter is required", 400)
        denied = _file_access_denied(state, path)
        if denied:
            return error_response(denied, 403)
        try:
            from ...datastore import store_manager

            stats = store_manager.object(url=path).stat()
        except FileNotFoundError:
            return error_response(f"file not found: {path}", 404)
        except Exception as exc:  # noqa: BLE001
            return error_response(f"failed to stat {path}: {exc}", 400)
        return json_response({"size": stats.size, "modified": stats.modified,
                              "content_type": getattr(stats, "content_type",
                                                      None)})
