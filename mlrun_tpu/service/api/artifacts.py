"""Artifacts + tags (reference: crud/artifacts.py;
server/api/api/endpoints/tags.py)."""

from __future__ import annotations

from aiohttp import web

from ..http_utils import (
    API,
    error_response,
    json_response,
    paginate,
    token_paginated_response,
)


def register(r: web.RouteTableDef, state):
    @r.post(API + "/projects/{project}/artifacts/{key}")
    async def store_artifact(request):
        body = await request.json()
        q = request.query
        state.db.store_artifact(
            request.match_info["key"], body, uid=q.get("uid"),
            iter=int(q.get("iter") or 0), tag=q.get("tag", ""),
            project=request.match_info["project"], tree=q.get("tree"))
        return json_response({"ok": True})

    @r.get(API + "/projects/{project}/artifacts/{key}")
    async def read_artifact(request):
        from ...db.base import RunDBError

        q = request.query
        try:
            artifact = state.db.read_artifact(
                request.match_info["key"], tag=q.get("tag"),
                iter=int(q.get("iter") or 0) if q.get("iter") else None,
                project=request.match_info["project"], tree=q.get("tree"),
                uid=q.get("uid"))
        except RunDBError as exc:
            return error_response(str(exc), 404)
        return json_response({"data": artifact})

    @r.get(API + "/projects/{project}/artifacts")
    async def list_artifacts(request):
        q = request.query
        filters = dict(
            name=q.get("name", ""), project=request.match_info["project"],
            tag=q.get("tag"), labels=q.getall("label", None),
            kind=q.get("kind"), tree=q.get("tree"))
        if "page_size" in q or "page_token" in q:
            return token_paginated_response(
                state, request, "list_artifacts", "artifacts", filters)
        artifacts = state.db.list_artifacts(**filters)
        return json_response(
            {"artifacts": paginate(artifacts, request)})

    @r.delete(API + "/projects/{project}/artifacts/{key}")
    async def del_artifact(request):
        state.db.del_artifact(
            request.match_info["key"], tag=request.query.get("tag"),
            project=request.match_info["project"],
            uid=request.query.get("uid"))
        return json_response({"ok": True})

    # -- tags (reference server/api/api/endpoints/tags.py) ------------------
    @r.post(API + "/projects/{project}/tags/{tag}")
    async def overwrite_tag(request):
        body = await request.json()
        if body.get("kind", "artifact") != "artifact":
            return error_response("only artifact tagging is supported", 400)
        tagged = state.db.tag_artifacts(
            request.match_info["project"], request.match_info["tag"],
            body.get("identifiers") or [])
        return json_response({"tagged": tagged})

    @r.delete(API + "/projects/{project}/tags/{tag}")
    async def delete_tag(request):
        body = await request.json()
        if body.get("kind", "artifact") != "artifact":
            return error_response("only artifact tagging is supported", 400)
        removed = state.db.untag_artifacts(
            request.match_info["project"], request.match_info["tag"],
            body.get("identifiers") or [])
        return json_response({"removed": removed})
