"""Schedules CRUD (reference: crud + the APScheduler-backed
scheduler.py surface; firing lives in app.py's scheduler loop /
service/cron.py)."""

from __future__ import annotations

from aiohttp import web

from ..cron import CronSchedule
from ..http_utils import API, error_response, json_response


def register(r: web.RouteTableDef, state):
    @r.post(API + "/projects/{project}/schedules/{name}")
    async def store_schedule(request):
        body = await request.json()
        try:
            CronSchedule(body.get("cron_trigger", ""))
        except ValueError as exc:
            return error_response(f"bad cron: {exc}")
        state.db.store_schedule(request.match_info["project"],
                                request.match_info["name"], body)
        return json_response({"ok": True})

    @r.get(API + "/projects/{project}/schedules/{name}")
    async def get_schedule(request):
        from ...db.base import RunDBError

        try:
            schedule = state.db.get_schedule(request.match_info["project"],
                                             request.match_info["name"])
        except RunDBError as exc:
            return error_response(str(exc), 404)
        return json_response({"data": schedule})

    @r.get(API + "/projects/{project}/schedules")
    async def list_schedules(request):
        return json_response({"schedules": state.db.list_schedules(
            request.match_info["project"])})

    @r.delete(API + "/projects/{project}/schedules/{name}")
    async def delete_schedule(request):
        state.db.delete_schedule(request.match_info["project"],
                                 request.match_info["name"])
        return json_response({"ok": True})
