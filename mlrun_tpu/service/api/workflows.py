"""Workflow submission/status + the pipelines surface (reference:
endpoints/workflows.py; endpoints/pipelines.py — a KFP proxy; here
the native workflow runner doubles as the pipeline backend)."""

from __future__ import annotations

import threading

from aiohttp import web

from ...common.runtimes_constants import RunStates
from ...utils import generate_uid, now_iso
from ..http_utils import API, error_response, json_response


def register(r: web.RouteTableDef, state):
    @r.post(API + "/projects/{project}/workflows/submit")
    async def submit_workflow(request):
        body = await request.json()
        workflow_id = generate_uid()
        project = request.match_info["project"]
        state.workflows[workflow_id] = {
            "id": workflow_id, "project": project,
            "state": RunStates.running, "spec": body, "started": now_iso(),
        }

        def run_workflow():
            try:
                # workflow spec carries the project source + workflow path
                pipeline = body.get("pipeline", {})
                from ...projects import load_project

                proj = load_project(
                    context=pipeline.get("context", "./"),
                    name=project, save=False)
                status = proj.run(
                    name=pipeline.get("name", ""),
                    workflow_path=pipeline.get("path", ""),
                    arguments=body.get("arguments"),
                    artifact_path=body.get("artifact_path", ""),
                    engine="local")
                state.workflows[workflow_id]["state"] = status.state
            except Exception as exc:  # noqa: BLE001
                state.workflows[workflow_id]["state"] = RunStates.error
                state.workflows[workflow_id]["error"] = str(exc)

        threading.Thread(target=run_workflow, daemon=True).start()
        return json_response({"id": workflow_id})

    @r.get(API + "/projects/{project}/workflows/{workflow_id}")
    async def workflow_status(request):
        workflow = state.workflows.get(request.match_info["workflow_id"])
        if workflow is None:
            return error_response("workflow not found", 404)
        return json_response({"state": workflow["state"],
                              "error": workflow.get("error")})

    @r.get(API + "/projects/{project}/pipelines")
    async def list_pipelines(request):
        project = request.match_info["project"]
        runs = [w for w in state.workflows.values()
                if project in ("*", "") or w.get("project") == project]
        return json_response({"runs": sorted(
            runs, key=lambda w: w.get("started", ""), reverse=True),
            "total_size": len(runs)})

    @r.get(API + "/projects/{project}/pipelines/{run_id}")
    async def get_pipeline(request):
        workflow = state.workflows.get(request.match_info["run_id"])
        if workflow is None:
            return error_response("pipeline run not found", 404)
        return json_response({"run": workflow})
