"""Hub source administration + catalog (reference:
server/api/api/endpoints/hub.py)."""

from __future__ import annotations

import os

from aiohttp import web

from ..http_utils import API, error_response, json_response


def register(r: web.RouteTableDef, state):
    def _hub_source_path(name: str):
        if name == "default":
            from ...hub import builtin_hub_path

            return builtin_hub_path()
        source = state.db.get_hub_source(name)
        return (source or {}).get("path")

    @r.put(API + "/hub/sources/{name}")
    async def store_hub_source(request):
        body = await request.json()
        name = request.match_info["name"]
        if name == "default":
            return error_response("the default source is built-in", 400)
        state.db.store_hub_source(name, body.get("source") or body,
                                  order=int(body.get("order", -1)))
        return json_response({"data": state.db.get_hub_source(name)})

    @r.get(API + "/hub/sources")
    async def list_hub_sources(request):
        sources = [{"name": "default", "builtin": True}]
        sources.extend(state.db.list_hub_sources())
        return json_response({"sources": sources})

    @r.get(API + "/hub/sources/{name}")
    async def get_hub_source(request):
        name = request.match_info["name"]
        if name == "default":
            return json_response({"data": {"name": "default",
                                           "builtin": True}})
        source = state.db.get_hub_source(name)
        if source is None:
            return error_response(f"hub source {name} not found", 404)
        return json_response({"data": source})

    @r.delete(API + "/hub/sources/{name}")
    async def delete_hub_source(request):
        state.db.delete_hub_source(request.match_info["name"])
        return json_response({"ok": True})

    @r.get(API + "/hub/sources/{name}/items")
    async def hub_catalog(request):
        path = _hub_source_path(request.match_info["name"])
        if not path or not os.path.isdir(path):
            return error_response("hub source has no readable path", 404)
        items = []
        for entry in sorted(os.listdir(path)):
            fn_yaml = os.path.join(path, entry, "function.yaml")
            if os.path.isfile(fn_yaml):
                items.append({"name": entry})
        return json_response({"catalog": items})

    @r.get(API + "/hub/sources/{name}/items/{item}")
    async def hub_item(request):
        import yaml

        path = _hub_source_path(request.match_info["name"])
        item = request.match_info["item"]
        if ".." in item or "/" in item or os.sep in item:
            return error_response("invalid hub item name", 400)
        fn_yaml = os.path.join(path or "", item, "function.yaml")
        if not path or not os.path.isfile(fn_yaml):
            return error_response(f"hub item {item} not found", 404)
        with open(fn_yaml) as f:
            return json_response({"data": yaml.safe_load(f)})
