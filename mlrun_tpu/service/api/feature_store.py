"""Feature-store object CRUD (reference: crud/feature_store.py —
feature-sets and feature-vectors share one generic surface)."""

from __future__ import annotations

from aiohttp import web

from ..http_utils import API, error_response, json_response


def register(r: web.RouteTableDef, state):
    def _fs_routes(kind: str, store, get, list_, delete):
        @r.post(API + "/projects/{project}/" + kind + "/{name}")
        async def _store(request):
            body = await request.json()
            uid = store(body, name=request.match_info["name"],
                        project=request.match_info["project"],
                        tag=request.query.get("tag"),
                        uid=request.query.get("uid"))
            return json_response({"uid": uid})

        @r.get(API + "/projects/{project}/" + kind + "/{name}")
        async def _get(request):
            from ...db.base import RunDBError

            try:
                obj = get(request.match_info["name"],
                          project=request.match_info["project"],
                          tag=request.query.get("tag"),
                          uid=request.query.get("uid"))
            except RunDBError as exc:
                return error_response(str(exc), 404)
            return json_response({"data": obj})

        @r.get(API + "/projects/{project}/" + kind)
        async def _list(request):
            objs = list_(project=request.match_info["project"],
                         name=request.query.get("name", ""),
                         tag=request.query.get("tag"))
            return json_response({kind.replace("-", "_"): objs})

        @r.delete(API + "/projects/{project}/" + kind + "/{name}")
        async def _delete(request):
            delete(request.match_info["name"],
                   project=request.match_info["project"])
            return json_response({"ok": True})

    _fs_routes("feature-sets", state.db.store_feature_set,
               state.db.get_feature_set, state.db.list_feature_sets,
               state.db.delete_feature_set)
    _fs_routes("feature-vectors", state.db.store_feature_vector,
               state.db.get_feature_vector, state.db.list_feature_vectors,
               state.db.delete_feature_vector)
