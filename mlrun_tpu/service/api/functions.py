"""Functions, deploy, build, and api-gateways (reference:
crud/functions.py; endpoints/functions.py:272 build;
nuclio function.py:551 deploy; endpoints/api_gateways.py)."""

from __future__ import annotations

import asyncio

from aiohttp import web

from ...common.runtimes_constants import RuntimeKinds
from ...config import mlconf
from ...utils import update_in
from ..http_utils import API, error_response, json_response, paginate


def register(r: web.RouteTableDef, state):
    @r.post(API + "/projects/{project}/functions/{name}")
    async def store_function(request):
        body = await request.json()
        hash_key = state.db.store_function(
            body, request.match_info["name"], request.match_info["project"],
            tag=request.query.get("tag", ""),
            versioned=bool(int(request.query.get("versioned", 0))))
        return json_response({"hash_key": hash_key})

    @r.get(API + "/projects/{project}/functions/{name}")
    async def get_function(request):
        from ...db.base import RunDBError

        try:
            func = state.db.get_function(
                request.match_info["name"], request.match_info["project"],
                tag=request.query.get("tag", ""),
                hash_key=request.query.get("hash_key", ""))
        except RunDBError as exc:
            return error_response(str(exc), 404)
        return json_response({"func": func})

    @r.get(API + "/projects/{project}/functions")
    async def list_functions(request):
        funcs = state.db.list_functions(
            name=request.query.get("name", ""),
            project=request.match_info["project"],
            tag=request.query.get("tag", ""),
            labels=request.query.getall("label", None))
        return json_response({"funcs": paginate(funcs, request)})

    @r.delete(API + "/projects/{project}/functions/{name}")
    async def delete_function(request):
        # a live gateway dies with its function
        loop = asyncio.get_event_loop()
        await loop.run_in_executor(
            None, lambda: state.deployments.teardown(
                request.match_info["name"], request.match_info["project"],
                store_state=False))
        state.db.delete_function(request.match_info["name"],
                                 request.match_info["project"])
        return json_response({"ok": True})

    @r.post(API + "/projects/{project}/functions/{name}/deploy")
    async def deploy_function(request):
        """Deploy = a RUNNING, addressable gateway (reference nuclio
        function.py:551; serving.py:580). The deployment manager spawns an
        ASGI graph-server process (local provider) or a Deployment+Service
        (kubernetes) and answers once it's invocable."""
        body = await request.json()
        function = body.get("function", {})
        update_in(function, "metadata.name", request.match_info["name"])
        update_in(function, "metadata.project",
                  request.match_info["project"])
        kind = function.get("kind", "")
        if kind not in (RuntimeKinds.serving, RuntimeKinds.remote,
                        RuntimeKinds.application):
            # batch kinds have nothing to run until submitted — deploy just
            # resolves the image + readiness (the build path)
            update_in(function, "status.state", "ready")
            state.db.store_function(
                function, request.match_info["name"],
                request.match_info["project"],
                tag=function.get("metadata", {}).get("tag", "latest"))
            return json_response({"data": {"state": "ready",
                                           "address": ""}})
        loop = asyncio.get_event_loop()
        info = await loop.run_in_executor(
            None, lambda: state.deployments.deploy(function))
        if info["state"] == "error":
            return error_response(
                f"function deploy failed: {info.get('error', '')}", 400)
        return json_response({"data": info})

    @r.delete(API + "/projects/{project}/functions/{name}/deploy")
    async def undeploy_function(request):
        loop = asyncio.get_event_loop()
        removed = await loop.run_in_executor(
            None, lambda: state.deployments.teardown(
                request.match_info["name"], request.match_info["project"]))
        return json_response({"removed": removed})

    # -- build --------------------------------------------------------------
    @r.post(API + "/build/function")
    async def build_function(request):
        """Real build path (reference server/api/utils/builder.py:39,144 +
        endpoints/functions.py:272): prebuilt image + code-in-env stays a
        no-op, but requirements/commands now trigger an actual build — a
        venv-cache pre-warm (local provider) or a Kaniko pod (kubernetes),
        tracked as a background task with a retrievable log."""
        body = await request.json()
        function = body.get("function", {})
        with_tpu = body.get("with_tpu", False)
        loop = asyncio.get_event_loop()
        status = await loop.run_in_executor(
            None, lambda: state.builder.build(function, with_tpu=with_tpu))
        return json_response({"data": {"status": status}})

    @r.get(API + "/build/status")
    async def build_status(request):
        """Build state + incremental log (reference get_builder_status)."""
        status = state.builder.status(
            request.query.get("name", ""),
            request.query.get("project", "") or mlconf.default_project,
            tag=request.query.get("tag", "latest"),
            offset=int(request.query.get("offset", 0) or 0))
        if status["state"] == "not_found":
            return error_response("function not found", 404)
        return json_response({"data": status})

    # -- api gateways (stored as api-gateway kind function objects) ---------
    @r.post(API + "/projects/{project}/api-gateways/{name}")
    async def store_api_gateway(request):
        body = await request.json()
        gateway = body.get("data", body)
        gateway["kind"] = "api-gateway"
        state.db.store_function(gateway, request.match_info["name"],
                                request.match_info["project"],
                                tag="latest")
        return json_response({"ok": True})

    @r.get(API + "/projects/{project}/api-gateways/{name}")
    async def get_api_gateway(request):
        from ...db.base import RunDBError

        try:
            gateway = state.db.get_function(
                request.match_info["name"], request.match_info["project"])
        except RunDBError as exc:
            return error_response(str(exc), 404)
        return json_response({"data": gateway})

    @r.get(API + "/projects/{project}/api-gateways")
    async def list_api_gateways(request):
        funcs = state.db.list_functions(
            project=request.match_info["project"])
        return json_response({"api_gateways": [
            f for f in funcs if f.get("kind") == "api-gateway"]})
