"""Minimal 5-field cron parser/scheduler (replaces the reference's
APScheduler dependency, server/api/utils/scheduler.py:48)."""

from __future__ import annotations

from datetime import datetime, timedelta
from typing import Optional


def _parse_field(field: str, lo: int, hi: int) -> set[int]:
    values: set[int] = set()
    for part in field.split(","):
        step = 1
        if "/" in part:
            part, step_s = part.split("/", 1)
            step = int(step_s)
        if part in ("*", ""):
            start, end = lo, hi
        elif "-" in part:
            start_s, end_s = part.split("-", 1)
            start, end = int(start_s), int(end_s)
        else:
            start = end = int(part)
        if start < lo or end > hi:
            raise ValueError(f"cron field value out of range [{lo},{hi}]: "
                             f"{part}")
        values.update(range(start, end + 1, step))
    return values


class CronSchedule:
    """minute hour day-of-month month day-of-week."""

    def __init__(self, expr: str):
        fields = expr.split()
        if len(fields) != 5:
            raise ValueError(f"cron expression must have 5 fields: '{expr}'")
        self.expr = expr
        self.minutes = _parse_field(fields[0], 0, 59)
        self.hours = _parse_field(fields[1], 0, 23)
        self.days = _parse_field(fields[2], 1, 31)
        self.months = _parse_field(fields[3], 1, 12)
        self.weekdays = _parse_field(fields[4], 0, 6)  # 0 = monday (ISO-1)

    def matches(self, when: datetime) -> bool:
        return (when.minute in self.minutes and when.hour in self.hours
                and when.day in self.days and when.month in self.months
                and when.weekday() in self.weekdays)

    def next_after(self, when: datetime) -> Optional[datetime]:
        """Next matching minute after `when` (searches up to 366 days)."""
        candidate = when.replace(second=0, microsecond=0) + \
            timedelta(minutes=1)
        for _ in range(366 * 24 * 60):
            if self.matches(candidate):
                return candidate
            candidate += timedelta(minutes=1)
        return None

    def min_interval_seconds(self) -> float:
        """Rough lower bound on firing interval (for validation)."""
        if len(self.minutes) > 1:
            sorted_m = sorted(self.minutes)
            gaps = [b - a for a, b in zip(sorted_m, sorted_m[1:])]
            gaps.append(60 - sorted_m[-1] + sorted_m[0])
            return min(gaps) * 60
        if len(self.hours) > 1:
            return 3600
        return 24 * 3600
