"""Function deployment manager — deploys actually deploy.

Reference analog: Nuclio deploys in `mlrun/runtimes/nuclio/function.py:551`
(deploy → a running, addressable, replicated function; `:887` invoke;
`:87-88,113-114` replica scaling) and `nuclio/serving.py:580` (serving
deploy). Nuclio itself is replaced by the in-package ASGI gateway
(`serving/asgi.py`); this manager turns a deploy request into a *live*
gateway process:

- ``LocalProcessProvider``: allocates a port, spawns ``mlrun-tpu serve``
  with the function's env (incl. SERVING_SPEC_ENV), waits for HTTP
  readiness, and records ``http://127.0.0.1:<port>`` in the function
  status.
- ``KubernetesProvider``: builds a Deployment (min_replicas) + Service
  pair; the address is the in-cluster service DNS name.

Gateways are tracked in the ``runtime_resources`` table (kind="gateway")
so they survive service restarts and the monitor loop can flip the
function status to ``error`` when a gateway dies.
"""

from __future__ import annotations

import socket
import threading
import time
import urllib.error
import urllib.request

from ..common.runtimes_constants import PodPhases
from ..config import mlconf
from ..utils import get_in, logger, update_in

GATEWAY_KIND = "gateway"
# states a gateway-backed function can be in (subset of the reference's
# nuclio deploy states: ready/error/unhealthy)
DEPLOY_READY = "ready"
DEPLOY_ERROR = "error"
DEPLOY_UNHEALTHY = "unhealthy"


def _free_port() -> int:
    with socket.socket() as sock:
        sock.bind(("127.0.0.1", 0))
        return sock.getsockname()[1]


def _http_ok(url: str, timeout: float = 1.0) -> bool:
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.status < 500
    except (urllib.error.URLError, OSError, ValueError):
        return False


class DeploymentManager:
    """Create/monitor/tear-down live function gateways."""

    def __init__(self, db, provider):
        self.db = db
        self.provider = provider
        # deploys of the SAME function serialize (concurrent deploys would
        # otherwise race teardown/spawn and leak an untracked gateway);
        # different functions still deploy in parallel
        self._locks: dict[tuple, threading.Lock] = {}
        self._locks_guard = threading.Lock()

    def _function_lock(self, name: str, project: str) -> threading.Lock:
        with self._locks_guard:
            return self._locks.setdefault((project, name),
                                          threading.Lock())

    # -- deploy ------------------------------------------------------------
    def deploy(self, function: dict, tag: str = "") -> dict:
        """Start (or replace) the gateway for a function and wait for it to
        come up. Returns ``{"state", "address", "resource_id"}`` and stores
        the function with its refreshed status (reference deploy returns
        once the function is invocable, function.py:551)."""
        name = get_in(function, "metadata.name", "fn")
        project = get_in(function, "metadata.project",
                         mlconf.default_project)
        tag = tag or get_in(function, "metadata.tag", "") or "latest"

        with self._function_lock(name, project):
            # replace semantics: a re-deploy tears the previous gateway
            # down first so two processes never race for the function's
            # identity
            self.teardown(name, project, store_state=False)

            if self.provider.kind == "kubernetes":
                info = self._deploy_kubernetes(function, name, project,
                                               tag=tag)
            else:
                info = self._deploy_local(function, name, project, tag=tag)

            update_in(function, "status.state", info["state"])
            update_in(function, "status.address", info["address"])
            if info["state"] == DEPLOY_READY:
                update_in(function, "status.external_invocation_urls",
                          [info["address"]])
            self.db.store_function(function, name, project, tag=tag)
            return info

    def _gateway_env(self, function: dict, project: str) -> list[dict]:
        # valueFrom entries (secretKeyRef/fieldRef) pass through for the
        # kubernetes manifest; the local provider only materializes
        # value-typed entries (it has no kubelet to resolve the refs)
        env = [dict(item) for item in
               get_in(function, "spec.env", []) or []
               if isinstance(item, dict)
               and ("value" in item or "valueFrom" in item)]
        names = {item.get("name") for item in env}
        if "MLT_DBPATH" not in names:
            env.append({
                "name": "MLT_DBPATH",
                "value": mlconf.get("dbpath", "")
                or f"http://127.0.0.1:{mlconf.httpdb.port}"})
        # the gateway is a fresh process: it must not inherit this
        # service's role and try to become a second chief
        env.append({"name": "MLT_CLUSTER_ROLE", "value": ""})
        # embedded user code travels with the gateway (asgi.server_from_env
        # execs it into the graph-class namespace; the reference bakes the
        # same source into the nuclio image)
        code = get_in(function, "spec.build.functionSourceCode", "")
        if code and mlconf.exec_code_env not in names:
            env.append({"name": mlconf.exec_code_env, "value": code})
        # project secrets: plain env with the local provider; with
        # kubernetes they ride a k8s Secret + envFrom (below) so values
        # never appear in the manifest
        if not hasattr(self.provider, "ensure_project_secret"):
            from .secrets import project_secret_env

            for key, value in project_secret_env(self.db, project).items():
                env.append({"name": key, "value": str(value)})
        return env

    def _project_k8s_secrets(self, deployment: dict, project: str):
        ensure = getattr(self.provider, "ensure_project_secret", None)
        if ensure is None:
            return
        from .secrets import project_secret_env

        secrets = project_secret_env(self.db, project)
        if not secrets:
            return
        secret_name = ensure(project, secrets)
        for container in deployment["spec"]["template"]["spec"][
                "containers"]:
            container.setdefault("envFrom", []).append(
                {"secretRef": {"name": secret_name}})

    def _deploy_local(self, function: dict, name: str, project: str,
                      tag: str = "latest") -> dict:
        port = _free_port()
        # bind locally; the *recorded* address uses the advertise host so a
        # status row read from another machine still names a host that
        # resolves to this gateway (mlconf.function.gateway_advertise_host,
        # default 127.0.0.1 for single-host setups)
        advertise = str(mlconf.function.gateway_advertise_host
                        or "127.0.0.1")
        address = f"http://{advertise}:{port}"
        resource = self._build_deployment(
            function, name, project, port=port, replicas=1,
            host="127.0.0.1" if advertise == "127.0.0.1" else "0.0.0.0")
        uid = f"gateway-{name}"
        try:
            resource_id = self.provider.create(resource, uid)
        except Exception as exc:  # noqa: BLE001
            logger.warning("gateway spawn failed", function=name,
                           error=str(exc))
            return {"state": DEPLOY_ERROR, "address": "",
                    "resource_id": "", "error": str(exc)}
        self.db.store_runtime_resource(uid, project, GATEWAY_KIND,
                                       resource_id, time.time(), tag=tag)
        ready_timeout = float(mlconf.function.gateway_ready_timeout)
        if get_in(function, "spec.build.requirements", None):
            # first boot pip-installs the overlay before the server binds
            ready_timeout = max(ready_timeout * 3, 60.0)
        deadline = time.time() + ready_timeout
        # readiness always polls loopback — the gateway is a child of this
        # service even when the advertised address names another interface
        probe = f"http://127.0.0.1:{port}"
        while time.time() < deadline:
            if _http_ok(f"{probe}/__stats__"):
                logger.info("gateway ready", function=name,
                            address=address)
                return {"state": DEPLOY_READY, "address": address,
                        "resource_id": resource_id}
            if self.provider.state(resource_id) not in (
                    PodPhases.running, PodPhases.pending):
                break
            time.sleep(0.2)
        # the local provider pumps gateway stdout into the log store under
        # the gateway uid — surface the tail so the failure is diagnosable
        log = b""
        try:
            _, log = self.db.get_log(uid, project)
        except Exception:  # noqa: BLE001
            pass
        self.provider.delete(resource_id)
        self.db.del_runtime_resource(uid, project)
        tail = log[-2000:].decode(errors="replace") if log else ""
        logger.warning("gateway did not become ready", function=name,
                       tail=tail)
        return {"state": DEPLOY_ERROR, "address": "", "resource_id": "",
                "error": f"gateway did not become ready: {tail}"}

    def _deploy_kubernetes(self, function: dict, name: str,
                           project: str, tag: str = "latest") -> dict:
        port = int(get_in(function, "spec.config.http.port", 0) or 8080)
        deployment = self._build_deployment(
            function, name, project, port=port,
            replicas=int(get_in(function, "spec.min_replicas", 1) or 1))
        service = self._build_service(name, project, port)
        self._project_k8s_secrets(deployment, project)
        uid = f"gateway-{name}"
        try:
            resource_id = self.provider.create(deployment, uid)
            self.provider.create_service(service)
        except Exception as exc:  # noqa: BLE001 - deploy() error contract:
            # quota/409/validation failures must come back as a state=error
            # dict (like _deploy_local), not a raw 500
            logger.warning("gateway deployment create failed",
                           function=name, error=str(exc))
            return {"state": DEPLOY_ERROR, "address": "",
                    "resource_id": "", "error": str(exc)}
        self.db.store_runtime_resource(uid, project, GATEWAY_KIND,
                                       resource_id, time.time(), tag=tag)
        address = (f"http://{service['metadata']['name']}."
                   f"{mlconf.namespace}.svc.cluster.local:{port}")
        ready_timeout = float(mlconf.function.gateway_ready_timeout)
        if get_in(function, "spec.build.requirements", None):
            # first boot pip-installs the overlay before the server binds
            # — same allowance the local path grants (ADVICE r4: without
            # it requirement-bearing k8s gateways routinely came up
            # DEPLOY_UNHEALTHY)
            ready_timeout = max(ready_timeout * 3, 60.0)
        deadline = time.time() + ready_timeout
        while time.time() < deadline:
            if self.provider.state(resource_id) == PodPhases.running:
                return {"state": DEPLOY_READY, "address": address,
                        "resource_id": resource_id}
            time.sleep(1.0)
        # k8s keeps retrying the rollout in the background; report the
        # address but not ready (the reference reports 'deploying' the
        # same way until the nuclio rollout settles)
        return {"state": DEPLOY_UNHEALTHY, "address": address,
                "resource_id": resource_id}

    def _build_deployment(self, function: dict, name: str, project: str,
                          port: int, replicas: int,
                          host: str = "0.0.0.0") -> dict:
        labels = {
            "mlrun-tpu/project": project,
            "mlrun-tpu/uid": f"gateway-{name}",
            "mlrun-tpu/class": GATEWAY_KIND,
            "mlrun-tpu/function": name,
        }
        # gateways honor build.requirements like batch runs do: the serve
        # command bootstraps onto the cached requirements overlay first
        # (runtime_handlers._wrap_with_bootstrap is the batch-side analog;
        # without this a serving function declaring requirements would
        # silently come up without them)
        command = ["mlrun-tpu", "serve",
                   "--port", str(port), "--host", host]
        requirements = list(
            get_in(function, "spec.build.requirements", []) or [])
        if requirements:
            wrapped = ["mlrun-tpu", "bootstrap"]
            for req in requirements:
                wrapped += ["-r", req]
            command = wrapped + ["--"] + command
        container = {
            "name": "gateway",
            "image": get_in(function, "spec.image", "")
            or mlconf.function.default_image,
            "command": command,
            "env": self._gateway_env(function, project),
            "ports": [{"containerPort": port}],
            "readinessProbe": {
                "httpGet": {"path": "/__stats__", "port": port},
                "periodSeconds": 5,
            },
        }
        resources = get_in(function, "spec.resources", None)
        if resources:
            container["resources"] = resources
        return {
            "apiVersion": "apps/v1",
            "kind": "Deployment",
            "metadata": {
                "name": f"mlt-gw-{project}-{name}"[:63],
                "namespace": mlconf.namespace,
                "labels": labels,
            },
            "spec": {
                "replicas": replicas,
                "selector": {"matchLabels": {
                    "mlrun-tpu/function": name,
                    "mlrun-tpu/project": project}},
                "template": {
                    "metadata": {"labels": labels},
                    "spec": {"containers": [container],
                             "restartPolicy": "Always"},
                },
            },
        }

    @staticmethod
    def _build_service(name: str, project: str, port: int) -> dict:
        return {
            "apiVersion": "v1",
            "kind": "Service",
            "metadata": {
                "name": f"mlt-gw-{project}-{name}"[:63],
                "namespace": mlconf.namespace,
                "labels": {"mlrun-tpu/class": GATEWAY_KIND,
                           "mlrun-tpu/project": project,
                           "mlrun-tpu/function": name},
            },
            "spec": {
                "selector": {"mlrun-tpu/function": name,
                             "mlrun-tpu/project": project},
                "ports": [{"port": port, "targetPort": port}],
            },
        }

    # -- lifecycle ---------------------------------------------------------
    def teardown(self, name: str, project: str,
                 store_state: bool = True) -> bool:
        """Stop the gateway (if any). With ``store_state`` the function's
        status flips to offline so clients stop invoking it."""
        uid = f"gateway-{name}"
        row = self._resource_row(uid, project)
        if row is None:
            return False
        try:
            self.provider.delete(row["resource_id"])
        except Exception as exc:  # noqa: BLE001
            logger.warning("gateway delete failed", function=name,
                           error=str(exc))
        self.db.del_runtime_resource(uid, project)
        if store_state:
            self._set_function_state(name, project, "offline",
                                     tag=row.get("tag", ""))
        return True

    def monitor(self):
        """Flip functions whose gateway died to ``error`` (the reference's
        nuclio state sync; VERDICT r2 asks for monitor-loop coverage of
        gateway death). Called from the service monitor loop."""
        for row in self.db.list_runtime_resources(kind=GATEWAY_KIND):
            uid = row["uid"]
            if not uid.startswith("gateway-"):
                continue
            name = uid.split("-", 1)[1]
            try:
                live = self.provider.state(row["resource_id"])
            except Exception as exc:  # noqa: BLE001
                # a 404 means the resource was deleted out-of-band
                # (kubectl delete) — that's a dead gateway, not a blip;
                # anything else (API hiccup) is skipped until next tick
                if getattr(exc, "status", None) == 404 \
                        or "not found" in str(exc).lower():
                    live = PodPhases.failed
                else:
                    continue
            if live == PodPhases.running:
                # the rollout settled after deploy() stopped waiting —
                # promote the function back to ready (ADVICE r4: monitor
                # only ever demoted, so a slow first boot left the stored
                # state 'unhealthy' forever even once the pod was up).
                # Cheap lock-free peek first: the all-healthy steady state
                # must not take N function locks per tick
                if not self._is_unhealthy(name, row["project"],
                                          tag=row.get("tag", "")):
                    continue
                with self._function_lock(name, row["project"]):
                    current = self._resource_row(uid, row["project"])
                    if current is not None and \
                            current["resource_id"] == row["resource_id"]:
                        self._promote_if_unhealthy(
                            name, row["project"], tag=row.get("tag", ""))
                continue
            if live in (PodPhases.failed, PodPhases.succeeded):
                # serialize with deploy(): a concurrent redeploy may have
                # just replaced this row — re-read under the lock and only
                # act if the dead resource is still the tracked one
                with self._function_lock(name, row["project"]):
                    current = self._resource_row(uid, row["project"])
                    if current is None or \
                            current["resource_id"] != row["resource_id"]:
                        continue
                    logger.warning("gateway died", function=name,
                                   project=row["project"], state=live)
                    # delete the provider resource too: a crash-looping
                    # k8s Deployment would otherwise stay in the cluster
                    # untracked and block every future redeploy with
                    # AlreadyExists
                    try:
                        self.provider.delete(row["resource_id"])
                    except Exception:  # noqa: BLE001 - already gone
                        pass
                    self.db.del_runtime_resource(uid, row["project"])
                    self._set_function_state(name, row["project"],
                                             DEPLOY_ERROR,
                                             tag=row.get("tag", ""))

    def _resource_row(self, uid: str, project: str) -> dict | None:
        for row in self.db.list_runtime_resources(kind=GATEWAY_KIND):
            if row["uid"] == uid and row["project"] == project:
                return row
        return None

    def _is_unhealthy(self, name: str, project: str,
                      tag: str = "") -> bool:
        try:
            function = self.db.get_function(name, project,
                                            tag=tag or "latest")
        except Exception:  # noqa: BLE001
            return False
        return bool(function) and get_in(
            function, "status.state", "") == DEPLOY_UNHEALTHY

    def _promote_if_unhealthy(self, name: str, project: str,
                              tag: str = ""):
        tag = tag or "latest"
        try:
            function = self.db.get_function(name, project, tag=tag)
        except Exception:  # noqa: BLE001
            return
        if not function or get_in(
                function, "status.state", "") != DEPLOY_UNHEALTHY:
            return
        address = get_in(function, "status.address", "")
        update_in(function, "status.state", DEPLOY_READY)
        if address:
            update_in(function, "status.external_invocation_urls",
                      [address])
        self.db.store_function(function, name, project, tag=tag)
        logger.info("gateway recovered", function=name, project=project)

    def _set_function_state(self, name: str, project: str, state: str,
                            tag: str = ""):
        # the deployed tag rides the runtime-resource row — a gateway
        # deployed as mytag must flip mytag's stored function, not latest
        tag = tag or "latest"
        try:
            function = self.db.get_function(name, project, tag=tag)
        except Exception:  # noqa: BLE001
            return
        if not function:
            return
        update_in(function, "status.state", state)
        if state != DEPLOY_READY:
            update_in(function, "status.address", "")
            update_in(function, "status.external_invocation_urls", [])
        self.db.store_function(function, name, project, tag=tag)
