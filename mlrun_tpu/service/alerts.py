"""Alert/event processing (reference analog: server/api/crud/{alerts,events}.py
+ alert_states in sqldb/models.py)."""

from __future__ import annotations

from datetime import datetime, timedelta, timezone

from ..utils import logger, now_iso

# (project, name) pairs already warned about a missing trigger_events
# list — one warning per config, not one per processed event
_warned_no_triggers: set = set()


def process_event(db, project: str, event_kind: str, event: dict) -> list:
    """Evaluate alert configs against an incoming event; fire notifications
    when criteria (count within period) are met. Returns fired alert names."""
    fired = []
    for config in db.list_alert_configs(project):
        # explicit trigger matching: a missing/empty trigger_events list
        # matches NOTHING (it used to silently match every event kind —
        # a config created without triggers would fire on anything);
        # catch-all is opt-in via the explicit "*" wildcard
        triggers = config.get("trigger_events") or []
        if not triggers:
            if (project, config.get("name")) not in _warned_no_triggers:
                _warned_no_triggers.add((project, config.get("name")))
                logger.warning(
                    "alert config has no trigger_events; it will never "
                    "fire (use [\"*\"] for an explicit catch-all)",
                    alert=config.get("name"), project=project)
            continue
        if "*" not in triggers and event_kind not in triggers:
            continue
        entity = config.get("entity_id", "*")
        if entity not in ("*", event.get("entity_id", "*")):
            continue
        criteria = config.get("criteria") or {}
        required = int(criteria.get("count", 1))
        period = float(criteria.get("period_seconds", 3600))
        since = datetime.now(timezone.utc) - timedelta(seconds=period)
        if _silenced(config):
            continue
        events = db.list_events(project, kind=event_kind,
                                since=since.isoformat())
        if len(events) >= required:
            if config.get("state") == "active" and \
                    config.get("reset_policy", "auto") == "manual":
                continue
            config["state"] = "active"
            config["count"] = config.get("count", 0) + 1
            config["last_fired"] = now_iso()
            db.store_alert_config(config.get("name"), config, project)
            _notify(config, event)
            fired.append(config.get("name"))
        elif config.get("reset_policy", "auto") == "auto" and \
                config.get("state") == "active":
            config["state"] = "inactive"
            db.store_alert_config(config.get("name"), config, project)
    return fired


def _silenced(config: dict) -> bool:
    """True while the config's silence window is open (silence_until ISO
    timestamp in the future): criteria still evaluate, nothing fires."""
    until = config.get("silence_until") or ""
    if not until:
        return False
    try:
        parsed = datetime.fromisoformat(until.replace("Z", "+00:00"))
    except ValueError:
        return False
    if parsed.tzinfo is None:
        parsed = parsed.replace(tzinfo=timezone.utc)
    return datetime.now(timezone.utc) < parsed


def _notify(config: dict, event: dict):
    from ..utils.notifications.notification import notification_types

    for spec in config.get("notifications") or [{"kind": "console"}]:
        kind = spec.get("kind", "console")
        cls = notification_types.get(kind)
        if cls is None:
            continue
        try:
            cls(spec.get("name", ""), spec.get("params", {})).push(
                f"alert '{config.get('name')}' fired: "
                f"{config.get('summary', '')}",
                severity=config.get("severity", "medium"))
        except Exception as exc:  # noqa: BLE001
            logger.warning("alert notification failed", error=str(exc))


# -- builtin alert templates (reference alert_templates: JobFailed /
# DataDriftDetected / SystemPerformance pre-baked configs a project
# instantiates with its own entity + notifications) -----------------------
ALERT_TEMPLATES: dict[str, dict] = {
    "JobFailed": {
        "description": "a run failed",
        "trigger_events": ["run_failed", "run_aborted"],
        "severity": "high",
        "criteria": {"count": 1, "period_seconds": 600},
        "reset_policy": "auto",
    },
    "DataDriftDetected": {
        "description": "model monitoring detected data drift",
        "trigger_events": ["data_drift_detected"],
        "severity": "high",
        "criteria": {"count": 1, "period_seconds": 3600},
        "reset_policy": "manual",
    },
    "DataDriftSuspected": {
        "description": "model monitoring suspects data drift",
        "trigger_events": ["data_drift_suspected"],
        "severity": "medium",
        "criteria": {"count": 3, "period_seconds": 3600},
        "reset_policy": "auto",
    },
    "SLOBurnRate": {
        "description": "an SLO is burning error budget on both the fast "
                       "and slow windows (obs/slo.py multi-window "
                       "burn-rate evaluation)",
        "trigger_events": ["slo_burn_rate"],
        "severity": "high",
        "criteria": {"count": 1, "period_seconds": 600},
        "reset_policy": "auto",
    },
    "SystemPerformance": {
        "description": "serving latency over threshold",
        "trigger_events": ["latency_high"],
        "severity": "medium",
        "criteria": {"count": 5, "period_seconds": 600},
        "reset_policy": "auto",
    },
}


def get_alert_template(name: str) -> dict:
    import copy

    template = ALERT_TEMPLATES.get(name)
    if template is None:
        raise KeyError(
            f"unknown alert template {name!r} "
            f"(available: {sorted(ALERT_TEMPLATES)})")
    # deep copy: nested criteria/trigger_events must not alias the
    # module-global registry (a caller mutation would corrupt every
    # later instantiation process-wide)
    return copy.deepcopy(template)


def list_alert_templates() -> list[dict]:
    import copy

    return [{"name": name, **copy.deepcopy(template)}
            for name, template in ALERT_TEMPLATES.items()]
