"""Chief/worker clusterization (reference analog: server/api/main.py:720-757
+ utils/clients/chief.py): worker replicas proxy mutating operations to the
chief and serve reads from the shared DB.

Role comes from ``MLT_CLUSTER_ROLE`` (chief|worker) and
``MLT_CHIEF_URL``; single-instance deployments are implicitly chief.
"""

from __future__ import annotations

import os
from typing import Optional

from ..utils import logger

MUTATING_METHODS = ("POST", "PATCH", "PUT", "DELETE")
# paths a worker may serve locally even when mutating (log append from
# local resources, run updates from in-process executions)
WORKER_ALLOWED_PREFIXES = ("logs",)


def cluster_role() -> str:
    return os.environ.get("MLT_CLUSTER_ROLE", "chief").lower()


def chief_url() -> str:
    return os.environ.get("MLT_CHIEF_URL", "").rstrip("/")


def is_chief() -> bool:
    return cluster_role() != "worker" or not chief_url()


async def maybe_proxy_to_chief(request, chief: bool | None = None
                               ) -> Optional["web.Response"]:
    """On a worker, forward mutating api calls to the chief; returns the
    proxied response, or None when the request should be handled locally.

    ``chief`` is the role captured at app build time — roles must not be
    re-read per request (a chief that later sees worker env would proxy to
    itself forever)."""
    from aiohttp import ClientSession, web

    chief = is_chief() if chief is None else chief
    if chief or request.method not in MUTATING_METHODS:
        return None
    tail = request.path.split("/api/v1/", 1)[-1]
    parts = tail.split("/")
    # projects/<p>/<kind>/... → kind at index 2; bare endpoints at 0
    kind = parts[2] if len(parts) > 2 and parts[0] == "projects" else parts[0]
    if kind in WORKER_ALLOWED_PREFIXES:
        return None
    target = f"{chief_url()}{request.path_qs}"
    body = await request.read()
    async with ClientSession() as session:
        async with session.request(
                request.method, target, data=body,
                headers={"Content-Type":
                         request.headers.get("Content-Type", "")}) as resp:
            payload = await resp.read()
            return web.Response(body=payload, status=resp.status,
                                content_type=resp.content_type)


def clusterization_middleware(chief: bool | None = None):
    from aiohttp import web

    chief = is_chief() if chief is None else chief

    @web.middleware
    async def middleware(request, handler):
        proxied = await maybe_proxy_to_chief(request, chief=chief)
        if proxied is not None:
            return proxied
        return await handler(request)

    return middleware
