"""Host-RAM KV tier under the device page pool (docs/serving.md
"Hierarchical KV").

The radix prefix cache (serving/prefix.py) lives inside ONE replica's
device page pool: pool pressure evicts refcount-0 pages outright and the
prefix is gone — the next request re-prefills it from tokens. At fleet
scale the hit rate is bounded by one pool's bytes, not by how hot the
prefix actually is. This module adds the missing level of the hierarchy:
a bounded-bytes host store of page payloads (int8 pages + scales, the
PR 15 storage format) keyed by ``block_chain_key`` chain nodes.

- **Demote** — ``PrefixCache.evict`` victims are copied host-side before
  their device page returns to the free list (serving/paged.py
  ``_reclaim_pages``, chaos ``llm.kv_demote``).
- **Promote** — an admission whose device-pool match stops short probes
  the tier for the next consecutive blocks and imports their pages back
  into freshly allocated pool pages instead of prefilling the suffix from
  tokens (``_prepare_admission``, chaos ``llm.kv_promote``).

Invariants (mirrors of the device-side prefix-cache contract):

- Ancestors outlive descendants: an entry whose child chain node is still
  resident can never evict, so a promote probe walking root-down over
  consecutive chain keys never finds a hole below a hit.
- Pinned entries (a promote in flight) never evict.
- Bounded bytes: ``put`` evicts unpinned childless entries LRU-first to
  fit; an entry larger than the whole budget is refused, never stored.

Pure host-side bookkeeping — numpy only, no jax imports. Thread-safe: the
scheduler thread demotes/promotes, but fetch handoffs assemble payloads
from tier-resident pages too, so a lock guards the index (entries' page
arrays are immutable by convention — writers store copies).
"""

from __future__ import annotations

import threading
from collections import OrderedDict


class _Entry:
    __slots__ = ("key", "parent_key", "pages", "nbytes", "pins")

    def __init__(self, key: int, parent_key: int | None, pages: dict,
                 nbytes: int):
        self.key = key
        self.parent_key = parent_key
        self.pages = pages
        self.nbytes = nbytes
        self.pins = 0


class HostKVTier:
    """Bounded-bytes host store of per-chain-node KV page payloads."""

    def __init__(self, capacity_bytes: int):
        if capacity_bytes <= 0:
            raise ValueError(
                f"capacity_bytes must be > 0, got {capacity_bytes}")
        self.capacity_bytes = int(capacity_bytes)
        self._lock = threading.Lock()
        # key -> _Entry, in LRU order (oldest first)
        self._entries: "OrderedDict[int, _Entry]" = OrderedDict()
        # parent chain key -> set of resident child keys. Children are
        # demoted leaf-first (before their parents), so a parent_key may
        # reference an entry that never arrives — tracked regardless, it
        # only matters once the parent IS resident.
        self._children: dict[int, set[int]] = {}
        self.bytes_used = 0
        # observability counters (surfaced through engine stats)
        self.demotes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def __contains__(self, key: int) -> bool:
        with self._lock:
            return key in self._entries

    @staticmethod
    def _payload_bytes(pages: dict) -> int:
        return sum(int(a.nbytes) for a in pages.values())

    def _evictable(self, entry: _Entry) -> bool:
        if entry.pins > 0:
            return False
        kids = self._children.get(entry.key)
        return not kids

    def _drop(self, entry: _Entry) -> None:
        del self._entries[entry.key]
        self.bytes_used -= entry.nbytes
        if entry.parent_key is not None:
            kids = self._children.get(entry.parent_key)
            if kids is not None:
                kids.discard(entry.key)
                if not kids:
                    del self._children[entry.parent_key]

    def put(self, key: int, parent_key: int | None, pages: dict) -> bool:
        """Store one chain node's page payload (``{name: ndarray}``,
        already host-side copies). Evicts unpinned childless entries
        LRU-first to fit. Returns False when the payload alone exceeds
        the budget or could not fit past pinned/parented residents —
        the demote is simply lost, never an error."""
        nbytes = self._payload_bytes(pages)
        if nbytes > self.capacity_bytes:
            return False
        with self._lock:
            prior = self._entries.get(key)
            if prior is not None:
                # refresh in place (same chain re-demoted)
                self.bytes_used -= prior.nbytes
                prior.pages = pages
                prior.nbytes = nbytes
                self.bytes_used += nbytes
                self._entries.move_to_end(key)
                self.demotes += 1
                return True
            while self.bytes_used + nbytes > self.capacity_bytes:
                victim = next(
                    (e for e in self._entries.values()
                     if self._evictable(e)), None)
                if victim is None:
                    return False
                self._drop(victim)
                self.evictions += 1
            entry = _Entry(key, parent_key, pages, nbytes)
            self._entries[key] = entry
            self.bytes_used += nbytes
            if parent_key is not None:
                self._children.setdefault(parent_key, set()).add(key)
            self.demotes += 1
            return True

    def get(self, key: int) -> dict | None:
        """Page payload for ``key`` (LRU-bumped) or None on miss."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                self.misses += 1
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            return entry.pages

    def peek(self, key: int) -> bool:
        """Residency probe without touching LRU order or counters."""
        with self._lock:
            return key in self._entries

    def pin(self, key: int) -> bool:
        """Hold ``key`` against eviction (a promote/fetch assembling its
        payload). Returns False when the entry is already gone."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                return False
            entry.pins += 1
            return True

    def unpin(self, key: int) -> None:
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None and entry.pins > 0:
                entry.pins -= 1

    def stats(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._entries),
                "bytes_used": self.bytes_used,
                "capacity_bytes": self.capacity_bytes,
                "demotes": self.demotes,
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
            }
