"""Serving pod fleet: autoscaler-driven JobSet elasticity with live
ring join, graceful drain, and pre-warmed replica bring-up
(docs/serving.md "Engine fleet", docs/fault_tolerance.md).

PR 8's :class:`~.fleet.EngineFleet` scales IN-PROCESS replicas, so a
real pod preemption or scale event was outside the fault model. This
module is the cross-process layer above it: one serving replica is one
single-slice JobSet (``k8s/jobset.build_serving_jobset``) whose pod
hosts one engine behind a :class:`PodReplicaClient` — the duck-typed
``submit``/``submit_prefill``/``submit_prefilled`` surface the fleet
already routes over, so the ring, the 503-class re-dispatch machinery
and the KV-handoff wire format all apply unchanged across the process
boundary.

The pod lifecycle is a deterministic state machine advanced one
transition per :meth:`ServingPodFleet.tick` (the autoscaler's clock —
no background threads, so chaos drills replay exactly):

    pending ──(pod Running)──▶ warming ──(pre-warm pass)──▶ ready
      ready ──(/readyz probe + ring join)──▶ joined
     joined ──(scale-down drain)──▶ draining ──(drained)──▶ deleted
     joined ──(pod 404: preemption)──▶ deleted (in-flight re-dispatched)

Pre-warm runs BEFORE the ring join, so the replica's first routed
request is already warm: the adapter working set replays from the
fleet's registered sources (one artifact fetch via the registry's host
cache, not N tenants' worth), the compile cache arrives via
``COMPILE_CACHE_ENV`` baked into the JobSet spec, and the hot prefix KV
is rebuilt by replaying the ring's REASSIGNED ``block_chain_key``s
(``EngineFleet.reassigned_hot_keys``) as background prefills over
:class:`~.llm_batch.KVHandoff` with ``register_prefix=True`` — the
joining engine indexes the imported pages, so the first real request on
a moved key is a prefix-cache hit.

Preemption is a steady-state input, not an exception: a joined pod
whose liveness read 404s has its in-flight requests failed with
:class:`~.resilience.ReplicaPreemptedError` carrying the decode state
as a KV handoff (exported during the grace window while the engine
still answers), so the fleet resumes them on survivors via
``submit_prefilled`` — no admitted request is dropped.

Everything here is host-side Python with no jax import at module level
(the engines behind the factory own the device); the k8s surface is the
provider seam, so the whole lifecycle runs against ``tests/fake_k8s``
without a cluster.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future

from ..chaos import FaultPoints, fire
from ..common.journal import open_journal
from ..config import mlconf
from ..k8s.jobset import build_serving_jobset
from ..obs import (
    FLEET_DISPATCHES,
    FLEET_POD_EVENTS,
    FLEET_POD_PHASE,
    FLEET_POD_PREWARM_SECONDS,
    JOURNAL_WRITES,
    RECONCILE_ACTIONS,
    RECONCILE_SECONDS,
)
from ..obs.flight import record as flight_record
from ..utils import logger
from .resilience import ReplicaPreemptedError, retry_after_hint

# state-machine phases, in lifecycle order (the gauge value)
_PHASES = {"pending": 0, "warming": 1, "ready": 2, "joined": 3,
           "draining": 4}

# bound on the per-request export/replay waits inside a tick — the
# lifecycle must never hang the autoscaler loop on one stuck future
_TICK_WAIT_S = 30.0

# journal snapshot op per live phase (the compacted record a restarted
# controller replays; phases left of "joined" re-enter conservatively)
_PHASE_OP = {"pending": "scale_up", "warming": "prewarm",
             "ready": "prewarm", "joined": "joined", "draining": "drain"}


def controller_crash(**context):
    """Entry point of the control-plane restart drill. Fires the
    declared ``fleet.controller_crash`` chaos point and stamps the
    flight recorder; the caller (a test or drill harness) then drops the
    fleet/autoscaler/tuning-controller objects WITHOUT graceful shutdown
    and constructs fresh ones over the same cluster + journal — recovery
    is asserted on the causal chain that follows
    (``fleet.crash → reconcile.adopt/orphan/resume → reconcile.converged``,
    docs/fault_tolerance.md "Control-plane crash recovery")."""
    flight_record("fleet.crash", **context)
    fire(FaultPoints.fleet_controller_crash, **context)


class PodReplicaClient:
    """The fleet-facing client for one pod-hosted engine.

    In production this is a ``RemoteStep``-backed HTTP client; here it
    wraps the in-pod engine directly behind the SAME duck-typed surface
    (``submit*`` returning Futures), which is exactly why the fleet
    cannot tell the difference. What it adds over the bare engine:

    - **liveness**: once :meth:`preempt` runs (pod gone), every new
      submit raises ``RemoteCallError(503)`` — the redispatchable class
      a dead pod's connection error maps to.
    - **in-flight tracking**: requests route through OUTER futures the
      client owns, so a preemption can fail them all promptly with
      :class:`ReplicaPreemptedError` — each carrying the decode state
      exported as a :class:`KVHandoff` during the grace window — instead
      of letting them hang to their timeouts.
    """

    def __init__(self, pod_name: str, engine):
        self.pod = pod_name
        self.replica = ""  # stamped by EngineReplica
        self._engine = engine
        self._dead = False
        self._lock = threading.Lock()
        self._inflight: dict[Future, dict] = {}

    # -- engine surface passthrough ------------------------------------------
    @property
    def page_size(self):
        return getattr(self._engine, "page_size", 64)

    @property
    def kv_dtype(self):
        return getattr(self._engine, "kv_dtype", "native")

    @property
    def stats(self):
        return self._engine.stats

    @property
    def _stopped(self) -> bool:
        # EngineReplica.healthy reads this duck attribute
        return self._dead or getattr(self._engine, "_stopped", False)

    @property
    def _slot_state(self):
        return getattr(self._engine, "_slot_state", ())

    def _queue_depth(self) -> int:
        return self._engine._queue_depth()

    def _free_page_frac(self):
        frac_fn = getattr(self._engine, "_free_page_frac", None)
        return frac_fn() if frac_fn else None

    def start(self):
        self._engine.start()

    def warmup(self):
        self._engine.warmup()

    def stop(self, timeout: float = 10.0):
        with self._lock:
            self._dead = True
        self._engine.stop()

    def add_adapter_source(self, name: str, source):
        self._engine.add_adapter_source(name, source)

    def retire_adapter(self, name: str, keep_source: bool = False):
        self._engine.retire_adapter(name, keep_source=keep_source)

    # -- dispatch ------------------------------------------------------------
    def _check_alive(self):
        if self._dead:
            from .remote import RemoteCallError

            raise RemoteCallError(
                f"pod {self.pod} is gone", status_code=503)

    def _track(self, req: dict, inner: Future) -> Future:
        outer: Future = Future()
        with self._lock:
            self._inflight[outer] = req
        inner.add_done_callback(lambda fut: self._relay(outer, fut))
        return outer

    def _relay(self, outer: Future, inner: Future):
        with self._lock:
            self._inflight.pop(outer, None)
        if outer.done():  # already failed by preempt()
            return
        exc = inner.exception()
        if exc is not None:
            outer.set_exception(exc)
        else:
            outer.set_result(inner.result())

    def submit(self, prompt_tokens, max_new_tokens: int = 64,
               eos_id=None, temperature: float = 0.0, top_k: int = 0,
               top_p: float = 1.0, max_wait=None, adapter: str = "",
               request_key=None, _trace=None) -> Future:
        self._check_alive()
        req = {"kind": "decode", "prompt": list(prompt_tokens),
               "adapter": adapter,
               "sampling": (temperature, top_k, top_p)}
        inner = self._engine.submit(
            prompt_tokens, max_new_tokens=max_new_tokens, eos_id=eos_id,
            temperature=temperature, top_k=top_k, top_p=top_p,
            max_wait=max_wait, adapter=adapter, request_key=request_key,
            _trace=_trace)
        return self._track(req, inner)

    def submit_prefill(self, prompt_tokens, eos_id=None,
                       temperature: float = 0.0, top_k: int = 0,
                       top_p: float = 1.0, max_wait=None,
                       adapter: str = "", request_key=None,
                       _trace=None) -> Future:
        self._check_alive()
        req = {"kind": "prefill", "prompt": list(prompt_tokens),
               "adapter": adapter,
               "sampling": (temperature, top_k, top_p)}
        inner = self._engine.submit_prefill(
            prompt_tokens, eos_id=eos_id, temperature=temperature,
            top_k=top_k, top_p=top_p, max_wait=max_wait, adapter=adapter,
            request_key=request_key, _trace=_trace)
        return self._track(req, inner)

    def submit_prefilled(self, handoff, max_new_tokens: int = 64,
                         eos_id=None, max_wait=None,
                         register_prefix: bool = False,
                         _trace=None) -> Future:
        self._check_alive()
        req = {"kind": "decode", "prompt": list(handoff.prompt),
               "adapter": handoff.adapter, "sampling": handoff.sampling}
        inner = self._engine.submit_prefilled(
            handoff, max_new_tokens=max_new_tokens, eos_id=eos_id,
            max_wait=max_wait, register_prefix=register_prefix,
            _trace=_trace)
        return self._track(req, inner)

    # -- cross-replica prefix fetch (docs/serving.md "Hierarchical KV") ------
    def fetch_prefix(self, prompt_tokens, adapter: str = "") -> Future:
        """Serve this pod's cached pages for a prompt as a page-payload
        handoff — the fetch SOURCE side. A control op, not a tracked
        request: the engine fails its own control futures on stop, so a
        preempted pod cannot strand the caller."""
        self._check_alive()
        return self._engine.fetch_prefix(prompt_tokens, adapter=adapter)

    def import_prefix(self, handoff) -> Future:
        """Index a fetched page payload into this pod's pool — the
        fetch TARGET side (pre-warm and the fleet's dispatch-time hop)."""
        self._check_alive()
        return self._engine.import_prefix(handoff)

    # -- preemption ----------------------------------------------------------
    def preempt(self, grace: bool = True) -> list[dict]:
        """The pod is going away NOW. Fail every in-flight outer future
        with :class:`ReplicaPreemptedError`; while the grace window
        lasts (``grace=True`` — the engine still answers locally), each
        decode's state is first re-exported as a KV handoff (a prefix
        HIT on this engine's own cache, so the export is one gather, not
        a re-prefill) and rides the error — the fleet resumes it on a
        survivor via ``submit_prefilled``. Returns the re-dispatched
        request records for flight/metric accounting."""
        with self._lock:
            self._dead = True
            inflight = list(self._inflight.items())
            self._inflight.clear()
        redispatched = []
        for outer, req in inflight:
            if outer.done():
                continue
            handoff = None
            if grace and req["kind"] == "decode":
                try:
                    temperature, top_k, top_p = req["sampling"]
                    handoff = self._engine.submit_prefill(
                        req["prompt"], temperature=temperature,
                        top_k=top_k, top_p=top_p,
                        adapter=req["adapter"]).result(
                        timeout=_TICK_WAIT_S)
                except Exception as exc:  # noqa: BLE001 - degrade to
                    # a handoff-less preemption (full re-dispatch)
                    logger.warning("preemption KV export failed",
                                   pod=self.pod, error=str(exc))
            outer.set_exception(ReplicaPreemptedError(
                f"pod {self.pod} preempted", handoff=handoff,
                retry_after_s=retry_after_hint()))
            redispatched.append(dict(req, handoff=handoff is not None))
        self._engine.stop()
        return redispatched


class ServingPodFleet:
    """Pod-level elasticity for an :class:`~.fleet.EngineFleet`.

    Owns the JobSet-per-replica lifecycle behind the provider seam
    (``KubernetesProvider`` — or the fake cluster in tests) and keeps
    the fleet's ring membership consistent with pod reality. The
    autoscaler drives it: ``scale_up``/``drain`` replace its direct
    ``fleet.add_replica``/``drain_replica`` calls, and ``tick`` advances
    every pod one lifecycle transition per autoscaler tick.

    ``engine_factory(role)`` builds the in-pod engine (in production
    the pod process builds it and the factory returns a RemoteStep
    client; the seam is identical either way).
    """

    def __init__(self, fleet, provider, engine_factory, *,
                 namespace: str | None = None,
                 accelerator: str | None = None,
                 topology: str = "1x1",
                 pod_spec: dict | None = None,
                 compile_cache_dir: str | None = None,
                 prewarm_max_keys: int = 32,
                 journal=None, reconcile_now: float = 0.0):
        self.fleet = fleet
        self.provider = provider
        self._factory = engine_factory
        self.namespace = namespace or getattr(
            provider, "namespace", None) or mlconf.namespace
        self.accelerator = accelerator or str(
            mlconf.tpu.default_accelerator)
        self.topology = topology
        self._pod_spec = pod_spec or {
            "containers": [{"name": "engine",
                            "image": str(mlconf.function.tpu_image)}]}
        self.compile_cache_dir = compile_cache_dir
        self.prewarm_max_keys = int(prewarm_max_keys)
        self._lock = threading.RLock()
        self._pods: dict[str, dict] = {}  # pod name -> record
        self._seq = 0
        # adapter working set replayed into every joining pod (the
        # registry host cache makes the N-th replay a local copy)
        self._adapter_sources: dict[str, object] = {}
        # durable intent journal + restart reconciliation (docs/
        # fault_tolerance.md "Control-plane crash recovery"); None =
        # journaling off (the default — zero behavior change)
        self._journal = journal if journal is not None else open_journal(
            "podfleet", snapshot=self._journal_snapshot)
        if self._journal is not None:
            self.reconcile(reconcile_now)

    # -- introspection -------------------------------------------------------
    def pods(self) -> dict[str, str]:
        with self._lock:
            return {name: rec["phase"]
                    for name, rec in self._pods.items()}

    def pending_count(self) -> int:
        """Pods on their way INTO the ring (pending/warming/ready) —
        capacity the autoscaler must count before scaling up again."""
        with self._lock:
            return sum(1 for rec in self._pods.values()
                       if rec["phase"] in ("pending", "warming", "ready"))

    def owns(self, replica_id: str) -> bool:
        with self._lock:
            return any(rec.get("rid") == replica_id
                       for rec in self._pods.values())

    def _by_rid(self, replica_id: str) -> dict | None:
        with self._lock:
            for rec in self._pods.values():
                if rec.get("rid") == replica_id:
                    return rec
        return None

    def _set_phase(self, rec: dict, phase: str):
        rec["phase"] = phase
        FLEET_POD_PHASE.set(_PHASES[phase], pod=rec["name"])

    def _event(self, rec: dict, event: str):
        FLEET_POD_EVENTS.inc(pod=rec["name"], event=event)

    # -- adapter working set -------------------------------------------------
    def add_adapter_source(self, name: str, source):
        """Register a tenant adapter fleet-wide AND remember it as part
        of the working set every joining pod pre-warms with."""
        with self._lock:
            self._adapter_sources[name] = source
        self.fleet.add_adapter_source(name, source)

    def retire_adapter(self, name: str, keep_source: bool = False):
        with self._lock:
            self._adapter_sources.pop(name, None)
        self.fleet.retire_adapter(name, keep_source=keep_source)

    # -- scale up ------------------------------------------------------------
    def scale_up(self, role: str = "unified", now: float = 0.0) -> str:
        """Submit one serving JobSet; the pod enters the lifecycle at
        ``pending`` and joins the ring only after pre-warm + readiness
        (ticks later). Returns the pod name."""
        with self._lock:
            self._seq += 1
            name = f"serve-{self.fleet._fleet_id}-{self._seq}"
        spec = build_serving_jobset(
            name, self.namespace, dict(self._pod_spec),
            accelerator=self.accelerator, topology=self.topology,
            compile_cache_dir=self.compile_cache_dir)
        pod_name = f"{name}-slice-0-0"
        rec = {"name": pod_name, "jobset": name,
               "resource_id": f"jobset/{name}", "role": role,
               "rid": None, "client": None, "prewarmed": False}
        # write-ahead: the intent lands in the journal BEFORE the
        # cluster call, so a crash in between still leaves a record
        # reconcile() can match against the (possibly created) JobSet
        self._journal_pod(rec, "scale_up")
        resource_id = self.provider.create(spec, run_uid=name)
        if resource_id != rec["resource_id"]:
            rec["resource_id"] = resource_id
            self._journal_pod(rec, "scale_up")
        with self._lock:
            self._pods[pod_name] = rec
        self._set_phase(rec, "pending")
        self._event(rec, "scale_up")
        flight_record("pod.scale_up", pod=pod_name, jobset=name,
                      role=role)
        logger.info("serving pod scale-up submitted", pod=pod_name,
                    jobset=name, role=role)
        return pod_name

    # -- scale down / drain --------------------------------------------------
    def drain(self, replica_id: str, now: float = 0.0):
        """Graceful scale-down entry: fire ``fleet.drain`` (production:
        POST ``/__drain__`` on the pod), pull the replica's ring points
        so NEW work routes elsewhere, and let in-flight work finish —
        the autoscaler's drain sweep calls :meth:`on_replica_removed`
        once load hits zero (or grace expires). If the drain endpoint is
        unreachable (injected ``fleet.drain`` error), escalate to the
        preemption path: the pod is deleted anyway, so in-flight work
        re-dispatches as handoffs instead of being stranded."""
        rec = self._by_rid(replica_id)
        if rec is None:
            raise KeyError(f"no pod backs replica '{replica_id}'")
        self._journal_pod(rec, "drain")
        try:
            fire(FaultPoints.fleet_drain, pod=rec["name"],
                 replica=replica_id)
        except Exception as exc:  # noqa: BLE001 - injected fault
            logger.warning("pod drain endpoint unreachable; escalating "
                           "to preemption re-dispatch", pod=rec["name"],
                           error=str(exc))
            self._preempt(rec)
            return
        self._set_phase(rec, "draining")
        self._event(rec, "drain")
        flight_record("pod.drain", pod=rec["name"], replica=replica_id)
        self.fleet.drain_replica(replica_id)

    def on_replica_removed(self, replica_id: str):
        """Autoscaler callback after ``fleet.remove_replica`` (drain
        complete): delete the pod's JobSet and retire its series."""
        rec = self._by_rid(replica_id)
        if rec is None:
            return
        self._journal_pod(rec, "delete")
        try:
            self.provider.delete(rec["resource_id"])
        except Exception as exc:  # noqa: BLE001 - already-gone is fine
            logger.warning("serving jobset delete failed",
                           jobset=rec["jobset"], error=str(exc))
        self._event(rec, "delete")
        flight_record("pod.delete", pod=rec["name"],
                      jobset=rec["jobset"])
        self._retire(rec)

    # -- lifecycle tick ------------------------------------------------------
    def tick(self, now: float = 0.0):
        """Advance every pod ONE lifecycle transition (deterministic —
        a chaos drill steps the exact same sequence every run), then
        probe joined pods for out-of-band preemption."""
        with self._lock:
            records = list(self._pods.values())
        for rec in records:
            phase = rec["phase"]
            try:
                if phase == "pending":
                    self._advance_pending(rec)
                elif phase == "warming":
                    self._advance_warming(rec)
                elif phase == "ready":
                    self._advance_ready(rec)
                elif phase in ("joined", "draining"):
                    self._check_liveness(rec)
            except Exception as exc:  # noqa: BLE001 - one pod's fault
                # must not stall the whole fleet's lifecycle
                logger.warning("pod lifecycle tick failed",
                               pod=rec["name"], phase=phase,
                               error=str(exc))

    def _advance_pending(self, rec: dict):
        phase = self._read_pod_phase(rec["name"])
        if phase is None:
            # the pod vanished before it ever ran (scheduler rejection,
            # early preemption) — nothing joined the ring yet, so just
            # clean up; the autoscaler's below-min repair resubmits
            logger.warning("pending serving pod vanished",
                           pod=rec["name"])
            self._event(rec, "kill")
            flight_record("pod.kill", pod=rec["name"], joined=False)
            self._journal_pod(rec, "delete")
            try:
                self.provider.delete(rec["resource_id"])
            except Exception:  # noqa: BLE001 - already gone
                pass
            self._retire(rec)
            return
        if phase != "Running":
            return  # still scheduling — try again next tick
        client = PodReplicaClient(rec["name"],
                                  self._factory(rec["role"]))
        rec["client"] = client
        # registered but OUT of the ring: visible to stats/prewarm,
        # taking no traffic until join_replica
        rec["rid"] = self.fleet.add_replica(
            rec["role"], engine=client, joined=False)
        self._set_phase(rec, "warming")
        self._journal_pod(rec, "prewarm")

    def _advance_warming(self, rec: dict):
        t0 = time.perf_counter()
        client = rec["client"]
        replayed = 0
        fetched = 0
        try:
            fire(FaultPoints.fleet_prewarm, pod=rec["name"],
                 replica=rec["rid"])
            with self._lock:
                sources = dict(self._adapter_sources)
            for name, source in sources.items():
                client.add_adapter_source(name, source)
            client.warmup()
            # seed the ring slice this replica will own, FETCH-first:
            # each reassigned hot key's pages are pulled straight out of
            # the CURRENT owner's pool (a page gather, no prefill
            # compute — docs/serving.md "Hierarchical KV") and imported
            # here; keys the owner no longer holds fall back to the
            # replay path (prefill on the owner, a prefix hit there,
            # imported via submit_prefilled with register_prefix=True).
            # [-0:] would be the WHOLE list — 0 must mean "replay none"
            keys = (self.fleet.reassigned_hot_keys(rec["rid"])
                    [-self.prewarm_max_keys:]
                    if self.prewarm_max_keys > 0 else [])
            for key, prompt, adapter in keys:
                payload = self._owner_fetch(key, prompt, adapter)
                if payload is not None:
                    try:
                        client.import_prefix(payload).result(
                            timeout=_TICK_WAIT_S)
                        fetched += 1
                        continue
                    except Exception as exc:  # noqa: BLE001 - replay
                        logger.warning("prewarm page import failed; "
                                       "replaying", pod=rec["name"],
                                       error=str(exc))
                handoff = self._owner_prefill(key, prompt, adapter)
                if handoff is None:
                    continue
                client.submit_prefilled(
                    handoff, max_new_tokens=1,
                    register_prefix=True).result(timeout=_TICK_WAIT_S)
                replayed += 1
            rec["prewarmed"] = True
        except Exception as exc:  # noqa: BLE001 - a failed pre-warm
            # joins COLD rather than stranding paid-for capacity
            logger.warning("pod pre-warm failed; will join cold",
                           pod=rec["name"], error=str(exc))
        wall = time.perf_counter() - t0
        FLEET_POD_PREWARM_SECONDS.observe(wall)
        self._event(rec, "prewarm")
        flight_record("pod.prewarm", pod=rec["name"],
                      replica=rec["rid"], replayed_keys=replayed,
                      fetched_keys=fetched,
                      warm=rec["prewarmed"], wall_s=wall)
        self._set_phase(rec, "ready")

    def _advance_ready(self, rec: dict):
        # production: GET /readyz — which gates on warmth
        # (serving/server.py), so "probe ok" == "engine warm". An
        # injected fleet.pod_ready error is a readiness flap: the pod
        # stays OUT of the ring and is re-probed next tick.
        try:
            fire(FaultPoints.fleet_pod_ready, pod=rec["name"],
                 replica=rec["rid"])
        except Exception as exc:  # noqa: BLE001 - injected flap
            self._event(rec, "ready_flap")
            logger.warning("pod readiness probe failed; staying out "
                           "of the ring", pod=rec["name"],
                           error=str(exc))
            return
        self._event(rec, "ready")
        # join: ~1/N of the keyspace moves to this (pre-warmed) replica
        self.fleet.join_replica(rec["rid"])
        self._set_phase(rec, "joined")
        self._event(rec, "join")
        self._journal_pod(rec, "joined")
        flight_record("pod.join", pod=rec["name"], replica=rec["rid"],
                      prewarmed=rec["prewarmed"])

    def _check_liveness(self, rec: dict):
        if self._read_pod_phase(rec["name"]) is not None:
            return
        self._preempt(rec)

    def _preempt(self, rec: dict):
        """The pod is gone (liveness 404) or its drain endpoint is
        unreachable: fail its in-flight work with handoff-carrying
        preemption errors (the fleet re-dispatches them), drop the
        replica from the ring, and clean up the JobSet."""
        self._event(rec, "kill")
        flight_record("pod.kill", pod=rec["name"], replica=rec["rid"],
                      joined=rec["phase"] in ("joined", "draining"))
        redispatched = rec["client"].preempt() if rec["client"] else []
        for req in redispatched:
            self._event(rec, "redispatch")
            flight_record("pod.redispatch", pod=rec["name"],
                          prompt_len=len(req["prompt"]),
                          handoff=req["handoff"])
        if rec["rid"] is not None:
            try:
                self.fleet.remove_replica(rec["rid"])
            except KeyError:
                pass  # the drain sweep already removed it
        self._journal_pod(rec, "delete")
        try:
            self.provider.delete(rec["resource_id"])
        except Exception:  # noqa: BLE001 - the JobSet record may have
            pass           # vanished with the pod
        self._event(rec, "delete")
        flight_record("pod.delete", pod=rec["name"],
                      jobset=rec["jobset"])
        self._retire(rec)

    # -- helpers -------------------------------------------------------------
    def _read_pod_phase(self, name: str) -> str | None:
        """One liveness/phase read through the provider's core API;
        None means the pod record is gone (404 — preempted)."""
        core = getattr(self.provider, "_core", None)
        if core is None:
            raise ValueError(
                "provider exposes no CoreV1 client for pod reads")
        try:
            pod = core.read_namespaced_pod(name, self.namespace)
        except Exception as exc:  # noqa: BLE001 - only 404 is "gone"
            if getattr(exc, "status", None) == 404:
                return None
            raise
        return pod.status.phase

    def _owner_fetch(self, key: int, prompt: list, adapter: str):
        """Pull one hot prompt's cached pages from its CURRENT ring
        owner as a page-payload handoff (docs/serving.md "Hierarchical
        KV") — the cheap pre-warm seed: a pool gather on the owner, no
        prefill compute. None when fetch is disabled, no owner speaks
        the protocol, or nobody holds the pages (the caller replays via
        :meth:`_owner_prefill` instead)."""
        fleet = self.fleet
        if not getattr(fleet, "_prefix_fetch", False):
            return None
        try:
            # an armed error models a dead fetch path (degrade to the
            # replay prefill); an armed delay models a slow pull
            fire(FaultPoints.llm_kv_fetch, key=key, target="prewarm")
        except Exception as exc:  # noqa: BLE001 - injected fault
            logger.warning("prewarm prefix fetch faulted; replaying",
                           key=key, error=str(exc))
            return None
        with fleet._lock:
            pool = dict(fleet._route_pool())
            order = fleet._ring.preference(key)
        for rid in order:
            replica = pool.get(rid)
            if replica is None or not replica.healthy:
                continue
            fetcher = getattr(replica.engine, "fetch_prefix", None)
            if fetcher is None:
                continue
            try:
                payload = fetcher(prompt, adapter=adapter).result(
                    timeout=_TICK_WAIT_S)
            except Exception as exc:  # noqa: BLE001 - next owner
                logger.warning("prewarm prefix fetch failed",
                               replica=rid, error=str(exc))
                continue
            if payload is not None:
                return payload
        return None

    def _owner_prefill(self, key: int, prompt: list, adapter: str):
        """Prefill one hot prompt on its CURRENT ring owner (a prefix
        hit there — the pages are already cached) and return the
        handoff; None when no owner could serve it."""
        fleet = self.fleet
        with fleet._lock:
            pool = dict(fleet._route_pool())
            order = fleet._ring.preference(key)
        for rid in order:
            replica = pool.get(rid)
            if replica is None or not replica.healthy:
                continue
            try:
                return replica.engine.submit_prefill(
                    prompt, adapter=adapter).result(timeout=_TICK_WAIT_S)
            except Exception as exc:  # noqa: BLE001 - next owner
                logger.warning("prewarm owner prefill failed",
                               replica=rid, error=str(exc))
        return None

    def _retire(self, rec: dict):
        """Zero leaked per-pod series: drop every label set this pod's
        lifecycle may have created (remove() is a no-op for label sets
        that never materialized)."""
        for event in ("scale_up", "prewarm", "ready", "ready_flap",
                      "join", "kill", "redispatch", "drain", "delete"):
            FLEET_POD_EVENTS.remove(pod=rec["name"], event=event)
        FLEET_POD_PHASE.remove(pod=rec["name"])
        with self._lock:
            self._pods.pop(rec["name"], None)

    # -- durable intent + crash recovery -------------------------------------
    def draining_rids(self) -> list[str]:
        """Replica ids currently mid-drain — the autoscaler re-derives
        its drain sweep from this, level-triggered, instead of trusting
        its own (possibly restarted-away) ``_draining`` dict."""
        with self._lock:
            return [rec["rid"] for rec in self._pods.values()
                    if rec["phase"] == "draining" and rec.get("rid")]

    def _journal_pod(self, rec: dict, op: str):
        if self._journal is None:
            return
        ok = self._journal.append(
            "pod", op=op, pod=rec["name"], jobset=rec["jobset"],
            resource_id=rec["resource_id"], role=rec["role"],
            rid=rec.get("rid"), prewarmed=bool(rec.get("prewarmed")))
        JOURNAL_WRITES.inc(journal="podfleet",
                           outcome="ok" if ok else "failed")

    def _journal_snapshot(self) -> list[dict]:
        """Compaction view: one full-state record per live pod (each
        append carries full state, so the latest record per pod IS the
        intent — deleted pods simply drop out)."""
        with self._lock:
            records = list(self._pods.values())
        return [{"kind": "pod", "op": _PHASE_OP[rec["phase"]],
                 "pod": rec["name"], "jobset": rec["jobset"],
                 "resource_id": rec["resource_id"], "role": rec["role"],
                 "rid": rec.get("rid"),
                 "prewarmed": bool(rec.get("prewarmed"))}
                for rec in records]

    def reconcile(self, now: float = 0.0) -> dict:
        """Converge journaled intent vs. the observed world, LEVEL-
        triggered (docs/fault_tolerance.md "Control-plane crash
        recovery"). Runs on construction whenever a journal is
        configured; idempotent afterwards.

        - **adopt**: a Running pod whose last intent was scale_up /
          prewarm / joined re-enters the state machine at the ``ready``
          probe phase (a still-scheduling pod re-enters at ``pending``);
          the normal tick re-probes and rejoins the ring via
          ``join_replica``.
        - **resume**: a pod mid-drain re-enters at ``draining`` with its
          ring points pulled again; the autoscaler's normal drain/delete
          sweep finishes the removal.
        - **orphan**: a JobSet whose intent already said ``delete`` is
          deleted now; a journaled pod with no world presence only has
          its stale series retired. Desired capacity is NEVER replayed
          from stale scale-ups — the autoscaler re-derives it from live
          signals and its below-min repair resubmits what is actually
          missing.
        """
        empty = {"adopted": [], "resumed": [], "orphaned": [],
                 "unknown": []}
        if self._journal is None:
            return empty
        lister = getattr(self.provider, "list_serving_jobsets", None)
        if lister is None:
            logger.warning("provider cannot list serving jobsets — "
                           "journal replayed but world not reconciled",
                           provider=type(self.provider).__name__)
            return empty
        t0 = time.perf_counter()
        intent: dict[str, dict] = {}
        for record in self._journal.replay():
            if record.get("kind") == "pod" and record.get("pod"):
                intent[record["pod"]] = record
        world = lister()
        adopted: list = []
        resumed: list = []
        orphaned: list = []
        unknown: list = []
        handled = set()
        with self._lock:
            known = set(self._pods)
        for pod, record in intent.items():
            handled.add(record.get("jobset"))
            if pod in known:
                continue  # already tracked — nothing crashed in between
            self._reconcile_pod(pod, record, world,
                                adopted, resumed, orphaned)
        for name in world:
            if name not in handled:
                # not ours (another fleet sharing the namespace) — a
                # level-triggered pass only acts on intent it owns
                unknown.append(name)
                RECONCILE_ACTIONS.inc(controller="podfleet",
                                      action="skip_unknown")
                logger.warning("serving jobset unknown to the intent "
                               "journal — left alone", jobset=name)
        wall = time.perf_counter() - t0
        RECONCILE_SECONDS.observe(wall)
        flight_record("reconcile.converged", controller="podfleet",
                      adopted=len(adopted), resumed=len(resumed),
                      orphaned=len(orphaned), unknown=len(unknown),
                      wall_s=wall)
        if intent:
            logger.info("pod fleet reconciled", adopted=len(adopted),
                        resumed=len(resumed), orphaned=len(orphaned),
                        unknown=len(unknown))
        self._journal.compact(self._journal_snapshot())
        return {"adopted": adopted, "resumed": resumed,
                "orphaned": orphaned, "unknown": unknown}

    def _reconcile_pod(self, pod: str, record: dict, world: dict,
                       adopted: list, resumed: list, orphaned: list):
        op = record.get("op", "scale_up")
        jobset = record.get("jobset", "")
        resource_id = record.get("resource_id", f"jobset/{jobset}")
        alive = jobset in world
        phase = self._read_pod_phase(pod) if alive else None
        if op == "delete" or phase is None \
                or (op == "drain" and phase != "Running"):
            # removal intent already decided, or the world moved on
            # (pod/JobSet gone) — finish the delete; capacity is NOT
            # resubmitted here, the autoscaler re-derives desired count
            if alive:
                try:
                    self.provider.delete(resource_id)
                except Exception:  # noqa: BLE001 - going away anyway
                    pass
            orphaned.append(pod)
            reason = "intent_deleted" if op == "delete" else "vanished"
            RECONCILE_ACTIONS.inc(
                controller="podfleet",
                action="orphan_deleted" if op == "delete"
                else "orphan_vanished")
            flight_record("reconcile.orphan", pod=pod, jobset=jobset,
                          reason=reason)
            self._retire_journaled(record)
            return
        rec = {"name": pod, "jobset": jobset,
               "resource_id": resource_id,
               "role": record.get("role") or "unified", "rid": None,
               "client": None,
               "prewarmed": bool(record.get("prewarmed"))}
        if phase != "Running":
            # still scheduling — re-enter at pending, the normal tick
            # advances it exactly like a fresh scale-up
            with self._lock:
                self._pods[pod] = rec
            self._set_phase(rec, "pending")
            adopted.append(pod)
            RECONCILE_ACTIONS.inc(controller="podfleet", action="adopt")
            flight_record("reconcile.adopt", pod=pod, phase="pending")
            self._retire_old_rid(record)
            self._journal_pod(rec, "scale_up")
            return
        client = PodReplicaClient(pod, self._factory(rec["role"]))
        rec["client"] = client
        # registered OUT of the ring, same as a fresh bring-up — the
        # re-probe (ready) or drain sweep decides what happens next
        rec["rid"] = self.fleet.add_replica(
            rec["role"], engine=client, joined=False)
        with self._lock:
            self._pods[pod] = rec
        if op == "drain":
            self.fleet.drain_replica(rec["rid"])
            self._set_phase(rec, "draining")
            resumed.append(pod)
            RECONCILE_ACTIONS.inc(controller="podfleet",
                                  action="resume_drain")
            flight_record("reconcile.resume", pod=pod,
                          replica=rec["rid"])
            self._journal_pod(rec, "drain")
        else:
            self._set_phase(rec, "ready")
            adopted.append(pod)
            RECONCILE_ACTIONS.inc(controller="podfleet", action="adopt")
            flight_record("reconcile.adopt", pod=pod,
                          replica=rec["rid"],
                          prewarmed=rec["prewarmed"])
            self._journal_pod(rec, "prewarm")
        self._retire_old_rid(record)

    def _retire_journaled(self, record: dict):
        """Series cleanup for a journaled pod that did not survive into
        this incarnation — the crash skipped the normal ``_retire``
        path, so its label sets would otherwise leak forever."""
        self._retire({"name": record.get("pod", "")})
        self._retire_old_rid(record)

    @staticmethod
    def _retire_old_rid(record: dict):
        """The previous incarnation's replica id is gone for good (ids
        are process-unique): drop its dispatch series."""
        rid = record.get("rid")
        if not rid:
            return
        for outcome in ("ok", "redispatch", "failed"):
            FLEET_DISPATCHES.remove(replica=rid, outcome=outcome)
