"""On-device token sampling for the LLM engines.

TPU-shaped sampling: one compiled program regardless of per-row settings.
Temperature / top-k / top-p are ARRAYS over the batch (per-slot in the
continuous-batching engine), so mixed greedy+sampled batches share a single
decode dispatch — no per-request recompilation, no host round-trips.

The usual trick for static shapes: top-k/top-p masks are applied inside a
fixed-size ``lax.top_k`` workspace (TOPK_WORKSPACE logits), then sampled
categorically and mapped back to vocab ids. Rows with no restriction
(top_k 0, top_p 1) sample the full vocabulary directly, and rows with
``temperature == 0`` take the argmax path via ``jnp.where`` — both are
exact, not workspace approximations.

No reference analog: the reference has no inference engine (its
V2ModelServer calls user predict(), mlrun/serving/v2_serving.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# every sampled distribution is truncated to this many candidates; large
# enough that top_p/top_k settings in practical ranges are exact
TOPK_WORKSPACE = 64


def sample_logits(logits: jax.Array, key: jax.Array,
                  temperature: jax.Array, top_k: jax.Array,
                  top_p: jax.Array) -> jax.Array:
    """Sample next tokens. logits [B, V]; temperature/top_k/top_p [B].

    - temperature 0 => greedy argmax for that row (exact)
    - top_k 0       => no top-k restriction (within the workspace)
    - top_p 1.0     => no nucleus restriction
    Returns int32 [B].
    """
    b, v = logits.shape
    temperature = jnp.asarray(temperature, jnp.float32).reshape(b)
    top_k = jnp.asarray(top_k, jnp.int32).reshape(b)
    top_p = jnp.asarray(top_p, jnp.float32).reshape(b)

    work = min(TOPK_WORKSPACE, v)
    top_logits, top_ids = jax.lax.top_k(logits.astype(jnp.float32), work)

    # top-k mask inside the (sorted-descending) workspace
    ranks = jnp.arange(work)[None, :]
    k_eff = jnp.where(top_k > 0, jnp.minimum(top_k, work), work)[:, None]
    masked = jnp.where(ranks < k_eff, top_logits, -jnp.inf)

    # nucleus: keep the smallest prefix with cumulative prob >= top_p
    # (always keep rank 0)
    safe_t = jnp.maximum(temperature, 1e-6)[:, None]
    probs = jax.nn.softmax(masked / safe_t, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep = (cum - probs) < top_p[:, None]  # prob mass BEFORE this rank
    keep = keep.at[:, 0].set(True)
    masked = jnp.where(keep, masked, -jnp.inf)

    keys = jax.random.split(key, b)
    choice = jax.vmap(
        lambda k_, l_, t_: jax.random.categorical(k_, l_ / jnp.maximum(
            t_, 1e-6)))(keys, masked, temperature)
    workspace_sampled = jnp.take_along_axis(
        top_ids, choice[:, None], axis=-1)[:, 0]
    # unrestricted rows (top_k==0, top_p>=1) sample the FULL vocabulary —
    # the workspace is only a device for applying top-k/top-p masks, and
    # truncating pure temperature sampling to it would silently zero the
    # tail's probability mass
    full_choice = jax.vmap(
        lambda k_, l_, t_: jax.random.categorical(
            k_, l_.astype(jnp.float32) / jnp.maximum(t_, 1e-6)))(
        keys, logits, temperature)
    restricted = (top_k > 0) | (top_p < 1.0)
    sampled = jnp.where(restricted, workspace_sampled, full_choice)
    greedy = jnp.argmax(logits, axis=-1)
    return jnp.where(temperature > 0, sampled, greedy).astype(jnp.int32)
