"""Paged KV cache for long-prompt serving (vLLM-style, TPU-shaped).

The dense continuous-batching cache reserves ``slots x max_len`` KV rows
even when most requests are short; a paged pool allocates KV in fixed-size
pages and maps each slot to pages through a page table, so the pool can be
sized for the EXPECTED total tokens, not slots x worst case — more
concurrent slots per chip at the same HBM.

TPU shaping (everything static under jit):
- pool:       [layers, n_pages + 1, page_size, kv_heads, head_dim] — the
  LAST physical page is a scratch page: writes for unmapped slots (-1 page
  ids) land there, so masked-out writes can never collide with a live
  page (scatter with duplicate indices has an undefined winner).
- page_table: [slots, pages_per_slot] int32 (page ids; -1 = unmapped)
- attention:  the kernel path reads the pool THROUGH the page table in
  place (decode: one token/slot; prefix-hit prefill: a suffix chunk over
  the cached pages, LSE-merged with the local flash — both in
  ops/paged_attention.py, int8 pools included via in-kernel dequant).
  The reference path gathers the slot's pages into a dense
  [slots, max_len] view per layer and runs the same masked attention as
  the dense engine — HBM-bandwidth work of the same order as
  attention's cache read, which is exactly what the kernels eliminate.
- page allocation/free is host-side bookkeeping in the scheduler thread
  (a free-list), exactly where the dense engine's slot bookkeeping lives.

Pages for prompt + max_new_tokens are reserved at admission, so decode can
never run out mid-generation (no preemption path needed).

Prefix-aware KV reuse (docs/serving.md "Prefill & prefix cache"): full
page-size blocks of each prompt are indexed in a radix trie
(serving/prefix.py) mapping block-chains to page ids with refcounts. On
admission the longest cached chain is shared read-only into the new
slot's page table (refcount++) and ONLY the uncached suffix is prefilled
— the dominant TTFT win on repeated-system-prompt traffic. Refcount-0
pages stay cached and are evicted LRU (leaf-first) when an allocation
needs them; eviction fires the ``llm.prefix_evict`` chaos point and the
evictable pool counts toward ``_free_page_frac`` so the PR 2 degradation
ladder sees reclaimable headroom, not just the raw free list.

No reference analog: the reference has no inference engine
(mlrun/serving/v2_serving.py calls user predict()).
"""

from __future__ import annotations

import functools
import queue
import time
from collections import deque
from concurrent.futures import Future

import jax
import jax.numpy as jnp
import numpy as np

from ..chaos import FaultPoints, fire
from ..config import mlconf
from ..models.llama import LlamaConfig
from ..obs import KV_TIER_BYTES, KV_TIER_EVENTS, KV_TIER_HITS
from ..utils import logger
from .kv_tier import HostKVTier
from .llm import _forward_with_cache, init_kv_cache
from .llm_batch import ContinuousBatchingEngine, KVHandoff, _Admission
from .prefix import PrefixCache, block_chain_key


def init_paged_pool(config: LlamaConfig, n_pages: int, page_size: int,
                    kv_dtype: str = "native") -> dict:
    """Page pool pytree with ``n_pages`` physical pages (callers that need
    a scratch page pass n_pages + 1 and keep the last id out of the free
    list). The int8 variant carries per-vector scales."""
    if kv_dtype not in ("native", "int8"):
        raise ValueError(f"unknown kv_dtype '{kv_dtype}' (native | int8)")
    shape = (config.n_layers, n_pages, page_size, config.n_kv_heads,
             config.head_dim)
    if kv_dtype == "int8":
        return {
            "k": jnp.zeros(shape, jnp.int8),
            "v": jnp.zeros(shape, jnp.int8),
            "k_scale": jnp.zeros(shape[:-1], jnp.float32),
            "v_scale": jnp.zeros(shape[:-1], jnp.float32),
        }
    return {
        "k": jnp.zeros(shape, config.dtype),
        "v": jnp.zeros(shape, config.dtype),
    }


def insert_prompt_pages(pool: dict, small: dict, page_ids: jax.Array,
                        page_size: int) -> dict:
    """Scatter a prefilled slot-cache (``small`` from init_kv_cache with
    batch=1, max_len a multiple of page_size) into the pool at
    ``page_ids`` ([pages_per_slot] int32). Ids < 0 write to the scratch
    page (last physical page) — never to a live one."""
    scratch = pool["k"].shape[1] - 1
    pages = page_ids.shape[0]

    def body(p, pool_):
        pid = page_ids[p]
        pid_safe = jnp.where(pid >= 0, pid, scratch)
        out = dict(pool_)
        for name in ("k", "v", "k_scale", "v_scale"):
            if name not in pool_:
                continue
            row = jax.lax.dynamic_slice_in_dim(
                small[name][:, 0], p * page_size, page_size, axis=1)
            out[name] = jax.lax.dynamic_update_index_in_dim(
                pool_[name], row.astype(pool_[name].dtype), pid_safe,
                axis=1)
        return out

    return jax.lax.fori_loop(0, pages, body, pool)


def gather_prefix_pages(pool: dict, small: dict, page_ids: jax.Array,
                        page_size: int) -> dict:
    """Inverse of :func:`insert_prompt_pages`: copy cached prefix pages
    from the pool into a batch=1 slot-cache (``small`` from
    init_kv_cache), so a suffix-only prefill can attend over the reused
    prefix KV without recomputing it. Ids < 0 leave the corresponding
    rows untouched (one compile covers every prefix length)."""
    pages = page_ids.shape[0]

    def body(p, small_):
        pid = page_ids[p]
        out = dict(small_)
        for name in ("k", "v", "k_scale", "v_scale"):
            if name not in pool or name not in small_:
                continue
            row = pool[name][:, jnp.maximum(pid, 0)]
            cur = jax.lax.dynamic_slice_in_dim(
                small_[name][:, 0], p * page_size, page_size, axis=1)
            row = jnp.where(pid >= 0, row.astype(small_[name].dtype), cur)
            out[name] = jax.lax.dynamic_update_slice_in_dim(
                small_[name][:, 0], row, p * page_size, axis=1)[:, None]
        return out

    return jax.lax.fori_loop(0, pages, body, small)


def _write_token_all_layers(pool: dict, k_tok, v_tok, page_table, pos,
                            page_size: int, scales=None) -> dict:
    """k_tok/v_tok: [L, slots, H, D]; write each slot's token into its
    current page at pos % page_size. Slots with an unmapped page (id < 0,
    e.g. inactive) write to the scratch page instead — duplicate scratch
    writes are harmless because the scratch page is never read."""
    scratch = pool["k"].shape[1] - 1
    page_idx = pos // page_size
    offset = pos % page_size
    pid = jnp.take_along_axis(page_table, page_idx[:, None], axis=1)[:, 0]
    pid_safe = jnp.where(pid >= 0, pid, scratch)

    out = dict(pool)
    rows = {"k": k_tok, "v": v_tok}
    if scales is not None:
        rows["k_scale"] = scales[0]
        rows["v_scale"] = scales[1]
    for name, row in rows.items():
        if name not in pool:
            continue
        out[name] = out[name].at[:, pid_safe, offset].set(
            row.astype(out[name].dtype))
    return out


def _decode_rowwise_paged(config: LlamaConfig, page_size: int,
                          attn_impl: str, params,
                          tokens: jax.Array, pool: dict,
                          page_table: jax.Array, pos: jax.Array,
                          rng: jax.Array = None,
                          temperature: jax.Array = None,
                          top_k: jax.Array = None, top_p: jax.Array = None,
                          lora=None, adapter_ids: jax.Array = None):
    """One decode token per slot against the page pool.

    ``attn_impl="reference"``: per layer, gather the slot's pages into a
    dense [slots, max_len] view, splice the just-computed token into the
    view for attention (it is only written to the pool once, for all
    layers, at the end), run the dense masked attention.

    ``attn_impl="kernel"``: per layer, scatter the token's KV into the
    pool FIRST (one [slots] page-table-routed write; int8 pools
    quantize per vector on the way in), then run the pallas
    paged-decode kernel which reads the pool THROUGH the page table —
    the dense view is never materialized, and on int8 pools the
    per-vector scales ride page-table-indexed operands with dequant
    in-register (ops/paged_attention.py). Both paths store and read
    identical bits at identical positions (int8 included — they share
    one _quantize_kv), so greedy decoding is token-identical between
    them.

    ``lora``/``adapter_ids`` add per-row multi-tenant LoRA exactly like
    the dense ``_decode_rowwise`` (docs/serving.md "Multi-tenant LoRA"):
    each slot gathers its own (A, B) bank factors by adapter slot index.

    tokens [slots, 1]; pos [slots] absolute positions.
    Returns (next_token, new_pool, new_pos).
    """
    from ..ops.norms import rms_norm
    from ..ops.paged_attention import paged_attention
    from ..ops.rotary import apply_rope, rope_table
    from .llm import _cached_attention, _lora_delta, _quantize_kv
    from .sampling import sample_logits

    b = tokens.shape[0]
    positions = pos[:, None]
    rows = jnp.arange(b)
    safe_table = jnp.maximum(page_table, 0)            # [slots, pages]
    x = params["embedding"][tokens].astype(config.dtype)
    cos, sin = rope_table(positions, config.head_dim, config.rope_theta)
    quantized = "k_scale" in pool
    use_kernel = attn_impl == "kernel"
    if use_kernel:
        scratch = pool["k"].shape[1] - 1
        page_idx = pos // page_size
        offset = pos % page_size
        pid = jnp.take_along_axis(page_table, page_idx[:, None],
                                  axis=1)[:, 0]
        pid_safe = jnp.where(pid >= 0, pid, scratch)
        pool = dict(pool)

    k_new, v_new = [], []
    for layer in range(config.n_layers):
        lp = jax.tree_util.tree_map(lambda a: a[layer], params["layers"])
        h = rms_norm(x, lp["attn_norm_scale"], config.norm_eps)

        def proj(h_in, w, t=None, _layer=layer):
            out = jnp.einsum("bse,eh->bsh", h_in, w,
                             preferred_element_type=jnp.float32)
            if lora is not None and t is not None and t in lora:
                out = out + _lora_delta(h_in, lora[t], _layer, adapter_ids)
            return out.astype(x.dtype)

        q = proj(h, lp["wq"], "wq").reshape(b, 1, config.n_heads,
                                            config.head_dim)
        k = proj(h, lp["wk"], "wk").reshape(b, 1, config.n_kv_heads,
                                            config.head_dim)
        v = proj(h, lp["wv"], "wv").reshape(b, 1, config.n_kv_heads,
                                            config.head_dim)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)

        if use_kernel:
            # token KV lands in the pool first (unmapped slots route to
            # the never-read scratch page), then the kernel attends
            # pool-side via the page table — no dense view, no gather.
            # int8 pools quantize the token per vector on the way in and
            # the kernel dequantizes in-register (scales ride
            # page-table-indexed operands)
            scales_kw = {}
            if quantized:
                kq_, ks_ = _quantize_kv(k[:, 0])
                vq_, vs_ = _quantize_kv(v[:, 0])
                pool["k"] = pool["k"].at[layer, pid_safe, offset].set(kq_)
                pool["v"] = pool["v"].at[layer, pid_safe, offset].set(vq_)
                pool["k_scale"] = pool["k_scale"].at[
                    layer, pid_safe, offset].set(ks_)
                pool["v_scale"] = pool["v_scale"].at[
                    layer, pid_safe, offset].set(vs_)
                scales_kw = {"k_scale": pool["k_scale"][layer],
                             "v_scale": pool["v_scale"][layer]}
            else:
                pool["k"] = pool["k"].at[layer, pid_safe, offset].set(
                    k[:, 0].astype(pool["k"].dtype))
                pool["v"] = pool["v"].at[layer, pid_safe, offset].set(
                    v[:, 0].astype(pool["v"].dtype))
            attn = paged_attention(
                q[:, 0], pool["k"][layer], pool["v"][layer], page_table,
                pos, page_size=page_size, impl="kernel",
                **scales_kw)[:, None]
        else:
            # dense per-layer view of this slot's pages (dequantized)
            kp = jnp.take(pool["k"][layer], safe_table, axis=0)
            vp = jnp.take(pool["v"][layer], safe_table, axis=0)
            s_, p_, ps_, hh, dd = kp.shape
            kd = kp.reshape(s_, p_ * ps_, hh, dd)
            vd = vp.reshape(s_, p_ * ps_, hh, dd)
            if quantized:
                ksc = jnp.take(pool["k_scale"][layer], safe_table,
                               axis=0).reshape(s_, p_ * ps_, hh)
                vsc = jnp.take(pool["v_scale"][layer], safe_table,
                               axis=0).reshape(s_, p_ * ps_, hh)
                kd = (kd.astype(jnp.float32) * ksc[..., None]).astype(
                    config.dtype)
                vd = (vd.astype(jnp.float32) * vsc[..., None]).astype(
                    config.dtype)
            else:
                kd = kd.astype(config.dtype)
                vd = vd.astype(config.dtype)
            # splice the new token into the dense view at each slot's
            # position
            kd = kd.at[rows, pos].set(k[:, 0])
            vd = vd.at[rows, pos].set(v[:, 0])
            attn = _cached_attention(config, q, kd, vd, positions,
                                     kd.shape[1])
            k_new.append(k[:, 0])
            v_new.append(v[:, 0])
        attn = attn.reshape(b, 1, config.qkv_dim)
        x_mid = x + proj(attn, lp["wo"], "wo")
        h2 = rms_norm(x_mid, lp["mlp_norm_scale"], config.norm_eps)
        gate = proj(h2, lp["w_gate"], "w_gate")
        up = proj(h2, lp["w_up"], "w_up")
        x = x_mid + proj(jax.nn.silu(gate) * up, lp["w_down"], "w_down")

    x = rms_norm(x, params["final_norm_scale"], config.norm_eps)
    head = params.get("lm_head")
    if head is None:
        head = params["embedding"].T
    logits = jnp.einsum("bse,ev->bsv", x, head,
                        preferred_element_type=jnp.float32)[:, 0]
    if rng is None:
        next_token = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    else:
        next_token = sample_logits(logits, rng, temperature, top_k, top_p)

    if use_kernel:
        # KV was written layer-by-layer before each attention call
        return next_token, pool, pos + 1

    # one pooled write for all layers: [L, slots, H, D]
    k_tok = jnp.stack(k_new)
    v_tok = jnp.stack(v_new)
    if quantized:
        kq, ks = _quantize_kv(k_tok)
        vq, vs = _quantize_kv(v_tok)
        new_pool = _write_token_all_layers(
            pool, kq, vq, page_table, pos, page_size, scales=(ks, vs))
    else:
        new_pool = _write_token_all_layers(
            pool, k_tok, v_tok, page_table, pos, page_size)
    return next_token, new_pool, pos + 1


def _verify_rowwise_paged(config: LlamaConfig, page_size: int,
                          attn_impl: str, params, chunk: jax.Array,
                          pool: dict, page_table: jax.Array,
                          pos: jax.Array, lora=None,
                          adapter_ids: jax.Array = None):
    """Batched multi-token speculative verify against the page pool
    (docs/serving.md "Speculative decoding"). ``chunk``: [slots, S] =
    each slot's committed last token plus its k draft proposals at
    absolute positions ``pos[r]..pos[r]+S-1``. ONE forward computes the
    target argmax at all S positions per slot.

    ``attn_impl="kernel"``: per layer, the chunk's KV scatters into the
    pool through the page table FIRST (int8 pools quantize per vector on
    the way in), then ``paged_verify_attention`` attends the prefix
    pages IN PLACE — the verify chunk is the prefill kernel's q-chunk
    form batched per slot, LSE-merged with the chunk's local causal
    part. No dense gather, no ``all_logits`` dense forward.

    ``attn_impl="reference"``: the gather+dense fallback
    (``paged_verify_reference``), bit-consistent with the reference
    decode path (raw chunk KV spliced into the dequantized view).

    Rollback is the host's ``_pos`` rewind: chunk writes land inside the
    slot's admission-reserved pages (``k_eff <= remaining`` keeps every
    accepted lane under the reservation; over-reservation lanes of rows
    speculating fewer than S-1 tokens route to the scratch page), and
    entries past the accepted position are overwritten before any later
    query can attend them — no page ever has to move back to the free
    list mid-round. ``pos`` is NOT advanced here; the host commits it.

    Returns (verified [slots, S] int32, new_pool).
    """
    from ..ops.norms import rms_norm
    from ..ops.paged_attention import paged_verify_attention
    from ..ops.rotary import apply_rope, rope_table
    from .llm import _dequantize_kv, _lora_delta, _quantize_kv

    b, s = chunk.shape
    pps = page_table.shape[1]
    positions = pos[:, None] + jnp.arange(s)[None, :]     # [slots, S]
    x = params["embedding"][chunk].astype(config.dtype)
    cos, sin = rope_table(positions, config.head_dim, config.rope_theta)
    quantized = "k_scale" in pool
    use_kernel = attn_impl == "kernel"
    scratch = pool["k"].shape[1] - 1
    page_idx = positions // page_size
    offset = positions % page_size
    pid = jnp.take_along_axis(page_table,
                              jnp.minimum(page_idx, pps - 1), axis=1)
    # lanes past the slot's mapped reservation (rows speculating fewer
    # than S-1 tokens this round) route to the never-read scratch page;
    # distinct in-reservation positions can never collide (one page id
    # per page index, one offset per position)
    pid_safe = jnp.where((pid >= 0) & (page_idx < pps), pid, scratch)
    pool = dict(pool)

    for layer in range(config.n_layers):
        lp = jax.tree_util.tree_map(lambda a: a[layer], params["layers"])
        h = rms_norm(x, lp["attn_norm_scale"], config.norm_eps)

        def proj(h_in, w, t=None, _layer=layer):
            out = jnp.einsum("bse,eh->bsh", h_in, w,
                             preferred_element_type=jnp.float32)
            if lora is not None and t is not None and t in lora:
                out = out + _lora_delta(h_in, lora[t], _layer, adapter_ids)
            return out.astype(x.dtype)

        q = proj(h, lp["wq"], "wq").reshape(b, s, config.n_heads,
                                            config.head_dim)
        k = proj(h, lp["wk"], "wk").reshape(b, s, config.n_kv_heads,
                                            config.head_dim)
        v = proj(h, lp["wv"], "wv").reshape(b, s, config.n_kv_heads,
                                            config.head_dim)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)

        scales_kw = {}
        if quantized:
            kq_, ks_ = _quantize_kv(k)
            vq_, vs_ = _quantize_kv(v)
            pool["k"] = pool["k"].at[layer, pid_safe, offset].set(kq_)
            pool["v"] = pool["v"].at[layer, pid_safe, offset].set(vq_)
            pool["k_scale"] = pool["k_scale"].at[
                layer, pid_safe, offset].set(ks_)
            pool["v_scale"] = pool["v_scale"].at[
                layer, pid_safe, offset].set(vs_)
            scales_kw = {"k_scale": pool["k_scale"][layer],
                         "v_scale": pool["v_scale"][layer]}
            if use_kernel:
                # the kernel's local chunk part must see the SAME bits a
                # later decode tick reads back from the int8 pool
                chunk_k = _dequantize_kv(kq_, ks_, config.dtype)
                chunk_v = _dequantize_kv(vq_, vs_, config.dtype)
            else:
                # reference decode splices the RAW token KV into its
                # dequantized view — the verify fallback matches it
                chunk_k, chunk_v = k, v
        else:
            pool["k"] = pool["k"].at[layer, pid_safe, offset].set(
                k.astype(pool["k"].dtype))
            pool["v"] = pool["v"].at[layer, pid_safe, offset].set(
                v.astype(pool["v"].dtype))
            chunk_k, chunk_v = k, v
        attn = paged_verify_attention(
            q, chunk_k, chunk_v, pool["k"][layer], pool["v"][layer],
            page_table, pos, page_size=page_size,
            impl="kernel" if use_kernel else "reference", **scales_kw)
        attn = attn.astype(x.dtype).reshape(b, s, config.qkv_dim)
        x_mid = x + proj(attn, lp["wo"], "wo")
        h2 = rms_norm(x_mid, lp["mlp_norm_scale"], config.norm_eps)
        gate = proj(h2, lp["w_gate"], "w_gate")
        up = proj(h2, lp["w_up"], "w_up")
        x = x_mid + proj(jax.nn.silu(gate) * up, lp["w_down"], "w_down")

    x = rms_norm(x, params["final_norm_scale"], config.norm_eps)
    head = params.get("lm_head")
    if head is None:
        head = params["embedding"].T
    logits = jnp.einsum("bse,ev->bsv", x, head,
                        preferred_element_type=jnp.float32)
    verified = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return verified, pool


class PagedContinuousBatchingEngine(ContinuousBatchingEngine):
    """Continuous batching over a paged KV pool.

    Same scheduler contract as ContinuousBatchingEngine (submit/generate/
    start/stop/warmup/stats), but slot KV lives in a shared page pool:
    ``n_pages`` defaults to the dense equivalent (slots x pages_per_slot);
    size it SMALLER to oversubscribe memory when typical prompt+generation
    lengths are below max_len. Pages for prompt+max_new are reserved at
    admission and requests wait (in order) until enough pages are free.
    """

    def __init__(self, config: LlamaConfig, params, max_len: int = 2048,
                 slots: int = 4, prefill_buckets: tuple = (128, 512, 1024),
                 seed: int = 0, kv_dtype: str = "native",
                 page_size: int = 128, n_pages: int | None = None,
                 max_queue_size: int = 0, max_wait: float = 0.0,
                 degradation: dict | None = None,
                 prefill_chunk: int | None = None,
                 latency_window: int | None = None,
                 prefix_cache: bool | None = None,
                 attention_impl: str | None = None,
                 adapters=None, max_live_adapters: int | None = None,
                 adapter_rate: float | None = None,
                 adapter_burst: float | None = None,
                 request_ledger: bool | None = None,
                 kv_tier=None, speculative: dict | None = None):
        from ..ops.paged_attention import resolve_paged_impl

        if max_len % page_size:
            raise ValueError(
                f"max_len {max_len} must be a multiple of page_size "
                f"{page_size} (a partial last page would misalign KV rows)")
        # set before super().__init__ — _make_cache runs during it
        self.page_size = page_size
        self.pages_per_slot = max_len // page_size
        self.n_pages = n_pages or slots * self.pages_per_slot
        # _pending exists before super().__init__ so _queue_depth /
        # pressure_level are safe during construction
        self._pending: deque = deque()
        if prefix_cache is None:
            prefix_cache = bool(mlconf.serving.llm.prefix_cache)
        self._prefix = PrefixCache(page_size) if prefix_cache else None
        # trie nodes each slot holds a refcount on (matched + registered)
        self._slot_prefix_nodes: dict[int, list] = {}
        # host-RAM KV tier (docs/serving.md "Hierarchical KV"): evicted
        # prefix chains demote host-side and promote back on admission.
        # ``kv_tier`` accepts True/False, a config-style dict, or None
        # (mlconf.serving.llm.kv_tier decides); needs the prefix cache
        conf = mlconf.serving.llm.get("kv_tier")
        tier_conf = dict(conf.to_dict()) if conf is not None else {}
        if isinstance(kv_tier, dict):
            # an explicit dict arg opts in unless it says otherwise
            tier_conf.update(kv_tier)
            kv_tier = kv_tier.get("enabled", True)
        elif kv_tier is None:
            kv_tier = tier_conf.get("enabled", False)
        self._kv_tier = (
            HostKVTier(int(tier_conf.get("host_bytes", 64 << 20)))
            if kv_tier and self._prefix is not None else None)
        # fetch_prefix/import_prefix control ops queue here and run on
        # the scheduler thread between ticks (_control_tick): the page
        # pool is donated through every decode dispatch, so off-thread
        # pool access is unsafe by construction
        self._control: deque = deque()
        super().__init__(config, params, max_len=max_len, slots=slots,
                         prefill_buckets=prefill_buckets, seed=seed,
                         kv_dtype=kv_dtype, max_queue_size=max_queue_size,
                         max_wait=max_wait, degradation=degradation,
                         prefill_chunk=prefill_chunk,
                         latency_window=latency_window,
                         attention_impl=attention_impl,
                         adapters=adapters,
                         max_live_adapters=max_live_adapters,
                         adapter_rate=adapter_rate,
                         adapter_burst=adapter_burst,
                         request_ledger=request_ledger,
                         speculative=speculative)
        # decode path: pallas paged kernel (page-table indexed) or the
        # gather+dense reference — resolved once, from the same knob the
        # base class resolved the prefill path from. int8 pools run the
        # SAME kernel (per-vector dequant scales ride page-table-indexed
        # operands); an explicit kernel request that cannot be honored
        # raised typed inside resolve_paged_impl.
        self.attn_impl = resolve_paged_impl(self.attention_impl)
        # prefix-hit suffix prefill: "kernel" attends the cached prefix
        # pages IN PLACE (multi-row paged prefill kernel LSE-merged with
        # the local flash over the suffix — docs/serving.md "Attention
        # kernels"); "gather" is the dense gather_prefix_pages seed
        # (reference/CPU fallback)
        self.paged_prefill_impl = (
            "kernel" if self.prefill_impl == "flash" else "gather")
        # +1 physical page: the scratch page for masked writes
        self._pool = init_paged_pool(config, self.n_pages + 1, page_size,
                                     kv_dtype)
        self._page_table = np.full((slots, self.pages_per_slot), -1,
                                   np.int32)
        self._pos = np.zeros((slots,), np.int32)
        self._free_pages: deque = deque(range(self.n_pages))
        self._slot_pages: dict[int, list] = {}
        # HBM bytes the gather path would copy per decode tick (the dense
        # k+v view of every slot, per layer) — what the kernel path avoids
        self._gather_bytes_per_tick = sum(
            arr.dtype.itemsize * config.n_layers * slots * max_len
            * int(np.prod(arr.shape[3:]))
            for name, arr in self._pool.items() if name in ("k", "v"))
        self._stats.update({"attn_kernel_ticks": 0, "attn_gather_ticks": 0,
                            "attn_hbm_bytes_avoided": 0,
                            "prefill_kernel_chunks": 0,
                            "prefill_gather_admissions": 0,
                            "kv_demotes": 0, "kv_demoted_pages": 0,
                            "kv_promotes": 0, "kv_promoted_pages": 0,
                            "kv_fetches": 0, "kv_fetched_pages": 0,
                            "kv_imports": 0, "kv_imported_pages": 0})
        # the paged engine's prefill carries the pool page size so a
        # prefix-hit dispatch can attend pool pages in place
        # (prefix_kv= — see _prefill_dispatch)
        self._prefill = jax.jit(functools.partial(
            _forward_with_cache, config, attn_impl=self.prefill_impl,
            page_size=page_size))
        self._decode_paged = jax.jit(
            functools.partial(_decode_rowwise_paged, config, page_size,
                              self.attn_impl),
            donate_argnums=(2,))
        self._insert_paged = jax.jit(
            functools.partial(insert_prompt_pages, page_size=page_size),
            donate_argnums=(0,))
        self._gather_paged = jax.jit(
            functools.partial(gather_prefix_pages, page_size=page_size),
            donate_argnums=(1,))

    def _make_cache(self):
        return None  # slot KV lives in the page pool

    def warmup(self):
        started = time.perf_counter()
        ids = jnp.full((self.pages_per_slot,), -1, jnp.int32)
        prefill_kw = self._lora_kwargs(0)
        decode_kw = self._lora_kwargs()
        for bucket in self.prefill_buckets:
            small = init_kv_cache(self.config, 1, self.max_len,
                                  kv_dtype=self.kv_dtype)
            _, small = self._prefill(
                self.params, jnp.zeros((1, bucket), jnp.int32), small,
                **prefill_kw)
            _, small = self._prefill(
                self.params, jnp.zeros((1, 1), jnp.int32), small,
                **prefill_kw)
            self._pool = self._insert_paged(self._pool, small, ids)
        if self.prefill_chunk and self.prefill_chunk not in \
                self.prefill_buckets:
            small = init_kv_cache(self.config, 1, self.max_len,
                                  kv_dtype=self.kv_dtype)
            self._prefill(self.params,
                          jnp.zeros((1, self.prefill_chunk), jnp.int32),
                          small, **prefill_kw)
        if self._prefix is not None:
            if self.paged_prefill_impl == "kernel":
                # compile the merged prefix-hit prefill programs (every
                # bucket/chunk shape + the 1-token replay) — the first
                # cache hit must not pay the compile. All-(-1) ids route
                # to the never-read scratch page; outputs are discarded
                ids = jnp.full((self.pages_per_slot,), -1, jnp.int32)
                prefix_kv = {"k": self._pool["k"], "v": self._pool["v"],
                             "page_ids": ids,
                             "base": jnp.int32(self.page_size)}
                if "k_scale" in self._pool:
                    prefix_kv["k_scale"] = self._pool["k_scale"]
                    prefix_kv["v_scale"] = self._pool["v_scale"]
                shapes = set(self.prefill_buckets) | {1}
                if self.prefill_chunk:
                    shapes.add(self.prefill_chunk)
                for shape in sorted(shapes):
                    small = init_kv_cache(self.config, 1, self.max_len,
                                          kv_dtype=self.kv_dtype)
                    self._prefill(self.params,
                                  jnp.zeros((1, shape), jnp.int32),
                                  small, prefix_kv=prefix_kv,
                                  **prefill_kw)
            else:
                # compile the prefix-page gather (first cache hit must
                # not pay the compile); all-(-1) ids touch no live page
                small = init_kv_cache(self.config, 1, self.max_len,
                                      kv_dtype=self.kv_dtype)
                self._gather_paged(
                    self._pool, small,
                    jnp.full((self.pages_per_slot,), -1, jnp.int32))
        step = jnp.zeros((self.slots, 1), jnp.int32)
        table = jnp.asarray(self._page_table)
        pos = jnp.asarray(self._pos)
        tok, self._pool, _ = self._decode_paged(
            self.params, step, self._pool, table, pos, **decode_kw)
        float(jnp.sum(tok))  # host fetch = real sync on the relay
        tok, self._pool, _ = self._decode_paged(
            self.params, step, self._pool, table, pos,
            jax.random.PRNGKey(0),
            jnp.zeros((self.slots,), jnp.float32),
            jnp.zeros((self.slots,), jnp.int32),
            jnp.ones((self.slots,), jnp.float32), **decode_kw)
        float(jnp.sum(tok))
        self._spec_warmup()
        logger.info("paged engine warm", slots=self.slots,
                    pages=self.n_pages, page_size=self.page_size,
                    warmup_s=round(time.perf_counter() - started, 2))

    def _spec_warmup_verify(self):
        # all-(-1) table routes every chunk write to the scratch page
        # and marks zero pages live; outputs are discarded. Called
        # directly (not via _spec_verify_dispatch) so warmup doesn't
        # count attention ticks.
        chunk = jnp.zeros((self.slots, self.spec_k + 1), jnp.int32)
        table = jnp.full((self.slots, self.pages_per_slot), -1, jnp.int32)
        pos = jnp.zeros((self.slots,), jnp.int32)
        lora_kw = self._lora_kwargs(self._slot_adapter_ids()) \
            if self._adapters is not None else {}
        _, self._pool = self._spec_verify_fn()(
            self.params, chunk, self._pool, table, pos, **lora_kw)

    # -- resilience: page-pool pressure + pending-deque expiry ---------------
    def _free_page_frac(self) -> float:
        """KV-page headroom — the degradation ladder degrades (speculative
        off, max_new clamp) before admission would start blocking on an
        exhausted pool. Refcount-0 cached prefix pages are reclaimable on
        demand, so they count as headroom."""
        if not self.n_pages:
            return 1.0
        free = len(self._free_pages)
        if self._prefix is not None:
            free += self._prefix.evictable_pages()
        return free / self.n_pages

    def _queue_depth(self) -> int:
        return self._queue.qsize() + len(self._pending)

    def _expire_queued(self):
        super()._expire_queued()
        # head-of-line requests parked waiting for pages also carry a
        # queue-time budget
        while self._pending and self._request_expired(
                self._pending[0][4], self._pending[0][5],
                self._pending[0][7]):
            self._pending.popleft()

    # -- admission: page reservation + prefix reuse -------------------------
    def _reclaim_pages(self, needed: int):
        """Evict LRU refcount-0 cached prefix pages until the free list
        covers ``needed`` pages. Fires the ``llm.prefix_evict`` chaos
        point per evicted page. With the host KV tier enabled each
        victim demotes host-side first (docs/serving.md "Hierarchical
        KV") — a failed demote loses the chain to the tier but never
        blocks the reclaim."""
        if self._prefix is None or len(self._free_pages) >= needed:
            return
        tier = self._kv_tier
        # _Node doesn't know its adapter — recover it from which
        # per-adapter root the victim's chain hangs off (one map per
        # reclaim, not per victim)
        root_adapters = {id(root): name for name, root
                         in self._prefix._roots.items()} \
            if tier is not None else None

        def on_evict(node):
            fire(FaultPoints.llm_prefix_evict, page_id=node.page_id,
                 refcount=node.refcount, last_used=node.last_used)
            if tier is None:
                return
            try:
                self._demote_node(node, root_adapters)
            except Exception:  # noqa: BLE001 - demote is best-effort:
                # the page is reclaimed either way, the chain is simply
                # lost to the tier
                with self._lock:
                    self._stats["kv_demotes"] += 1
                KV_TIER_EVENTS.inc(engine=self._obs_name,
                                   replica=self.replica, op="demote",
                                   outcome="error")

        freed = self._prefix.evict(needed - len(self._free_pages),
                                   on_evict)
        self._free_pages.extend(freed)

    def _demote_node(self, node, root_adapters: dict):
        """Copy one eviction victim's page host-side into the KV tier,
        keyed by its block-chain identity (chaos ``llm.kv_demote``).
        Eviction is leaf-first, so a chain demotes child-before-parent;
        the tier's ancestors-outlive-descendants eviction keeps promote
        probes hole-free regardless."""
        blocks = []
        cur = node
        while cur.parent is not None:
            blocks.append(cur.block)
            cur = cur.parent
        adapter = root_adapters.get(id(cur), "")
        blocks.reverse()
        flat = [t for block in blocks for t in block]
        key = block_chain_key(flat, self.page_size, adapter=adapter)
        parent_key = block_chain_key(
            flat[:-self.page_size], self.page_size, adapter=adapter) \
            if len(blocks) > 1 else None
        fire(FaultPoints.llm_kv_demote, key=key, page_id=node.page_id,
             blocks=len(blocks), adapter=adapter)
        pages = {name: np.asarray(self._pool[name][:, node.page_id])
                 for name in self._pool}
        stored = self._kv_tier.put(key, parent_key, pages)
        with self._lock:
            self._stats["kv_demotes"] += 1
            if stored:
                self._stats["kv_demoted_pages"] += 1
        KV_TIER_EVENTS.inc(engine=self._obs_name, replica=self.replica,
                           op="demote",
                           outcome="ok" if stored else "fallback")
        KV_TIER_BYTES.set(self._kv_tier.bytes_used,
                          engine=self._obs_name, replica=self.replica)

    def _tier_probe(self, prompt, adapter: str, k: int) -> list:
        """Consecutive host-tier payloads for the blocks just past the
        first ``k`` device-matched ones, probed root-down and stopped at
        the first miss (the tier's ancestors-outlive-descendants
        invariant makes deeper probes pointless). Same cap as
        ``PrefixCache.match``: at least one suffix token always remains
        to prefill."""
        limit = max(0, (len(prompt) - 1) // self.page_size)
        hits: list = []
        for i in range(k, limit):
            payload = self._kv_tier.get(block_chain_key(
                prompt[:(i + 1) * self.page_size], self.page_size,
                adapter=adapter))
            if payload is None:
                break
            hits.append(payload)
        return hits

    def _tier_import(self, hits: list, ids, k: int) -> int:
        """Write probed host-tier payloads into the admission's already
        reserved fresh pages — the ``gather_prefix_pages``-inverse
        import: host rows land at the pool pages the slot's page table
        already points at, bit-identical to what was demoted (chaos
        ``llm.kv_promote``). Returns the number of promoted blocks."""
        fire(FaultPoints.llm_kv_promote, blocks=len(hits), base_blocks=k)
        pids = jnp.asarray(np.asarray(ids[k:k + len(hits)], np.int32))
        for name in self._pool:
            rows = jnp.asarray(np.stack([h[name] for h in hits], axis=1))
            self._pool[name] = self._pool[name].at[:, pids].set(
                rows.astype(self._pool[name].dtype))
        with self._lock:
            self._stats["kv_promotes"] += 1
            self._stats["kv_promoted_pages"] += len(hits)
        KV_TIER_EVENTS.inc(engine=self._obs_name, replica=self.replica,
                           op="promote", outcome="ok")
        KV_TIER_HITS.inc(len(hits), engine=self._obs_name,
                         replica=self.replica, tier="host")
        return len(hits)

    # -- hierarchical KV: cross-replica page fetch ---------------------------
    def fetch_prefix(self, prompt_tokens, adapter: str = "") -> Future:
        """Assemble this engine's cached KV for ``prompt_tokens``'s
        leading full blocks into a prefix-only :class:`KVHandoff`
        (device pages first, extended through the host tier) — the wire
        payload a reassigned key's new ring owner imports via
        :meth:`import_prefix` instead of re-prefilling (docs/serving.md
        "Hierarchical KV"). Resolves to None when nothing is cached.
        The op runs on the scheduler thread between ticks
        (``_control_tick``): the page pool is donated through every
        decode dispatch, so off-thread pool reads are unsafe."""
        future: Future = Future()
        self._control.append(("fetch", (list(prompt_tokens), adapter),
                              future))
        if not self._running:
            self.start()
        return future

    def import_prefix(self, handoff: KVHandoff) -> Future:
        """Import a :meth:`fetch_prefix` payload's full blocks into the
        page pool + prefix index without admitting a request — the
        receiving side of the fetch hop. Resolves to the number of newly
        cached pages (0 = already cached, or no pages free)."""
        expects_scales = self.kv_dtype == "int8"
        wire_dtype = getattr(handoff, "kv_dtype", None) or (
            "int8" if "k_scale" in handoff.kv else "native")
        if wire_dtype != self.kv_dtype or \
                ("k_scale" in handoff.kv) != expects_scales:
            raise ValueError(
                f"KV handoff dtype mismatch: engine kv_dtype="
                f"'{self.kv_dtype}' cannot import a '{wire_dtype}' "
                f"payload — fetch and import pools must quantize alike "
                f"(docs/serving.md 'Engine fleet')")
        future: Future = Future()
        self._control.append(("import", (handoff,), future))
        if not self._running:
            self.start()
        return future

    def _control_tick(self):
        while self._control:
            kind, args, future = self._control.popleft()
            if future.done():
                continue
            try:
                if kind == "fetch":
                    future.set_result(self._do_fetch_prefix(*args))
                else:
                    future.set_result(self._do_import_prefix(*args))
            except Exception as exc:  # noqa: BLE001 - a control op must
                # fail its own future, never the scheduler
                future.set_exception(exc)

    def _do_fetch_prefix(self, prompt, adapter: str):
        if self._prefix is None:
            return None
        matched_pages, nodes = self._prefix.match(prompt, adapter=adapter)
        k = len(matched_pages)
        try:
            kv: dict = {}
            if k:
                pids = np.asarray(matched_pages, np.int64)
                for name in self._pool:
                    rows = np.asarray(self._pool[name][:, pids])
                    kv[name] = rows.reshape(
                        rows.shape[0], k * self.page_size,
                        *rows.shape[3:])
            tier_rows = [] if self._kv_tier is None \
                else self._tier_probe(prompt, adapter, k)
            if tier_rows:
                for name in self._pool:
                    stacked = np.stack([h[name] for h in tier_rows],
                                       axis=1)
                    rows = stacked.reshape(
                        stacked.shape[0],
                        len(tier_rows) * self.page_size,
                        *stacked.shape[3:])
                    kv[name] = np.concatenate([kv[name], rows], axis=1) \
                        if name in kv else rows
        finally:
            self._prefix.release(nodes)
        total = k + len(tier_rows)
        if not total:
            KV_TIER_EVENTS.inc(engine=self._obs_name,
                               replica=self.replica, op="fetch",
                               outcome="miss")
            return None
        rows_tok = total * self.page_size
        handoff = KVHandoff(
            prompt=list(prompt[:rows_tok]), first_token=-1, kv=kv,
            prompt_len=rows_tok, kv_dtype=self.kv_dtype,
            cached_prefix=rows_tok, replica=self.replica,
            adapter=adapter, prewarm=True)
        with self._lock:
            self._stats["kv_fetches"] += 1
            self._stats["kv_fetched_pages"] += total
        KV_TIER_EVENTS.inc(engine=self._obs_name, replica=self.replica,
                           op="fetch", outcome="ok")
        return handoff

    def _do_import_prefix(self, handoff: KVHandoff) -> int:
        if self._prefix is None:
            return 0
        prompt = list(handoff.prompt)
        full = min(len(prompt), handoff.prompt_len) // self.page_size
        full = min(full, self.pages_per_slot)
        if full <= 0:
            return 0
        adapter = handoff.adapter
        # a fetch payload is EXACTLY full blocks; match() always leaves
        # one suffix token unmatched, so probe with a sentinel token to
        # see every already-cached block (the sentinel is never indexed)
        _, nodes = self._prefix.match(prompt + [0], adapter=adapter)
        k = len(nodes)
        fresh: list = []
        try:
            need = full - k
            if need > 0:
                self._reclaim_pages(need)
            if need > len(self._free_pages):
                # partial import stays contiguous root-down, so the
                # chain invariant holds for whatever fits
                need = len(self._free_pages)
                full = k + need
            if need <= 0:
                return 0
            fresh = [self._free_pages.popleft() for _ in range(need)]
            ids = np.full((self.pages_per_slot,), -1, np.int32)
            ids[k:full] = fresh
            pids = jnp.asarray(np.asarray(fresh, np.int32))
            for name in self._pool:
                payload = np.asarray(handoff.kv[name][
                    :, k * self.page_size:full * self.page_size])
                payload = payload.reshape(
                    payload.shape[0], need, self.page_size,
                    *payload.shape[2:])
                self._pool[name] = self._pool[name].at[:, pids].set(
                    jnp.asarray(payload).astype(self._pool[name].dtype))
            new_nodes, claimed = self._prefix.register(
                prompt[:full * self.page_size], ids, nodes,
                adapter=adapter)
            claimed_set = set(claimed)
            self._free_pages.extend(
                p for p in fresh if p not in claimed_set)
            fresh = []
            nodes = nodes + new_nodes
            with self._lock:
                self._stats["kv_imports"] += 1
                self._stats["kv_imported_pages"] += len(claimed)
            KV_TIER_HITS.inc(len(claimed), engine=self._obs_name,
                             replica=self.replica, tier="remote")
            return len(claimed)
        except Exception:
            self._free_pages.extend(fresh)
            raise
        finally:
            self._prefix.release(nodes)

    def _remove_kv_tier_series(self):
        """Drop this engine's hierarchical-KV series on stop — the same
        series-lifecycle contract the stats-mirror families follow
        (scale-down must not leak series)."""
        labels = {"engine": self._obs_name, "replica": self.replica}
        KV_TIER_BYTES.remove(**labels)
        for tier in ("device", "host", "remote"):
            KV_TIER_HITS.remove(tier=tier, **labels)
        for op in ("demote", "promote", "fetch"):
            for outcome in ("ok", "miss", "fallback", "error"):
                KV_TIER_EVENTS.remove(op=op, outcome=outcome, **labels)

    def _unregister_metrics(self):
        super()._unregister_metrics()
        self._remove_kv_tier_series()

    def _prepare_admission(self) -> _Admission | None:
        free = next((i for i, s in enumerate(self._slot_state)
                     if not s.active), None)
        if free is None:
            return None
        while True:
            if not self._pending:
                try:
                    item = self._queue.get_nowait()
                except queue.Empty:
                    return None
                # the item left the admission queue; the head-of-line
                # sweep in _expire_queued tracks it from here
                self._consume_budget(item[7])
                self._pending.append(item)
            item = self._pending[0]
            if not self._validate_item(item):
                self._pending.popleft()
                continue
            (request_id, prompt, max_new, eos_id, future, submitted,
             sampling, expires) = item[:8]
            extra = item[9] if len(item) > 9 else None
            adapter = item[10] if len(item) > 10 else ""
            ledger = item[11] if len(item) > 11 else None
            prompt_len = len(prompt)
            needed = -(-(prompt_len + max_new) // self.page_size)
            if needed > self.n_pages:
                # would never fit — fail fast instead of blocking the
                # queue head forever
                self._pending.popleft()
                future.set_exception(ValueError(
                    f"request needs {needed} pages but the pool has only "
                    f"{self.n_pages}; raise n_pages or lower "
                    f"max_new_tokens"))
                continue
            matched_pages: list = []
            matched_nodes: list = []
            # an imported handoff arrives with its full prompt KV — a
            # local prefix match would only re-gather what the payload
            # already carries, so imports always take fresh pages.
            # Matching is per ADAPTER root: KV computed under adapter A
            # is never served to adapter B (same-tenant hits still
            # share — docs/serving.md "Multi-tenant LoRA")
            if self._prefix is not None and not isinstance(extra, KVHandoff):
                matched_pages, matched_nodes = self._prefix.match(
                    prompt, adapter=adapter)
            k = len(matched_pages)
            fresh_needed = needed - k
            available = len(self._free_pages)
            if self._prefix is not None:
                available += self._prefix.evictable_pages()
            if available < fresh_needed:
                # head-of-line waits for pages (in order); drop the match
                # holds so the cached prefix stays evictable meanwhile —
                # the parked time keeps charging queue_wait on the
                # ledger (the request is still waiting, not being served)
                if self._prefix is not None:
                    self._prefix.release(matched_nodes)
                return None
            if ledger is not None and adapter:
                ledger.enter("adapter_load_wait")
            adapter_slot = self._resolve_adapter(adapter, future)
            if adapter_slot is None:
                # adapter load failed — request failed typed; release
                # the match holds and move on
                if self._prefix is not None:
                    self._prefix.release(matched_nodes)
                self._pending.popleft()
                continue
            if ledger is not None:
                # claimed for good: page reservation + prefix gather
                # below are admission work
                ledger.enter("admission")
            self._pending.popleft()
            fresh: list = []
            try:
                if self._prefix is not None \
                        and not isinstance(extra, KVHandoff):
                    self._prefix.queries += 1
                    if k:
                        self._prefix.hits += 1
                        self._prefix.cached_tokens += k * self.page_size
                        if self._kv_tier is not None:
                            KV_TIER_HITS.inc(
                                k, engine=self._obs_name,
                                replica=self.replica, tier="device")
                self._reclaim_pages(fresh_needed)
                fresh = [self._free_pages.popleft()
                         for _ in range(fresh_needed)]
                ids = np.full((self.pages_per_slot,), -1, np.int32)
                ids[:k] = matched_pages
                ids[k:needed] = fresh
                # host-tier promote (docs/serving.md "Hierarchical KV"):
                # blocks just past the device match that are resident in
                # the host tier import into their already-reserved fresh
                # pages instead of prefilling from tokens. A failed
                # promote degrades to plain token prefill — the fresh
                # pages are simply prefilled over — never a client error
                if self._kv_tier is not None and k < needed \
                        and not isinstance(extra, KVHandoff):
                    hits = self._tier_probe(prompt, adapter, k)
                    if hits:
                        if ledger is not None:
                            ledger.enter("promote")
                        try:
                            promoted = self._tier_import(hits, ids, k)
                            new_nodes, claimed = self._prefix.register(
                                prompt[:(k + promoted) * self.page_size],
                                ids, matched_nodes, adapter=adapter)
                            matched_nodes = matched_nodes + new_nodes
                            if claimed:
                                claimed_set = set(claimed)
                                fresh = [p for p in fresh
                                         if p not in claimed_set]
                            k += promoted
                        except Exception:  # noqa: BLE001 - fall back
                            # to prefilling the suffix from tokens
                            KV_TIER_EVENTS.inc(
                                engine=self._obs_name,
                                replica=self.replica, op="promote",
                                outcome="error")
                        if ledger is not None:
                            ledger.enter("admission")
                adm = _Admission(
                    slot=free, request_id=request_id, prompt=prompt,
                    max_new=max_new, eos_id=eos_id, future=future,
                    submitted=submitted, sampling=sampling,
                    expires=expires, trace=item[8], claimed=time.time(),
                    base=k * self.page_size, offset=k * self.page_size,
                    adapter=adapter, adapter_slot=adapter_slot,
                    ledger=ledger)
                adm.page_ids = ids
                adm.pages = fresh
                adm.prefix_nodes = matched_nodes
                self._apply_directive(adm, extra)
                if adm.small is None:
                    adm.small = init_kv_cache(self.config, 1, self.max_len,
                                              kv_dtype=self.kv_dtype)
                if k:
                    prefix_ids = ids.copy()
                    prefix_ids[k:] = -1
                    if self.paged_prefill_impl == "kernel":
                        # the suffix prefill attends the shared prefix
                        # pages IN PLACE through the page ids (merged
                        # paged-prefill kernel) — the cached KV is
                        # never materialized densely (the acceptance
                        # stat: prefill_gather_admissions stays 0)
                        adm.kernel_prefix = True
                        adm.prefix_ids = prefix_ids
                    else:
                        # reference fallback: seed the batch=1 cache
                        # with a dense gather of the prefix KV; the
                        # suffix-only prefill attends over it from
                        # pos=base
                        with self._lock:
                            self._stats[
                                "prefill_gather_admissions"] += 1
                        adm.small = self._gather_paged(
                            self._pool, adm.small,
                            jnp.asarray(prefix_ids))
                return adm
            except Exception as exc:
                # popped but not yet tracked in self._admission: fail the
                # future and give back the storage before the scheduler
                # dies (e.g. an armed llm.prefix_evict error), or the
                # request would hang outside every drained container
                self._free_pages.extend(fresh)
                if self._prefix is not None:
                    self._prefix.release(matched_nodes)
                if not future.done():
                    future.set_exception(exc)
                raise

    def _prefill_dispatch(self, adm: _Admission, tokens, lora_kw):
        """Prefix-hit admissions on the kernel path attend the cached
        prefix pages in place: the pool + page ids ride the dispatch as
        ``prefix_kv`` and every chunk (and the last-token replay)
        LSE-merges the paged-prefill kernel's partial state with the
        local attention over the suffix rows."""
        if not adm.kernel_prefix:
            return super()._prefill_dispatch(adm, tokens, lora_kw)
        prefix_kv = {"k": self._pool["k"], "v": self._pool["v"],
                     "page_ids": jnp.asarray(adm.prefix_ids),
                     "base": jnp.int32(adm.base)}
        if "k_scale" in self._pool:
            prefix_kv["k_scale"] = self._pool["k_scale"]
            prefix_kv["v_scale"] = self._pool["v_scale"]
        with self._lock:
            self._stats["prefill_kernel_chunks"] += 1
        return self._prefill(self.params, tokens, adm.small,
                             prefix_kv=prefix_kv, **lora_kw)

    def _handoff_kv(self, adm: _Admission, rows: int) -> dict:
        kv = super()._handoff_kv(adm, rows)
        k = adm.base // self.page_size
        if not adm.kernel_prefix or not k:
            return kv
        # kernel-prefix exports: rows < base were never gathered into
        # the slot cache — assemble them from the shared pool pages at
        # serialization time (a host copy of exactly the prefix pages,
        # the unavoidable wire copy; int8 pages + scales ship as-is,
        # never densified to fp32)
        ids = np.asarray(adm.page_ids[:k], np.int64)
        for name, payload in list(kv.items()):
            if not payload.flags.writeable:
                payload = kv[name] = payload.copy()
            pages = np.asarray(self._pool[name][:, ids])
            payload[:, :adm.base] = pages.reshape(
                pages.shape[0], adm.base, *pages.shape[3:])
        return kv

    def _complete_storage(self, adm: _Admission):
        k = adm.base // self.page_size
        insert_ids = np.asarray(adm.page_ids, np.int32).copy()
        # shared prefix pages are read-only — route their rows to scratch
        insert_ids[:k] = -1
        self._pool = self._insert_paged(self._pool, adm.small,
                                        jnp.asarray(insert_ids))
        held = list(adm.prefix_nodes)
        pages = list(adm.pages)
        # imported handoffs skip registration: a decode-pool replica never
        # serves prefills, so caching their blocks would only displace
        # pages without ever producing a hit. Exception: a pre-warm
        # replay (register_import, serving/podfleet.py) imports exactly
        # to seed this engine's prefix index before it takes ring traffic
        if self._prefix is not None and \
                (not adm.prefilled or adm.register_import):
            # index this prompt's freshly written full blocks for future
            # reuse UNDER THE REQUEST'S ADAPTER ROOT; claimed pages
            # become cache-owned (not freed on release — they stay
            # cached until evicted)
            new_nodes, claimed = self._prefix.register(
                adm.prompt, adm.page_ids, adm.prefix_nodes,
                adapter=adm.adapter)
            held.extend(new_nodes)
            if claimed:
                claimed_set = set(claimed)
                pages = [p for p in pages if p not in claimed_set]
        self._slot_pages[adm.slot] = pages
        self._slot_prefix_nodes[adm.slot] = held
        self._page_table[adm.slot] = adm.page_ids
        self._pos[adm.slot] = len(adm.prompt)

    def _abort_admission(self, adm: _Admission):
        self._free_pages.extend(adm.pages)
        if self._prefix is not None:
            self._prefix.release(adm.prefix_nodes)

    def _fail_pending(self, exc: Exception):
        # head-of-line requests parked in the pending deque must fail
        # with everything else on stop/crash
        while self._pending:
            future = self._pending.popleft()[4]
            if not future.done():
                future.set_exception(exc)
        # queued fetch/import control ops fail the same way — a fetch
        # hop waiting on a stopping replica must not hang
        while self._control:
            future = self._control.popleft()[2]
            if not future.done():
                future.set_exception(exc)
        super()._fail_pending(exc)

    def _release_slot_storage(self, index: int):
        for pid in self._slot_pages.pop(index, []):
            self._free_pages.append(pid)
        if self._prefix is not None:
            # cache-owned pages: drop this slot's holds; refcount-0 pages
            # STAY cached (hot prefixes survive across requests) until
            # the LRU eviction reclaims them under pool pressure
            self._prefix.release(self._slot_prefix_nodes.pop(index, []))
        self._page_table[index] = -1
        self._pos[index] = 0
        self._spec_release_slot(index)

    # paged-only cumulative stats mirrored to mlt_llm_events_total
    _COUNTER_STATS = ContinuousBatchingEngine._COUNTER_STATS + (
        "attn_kernel_ticks", "attn_gather_ticks", "attn_hbm_bytes_avoided",
        "prefill_kernel_chunks", "prefill_gather_admissions",
        "kv_demotes", "kv_demoted_pages", "kv_promotes",
        "kv_promoted_pages", "kv_fetches", "kv_fetched_pages",
        "kv_imports", "kv_imported_pages")

    @property
    def stats(self) -> dict:
        out = ContinuousBatchingEngine.stats.fget(self)
        out["decode_attn_impl"] = self.attn_impl
        out["paged_prefill_impl"] = self.paged_prefill_impl
        out["free_pages"] = len(self._free_pages)
        if self._prefix is not None:
            queries = self._prefix.queries
            out["prefix_queries"] = queries
            out["prefix_hits"] = self._prefix.hits
            out["prefix_hit_rate"] = (
                self._prefix.hits / queries if queries else 0.0)
            out["prefix_cached_tokens"] = self._prefix.cached_tokens
            out["prefix_evictions"] = self._prefix.evictions
            out["prefix_cached_pages"] = self._prefix.cached_pages()
        if self._kv_tier is not None:
            out["kv_tier"] = self._kv_tier.stats()
        return out

    # -- speculative decoding (paged hooks; policy lives in the base) ----

    def _make_verify_fn(self):
        return jax.jit(
            functools.partial(_verify_rowwise_paged, self.config,
                              self.page_size, self.attn_impl),
            donate_argnums=(2,))

    def _spec_apply_positions(self, committed: dict):
        # the pool-side rollback: rejected draft positions simply aren't
        # committed — their pool entries are overwritten before any read
        # (docs/serving.md "Speculative decoding"). Pages were reserved
        # at admission for prompt+max_new, and k_eff <= remaining keeps
        # every chunk write inside that reservation, so nothing moves on
        # the free list and _free_page_frac stays honest by construction.
        for index, value in committed.items():
            self._pos[index] = value

    def _spec_verify_dispatch(self, chunk, active):
        table = jnp.asarray(self._page_table)
        pos = jnp.asarray(self._pos)
        lora_kw = self._lora_kwargs(self._slot_adapter_ids()) \
            if self._adapters is not None else {}
        verified, self._pool = self._spec_verify_fn()(
            self.params, jnp.asarray(chunk), self._pool, table, pos,
            **lora_kw)
        with self._lock:
            # a verify dispatch is one attention tick like any other: on
            # the kernel path it never gathers a dense view
            # (attn_gather_ticks stays 0) and the avoided HBM copy is
            # accounted the same way as a decode tick
            if self.attn_impl == "kernel":
                self._stats["attn_kernel_ticks"] += 1
                self._stats["attn_hbm_bytes_avoided"] += \
                    self._gather_bytes_per_tick
            else:
                self._stats["attn_gather_ticks"] += 1
        return np.asarray(verified)

    def _plain_decode_tick(self, active) -> int:
        last = np.zeros((self.slots, 1), np.int32)
        for i in active:
            last[i, 0] = self._slot_state[i].tokens[-1]
        table = jnp.asarray(self._page_table)
        pos = jnp.asarray(self._pos)
        lora_kw = self._lora_kwargs(self._slot_adapter_ids()) \
            if self._adapters is not None else {}
        self._ledger_mark(active, "decode_active")
        if any(self._slot_state[i].temperature > 0 for i in active):
            temp = np.zeros((self.slots,), np.float32)
            top_k = np.zeros((self.slots,), np.int32)
            top_p = np.ones((self.slots,), np.float32)
            for i in active:
                slot = self._slot_state[i]
                temp[i] = slot.temperature
                top_k[i] = slot.top_k
                top_p[i] = slot.top_p
            self._rng, sub = jax.random.split(self._rng)
            next_token, self._pool, _ = self._decode_paged(
                self.params, jnp.asarray(last), self._pool, table, pos,
                sub, jnp.asarray(temp), jnp.asarray(top_k),
                jnp.asarray(top_p), **lora_kw)
        else:
            next_token, self._pool, _ = self._decode_paged(
                self.params, jnp.asarray(last), self._pool, table, pos,
                **lora_kw)
        tokens_host = np.asarray(next_token)
        self._ledger_mark(active, "decode_stall")
        with self._lock:
            # the microbench/acceptance stat: on the kernel path the tick
            # never gathers a dense view (attn_gather_ticks stays 0) and
            # the avoided HBM copy is accounted per tick
            if self.attn_impl == "kernel":
                self._stats["attn_kernel_ticks"] += 1
                self._stats["attn_hbm_bytes_avoided"] += \
                    self._gather_bytes_per_tick
            else:
                self._stats["attn_gather_ticks"] += 1
        for i in active:
            slot = self._slot_state[i]
            token = int(tokens_host[i])
            slot.tokens.append(token)
            slot.remaining -= 1
            self._pos[i] += 1
            capacity = slot.prompt_len + len(slot.tokens) >= self.max_len
            if (slot.eos_id is not None and token == slot.eos_id) or \
                    slot.remaining <= 0 or capacity:
                self._finish(i)
        return len(active)
