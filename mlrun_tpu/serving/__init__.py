from .canary import (  # noqa: F401
    CanaryRouter,
    get_canary_router,
    set_canary_router,
    split_key_for,
)
from .fleet import (  # noqa: F401
    ConsistentHashRing,
    EngineFleet,
    EngineReplica,
)
from .samples import (  # noqa: F401
    SampleRing,
    emit_sample,
    sampling_enabled,
    set_sample_observer,
)
from .remote import BatchHttpRequests, RemoteCallError, RemoteStep  # noqa: F401
from .resilience import (  # noqa: F401
    AdmissionController,
    AdmissionRejected,
    CircuitBreaker,
    CircuitOpenError,
    DeadlineExceeded,
    DegradationLadder,
    EngineStoppedError,
    PromptTooLongError,
    QueueFullError,
    ReplicaUnavailableError,
    ResilienceError,
    ServerDrainingError,
    StepResilience,
)
from .routers import (  # noqa: F401
    EnrichmentModelRouter,
    EnrichmentVotingEnsemble,
    ModelRouter,
    ParallelRun,
    PrefixAffinityRouter,
    VotingEnsemble,
)
from .server import (  # noqa: F401
    GraphContext,
    GraphServer,
    MockEvent,
    MockTrigger,
    Response,
    create_graph_server,
    v2_serving_handler,
    v2_serving_init,
)
from .states import (  # noqa: F401
    BaseStep,
    FlowStep,
    QueueStep,
    RootFlowStep,
    RouterStep,
    TaskStep,
)
from .v2_serving import TpuModelServer, V2ModelServer  # noqa: F401
from .v1_serving import MLModelServer  # noqa: F401
