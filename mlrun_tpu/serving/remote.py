"""Remote http steps (reference analog: mlrun/serving/remote.py:39 RemoteStep,
:241 BatchHttpRequests).

Resilience semantics (docs/serving_resilience.md):

- retries apply ONLY to retryable failures — connection errors, timeouts,
  429 and 5xx responses. Other 4xx responses are the caller's bug and
  fail immediately instead of hammering the endpoint in a tight loop.
- backoff between attempts is exponential with deterministic jitter
  (``common/retry.py compute_backoff`` keyed on step+event), so a chaos
  test's retry timeline is reproducible.
- the raised :class:`RemoteCallError` preserves the original exception as
  ``__cause__`` and carries ``status_code``, instead of flattening
  everything to ``RuntimeError(str)``.
- the per-attempt HTTP timeout is clamped to the event's remaining
  deadline budget (``X-MLT-Timeout`` propagation — serving/resilience.py).
- ``chaos`` hook: every attempt fires ``serving.remote`` first, so tests
  inject connection errors / HTTP statuses without a live endpoint.
"""

from __future__ import annotations

import concurrent.futures
import time
from typing import Optional

from ..chaos import FaultPoints, fire
from ..common.retry import RetryPolicy, compute_backoff
from ..obs import format_trace_header
from ..utils import logger
from ..utils.safe_eval import safe_eval
from .resilience import DeadlineExceeded, deadline_remaining

# patch point for tests (deterministic backoff assertions without sleeping)
_sleep = time.sleep


def _attribute_network(body, hop_s: float):
    """Fold the HTTP hop into a v2 response's phase-ledger timing
    (docs/observability.md "Request attribution"): every item's
    caller-visible wall IS the hop wall (the batch returns together),
    so each item's ``network`` gap is the hop wall minus THAT item's
    server-side attributed wall — transfer, retries, and queueing
    behind the batch's slowest sibling — keeping each item's timing
    summing to the caller-visible wall. No-op for bodies without an
    opt-in ``timing`` field."""
    if not isinstance(body, dict):
        return
    timings = body.get("timing")
    if not isinstance(timings, list):
        return
    for timing in timings:
        if not isinstance(timing, dict):
            continue
        gap = hop_s - timing.get("wall_s", 0.0)
        if gap <= 0:
            continue
        phases = timing.setdefault("phases", {})
        phases["network"] = phases.get("network", 0.0) + gap
        timing["wall_s"] = timing.get("wall_s", 0.0) + gap


class RemoteCallError(RuntimeError):
    """A remote step exhausted its retries (or hit a permanent failure).
    ``status_code`` is the last HTTP status (None for transport errors);
    the original exception is chained as ``__cause__``."""

    def __init__(self, message: str, status_code: int | None = None):
        super().__init__(message)
        self.status_code = status_code


def _failure_status(exc: Exception) -> Optional[int]:
    response = getattr(exc, "response", None)
    return getattr(response, "status_code", None)


def _is_retryable(exc: Exception) -> bool:
    """Connection errors, timeouts, 429 and 5xx are transient; any other
    HTTP error (401, 404, 422, ...) is permanent."""
    import requests

    if isinstance(exc, requests.exceptions.HTTPError):
        status = _failure_status(exc)
        return status is not None and (status == 429 or status >= 500)
    if isinstance(exc, (requests.exceptions.ConnectionError,
                        requests.exceptions.Timeout)):
        return True
    return False


class RemoteStep:
    """Call an external http endpoint as a graph step."""

    def __init__(self, context=None, name: str | None = None, url: str = "",
                 subpath: str = "", method: str = "POST",
                 headers: dict | None = None, return_json: bool = True,
                 timeout: int = 30, retries: int = 2, url_expression: str = "",
                 body_expression: str = "", backoff: float = 0.2,
                 backoff_factor: float = 2.0, backoff_max: float = 10.0,
                 **kwargs):
        self.context = context
        self.name = name
        self.url = url
        self.subpath = subpath
        self.method = method
        self.headers = headers or {}
        self.return_json = return_json
        self.timeout = timeout
        self.retries = retries
        self.url_expression = url_expression
        self.body_expression = body_expression
        self._retry_policy = RetryPolicy(
            max_retries=retries, backoff=backoff,
            backoff_factor=backoff_factor, backoff_max=backoff_max)

    def post_init(self, mode: str = "sync"):
        pass

    def _resolve_url(self, event) -> str:
        if self.url_expression:
            return safe_eval(self.url_expression, {"event": event})
        url = self.url.rstrip("/")
        if self.subpath:
            url += "/" + self.subpath.lstrip("/")
        return url

    def _outbound_span(self, event, url: str):
        """(span, headers) for one outbound call: a child span of the
        current step span, with ``X-MLT-Trace`` injected so the callee's
        server joins this trace (docs/observability.md header contract).
        Without an active trace the step's configured headers pass
        through untouched."""
        tracer = getattr(self.context, "tracer", None)
        trace_id = getattr(event, "trace_id", None)
        if tracer is None or not trace_id:
            return None, self.headers
        current = tracer.current()
        parent_id = (current.span_id
                     if current is not None and current.trace_id == trace_id
                     else getattr(event, "span_id", None))
        span = tracer.start_span(
            f"remote.{self.name}", trace_id=trace_id, parent_id=parent_id,
            attrs={"url": url}, activate=True)
        headers = dict(self.headers)
        headers["X-MLT-Trace"] = format_trace_header(trace_id, span.span_id)
        return span, headers

    def _finish_span(self, span, status: str = "ok"):
        if span is not None:
            self.context.tracer.end_span(span, status=status)

    def _clamped_timeout(self, event) -> float:
        """HTTP timeout clamped to the event's remaining deadline budget —
        a remote call must never outlive the request it serves."""
        remaining = deadline_remaining(event)
        if remaining is None:
            return self.timeout
        if remaining <= 0:
            raise DeadlineExceeded(
                f"remote step '{self.name}' has no deadline budget left")
        return min(self.timeout, remaining)

    def _call_with_retries(self, call, event, item_id: str = ""):
        """Shared attempt loop: classify, back off (deterministic jitter
        keyed on step+event+item), preserve the original failure."""
        last_exc: Exception | None = None
        for attempt in range(self.retries + 1):
            try:
                fire(FaultPoints.serving_remote, step=self.name,
                     attempt=attempt, event=event)
                return call(self._clamped_timeout(event))
            except DeadlineExceeded:
                raise
            except Exception as exc:  # noqa: BLE001 - classified below
                last_exc = exc
                if not _is_retryable(exc) or attempt >= self.retries:
                    break
                delay = compute_backoff(
                    attempt, self._retry_policy,
                    seed=f"{self.name}:{getattr(event, 'id', '')}:{item_id}")
                remaining = deadline_remaining(event)
                if remaining is not None and delay >= remaining:
                    break  # no budget for another attempt
                logger.warning("remote step retrying", step=self.name,
                               attempt=attempt + 1, delay=round(delay, 3),
                               error=str(exc))
                if delay > 0:
                    _sleep(delay)
        status = _failure_status(last_exc)
        raise RemoteCallError(
            f"remote step {self.name} failed: "
            f"{type(last_exc).__name__}: {last_exc}",
            status_code=status) from last_exc

    def do_event(self, event):
        import requests

        url = self._resolve_url(event)
        body = event.body
        if self.body_expression:
            body = safe_eval(self.body_expression, {"event": event})
        kwargs = {}
        if self.method.upper() != "GET" and body is not None:
            if isinstance(body, (dict, list)):
                kwargs["json"] = body
            else:
                kwargs["data"] = body
        span, headers = self._outbound_span(event, url)

        def call(timeout):
            resp = requests.request(self.method.upper(), url,
                                    headers=headers, timeout=timeout,
                                    **kwargs)
            resp.raise_for_status()
            return resp.json() if self.return_json else resp.content

        hop_started = time.perf_counter()
        try:
            event.body = self._call_with_retries(call, event)
        except Exception:
            self._finish_span(span, "error")
            raise
        self._finish_span(span)
        _attribute_network(event.body,
                           time.perf_counter() - hop_started)
        return event


class BatchHttpRequests(RemoteStep):
    """Issue one request per list item concurrently (reference remote.py:241).

    Per-item isolation: one failing item no longer aborts the whole batch
    and loses every other result — each item resolves independently to a
    ``{"result": ...}`` or ``{"error": ..., "status_code": ...}`` envelope
    (order preserved), and each item gets the parent class's full
    retry/backoff treatment.
    """

    def __init__(self, *args, max_in_flight: int = 8, **kwargs):
        super().__init__(*args, **kwargs)
        self.max_in_flight = max_in_flight

    def do_event(self, event):
        import requests

        items = event.body if isinstance(event.body, list) else [event.body]
        url = self._resolve_url(event)
        # one span covers the whole batch; every item's request carries
        # the same injected trace header so callee spans parent onto it
        span, headers = self._outbound_span(event, url)

        def call_item(index_item):
            index, item = index_item

            def call(timeout):
                resp = requests.request(
                    self.method.upper(), url, headers=headers,
                    timeout=timeout,
                    json=item if isinstance(item, (dict, list)) else None)
                resp.raise_for_status()
                return resp.json() if self.return_json else resp.content

            try:
                return {"result": self._call_with_retries(
                    call, event, item_id=str(index))}
            except DeadlineExceeded:
                # not a per-item failure: the whole request's budget is
                # spent — propagate so the server answers with a fast 504
                raise
            except Exception as exc:  # noqa: BLE001 - per-item envelope
                envelope = {"error": str(exc)}
                status = getattr(exc, "status_code", None)
                if status is not None:
                    envelope["status_code"] = status
                return envelope

        try:
            with concurrent.futures.ThreadPoolExecutor(
                    max_workers=self.max_in_flight) as pool:
                event.body = list(pool.map(call_item, enumerate(items)))
        except Exception:
            self._finish_span(span, "error")
            raise
        self._finish_span(span)
        return event
