"""Remote http steps (reference analog: mlrun/serving/remote.py:39 RemoteStep,
:241 BatchHttpRequests)."""

from __future__ import annotations

import concurrent.futures
import json
from typing import Optional

from ..utils import logger
from ..utils.safe_eval import safe_eval


class RemoteStep:
    """Call an external http endpoint as a graph step."""

    def __init__(self, context=None, name: str | None = None, url: str = "",
                 subpath: str = "", method: str = "POST",
                 headers: dict | None = None, return_json: bool = True,
                 timeout: int = 30, retries: int = 2, url_expression: str = "",
                 body_expression: str = "", **kwargs):
        self.context = context
        self.name = name
        self.url = url
        self.subpath = subpath
        self.method = method
        self.headers = headers or {}
        self.return_json = return_json
        self.timeout = timeout
        self.retries = retries
        self.url_expression = url_expression
        self.body_expression = body_expression

    def post_init(self, mode: str = "sync"):
        pass

    def _resolve_url(self, event) -> str:
        if self.url_expression:
            return safe_eval(self.url_expression, {"event": event})
        url = self.url.rstrip("/")
        if self.subpath:
            url += "/" + self.subpath.lstrip("/")
        return url

    def do_event(self, event):
        import requests

        url = self._resolve_url(event)
        body = event.body
        if self.body_expression:
            body = safe_eval(self.body_expression, {"event": event})
        kwargs = {}
        if self.method.upper() != "GET" and body is not None:
            if isinstance(body, (dict, list)):
                kwargs["json"] = body
            else:
                kwargs["data"] = body
        last_exc = None
        for _ in range(self.retries + 1):
            try:
                resp = requests.request(
                    self.method.upper(), url, headers=self.headers,
                    timeout=self.timeout, **kwargs)
                resp.raise_for_status()
                event.body = resp.json() if self.return_json else resp.content
                return event
            except Exception as exc:  # noqa: BLE001 - retried
                last_exc = exc
        raise RuntimeError(f"remote step {self.name} failed: {last_exc}")


class BatchHttpRequests(RemoteStep):
    """Issue one request per list item concurrently (reference remote.py:241)."""

    def __init__(self, *args, max_in_flight: int = 8, **kwargs):
        super().__init__(*args, **kwargs)
        self.max_in_flight = max_in_flight

    def do_event(self, event):
        import requests

        items = event.body if isinstance(event.body, list) else [event.body]
        url = self._resolve_url(event)

        def call(item):
            resp = requests.request(
                self.method.upper(), url, headers=self.headers,
                timeout=self.timeout,
                json=item if isinstance(item, (dict, list)) else None)
            resp.raise_for_status()
            return resp.json() if self.return_json else resp.content

        with concurrent.futures.ThreadPoolExecutor(
                max_workers=self.max_in_flight) as pool:
            event.body = list(pool.map(call, items))
        return event
