"""Serving-side sample tap for the model-monitoring loop
(docs/continuous_tuning.md).

The LLM engines complete thousands of requests per second; the drift
analyzer (``model_monitoring/stream_processing.py``) needs a bounded,
cheap view of that traffic — per-request output tokens, lengths,
latencies and a first-token logit margin — without the engines importing
any monitoring code. Same pattern as the chaos fire observer
(``chaos/registry.py``): an observer is pushed in from above, and the
engines pay ONE module-attribute check per completion while nothing is
armed. Stdlib-only, importable below every serving layer.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Callable, Optional

# observer(sample: dict) installed by the monitoring controller; None =
# dark (the engines skip even building the sample dict)
_observer: Optional[Callable[[dict], None]] = None


def sampling_enabled() -> bool:
    """The engines' fast-path gate: build a sample only when someone is
    listening (one module-attribute read when dark)."""
    return _observer is not None


def get_sample_observer() -> Optional[Callable[[dict], None]]:
    """The currently installed observer (an uninstaller must check it
    still owns the slot — see ContinuousTuningController.stop)."""
    return _observer


def set_sample_observer(observer: Optional[Callable[[dict], None]]):
    """Install (or clear, with None) the process-wide sample observer.
    The observer runs on engine scheduler threads — it must be cheap and
    never raise consequences into the engine (emit_sample swallows)."""
    global _observer
    _observer = observer


def emit_sample(**sample):
    """Hand one completed-request sample to the observer, if armed.
    Sample keys (engines fill what they cheaply have): ``adapter``,
    ``tokens`` (generated token ids), ``prompt_len``, ``generated``,
    ``ttft_s``, ``total_s``, ``logit_margin`` (first-token top1-top2
    logit gap, NaN when unavailable), ``engine``, ``replica``."""
    observer = _observer
    if observer is None:
        return
    try:
        observer(sample)
    except Exception:  # noqa: BLE001 - monitoring must never fail a
        pass           # request's completion path


class SampleRing:
    """Bounded thread-safe sample buffer: the default observer target.
    Engines append from scheduler threads; the monitoring controller
    drains on its tick. Overflow drops OLDEST (the analyzer wants the
    current window, not history) and is counted."""

    def __init__(self, maxlen: int = 4096):
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=max(16, int(maxlen)))
        self.dropped = 0
        self.total = 0

    def append(self, sample: dict):
        with self._lock:
            if len(self._ring) == self._ring.maxlen:
                self.dropped += 1
            self._ring.append(sample)
            self.total += 1

    def drain(self) -> list:
        with self._lock:
            out = list(self._ring)
            self._ring.clear()
        return out

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)
