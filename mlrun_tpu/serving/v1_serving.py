"""Legacy v1 model server (reference analog: mlrun/serving/v1_serving.py:70
MLModelServer) — kept for API parity; new code should subclass V2ModelServer.
"""

from __future__ import annotations

from ..utils import logger
from .v2_serving import V2ModelServer


class MLModelServer(V2ModelServer):
    """v1-protocol server: body {"instances": [...]} → {"predictions": [...]}.

    Subclasses implement load() and predict(body) like the v1 API.
    """

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.protocol = "v1"

    def validate(self, request: dict, operation: str) -> dict:
        if "instances" not in request and "inputs" not in request:
            raise ValueError(
                "v1 request must contain an 'instances' field")
        return request

    def preprocess(self, request: dict, operation: str) -> dict:
        if "instances" in request and "inputs" not in request:
            request["inputs"] = request["instances"]
        return request

    def postprocess(self, response: dict) -> dict:
        if "outputs" in response:
            response["predictions"] = response.pop("outputs")
        return response
