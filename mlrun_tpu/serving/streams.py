"""Stream pushers for queue steps + monitoring events.

Reference analog: the storey stream bridges in mlrun/serving/states.py:1650-1674
(V3IO/Kafka). Here: an in-memory stream (tests, single-process serving) and a
file-backed stream (durable local monitoring pipeline); kafka gated on import.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Callable


class _InMemStream:
    def __init__(self, name: str, maxlen: int = 10000):
        self.name = name
        self._items: deque = deque(maxlen=maxlen)
        self._lock = threading.Lock()
        self._subscribers: list[Callable] = []

    def push(self, data):
        if isinstance(data, list):
            items = data
        else:
            items = [data]
        with self._lock:
            for item in items:
                self._items.append(item)
                for callback in self._subscribers:
                    callback(item)

    def pull(self, max_items: int = 100) -> list:
        out = []
        with self._lock:
            while self._items and len(out) < max_items:
                out.append(self._items.popleft())
        return out

    def subscribe(self, callback: Callable):
        self._subscribers.append(callback)

    def __len__(self):
        return len(self._items)


_inmem_streams: dict[str, _InMemStream] = {}
_lock = threading.Lock()


def get_in_memory_stream(name: str) -> _InMemStream:
    with _lock:
        if name not in _inmem_streams:
            _inmem_streams[name] = _InMemStream(name)
        return _inmem_streams[name]


class _FileStream:
    """Durable jsonl stream: one file per stream, append-only."""

    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._lock = threading.Lock()

    def push(self, data):
        items = data if isinstance(data, list) else [data]
        with self._lock, open(self.path, "a") as fp:
            for item in items:
                fp.write(json.dumps(item, default=str) + "\n")

    def pull(self, offset: int = 0, max_items: int = 0) -> tuple[list, int]:
        if not os.path.isfile(self.path):
            return [], offset
        out = []
        with open(self.path) as fp:
            fp.seek(offset)
            for line in fp:
                if line.strip():
                    out.append(json.loads(line))
                if max_items and len(out) >= max_items:
                    break
            offset = fp.tell()
        return out, offset


class _KafkaStream:
    def __init__(self, brokers: str, topic: str):
        from kafka import KafkaProducer  # gated import

        self._producer = KafkaProducer(bootstrap_servers=brokers.split(","))
        self.topic = topic

    def push(self, data):
        items = data if isinstance(data, list) else [data]
        for item in items:
            self._producer.send(
                self.topic, json.dumps(item, default=str).encode())


def get_stream_pusher(path: str, **options):
    """Resolve a stream path: memory://name, file:///path, kafka://brokers/topic."""
    if path.startswith("memory://"):
        return get_in_memory_stream(path[len("memory://"):])
    if path.startswith("kafka://"):
        body = path[len("kafka://"):]
        brokers, _, topic = body.partition("/")
        return _KafkaStream(options.get("brokers", brokers), topic)
    if path.startswith("file://"):
        return _FileStream(path[len("file://"):])
    # bare path → file stream
    return _FileStream(path)
