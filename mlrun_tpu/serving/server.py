"""Graph server (reference analog: mlrun/serving/server.py:86 GraphServer,
:252 run, :315 v2_serving_init, :387 v2_serving_handler, :437 MockEvent,
:493 GraphContext — fresh implementation).

The server hosts a serving graph in-process. Online deployments wrap it in the
ASGI app (``mlrun_tpu.serving.asgi``) instead of Nuclio; offline tests call
``server.test(...)`` exactly like the reference's offline-testing flow.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
import traceback
import uuid
from typing import Any, Optional, Union

from ..config import mlconf
from ..model import ModelObj
from ..obs import (
    BREAKER_STATE,
    REGISTRY,
    REQUEST_LATENCY,
    SERVER_INFLIGHT,
    SERVING_EVENTS,
    get_tracer,
    parse_trace_header,
)
from ..secrets import SecretsStore
from ..utils import logger, now_iso
from .resilience import (
    ResilienceError,
    ServerDrainingError,
    deadline_from_headers,
    deadline_remaining,
    retry_after_hint,
)
from .states import FlowStep, RootFlowStep, RouterStep, graph_root_setter


class MockEvent:
    """Event object used offline and by the ASGI adapter (server.py:437)."""

    def __init__(self, body=None, content_type=None, headers=None, method=None,
                 path=None, event_id=None, trigger=None, error=None,
                 deadline: float | None = None):
        self.id = event_id or uuid.uuid4().hex
        # trace context stamped by GraphServer.run (docs/observability.md):
        # steps/remote calls/engines hang their child spans off these
        self.trace_id = None
        self.span_id = None
        self.key = ""
        self.body = body
        self.time = now_iso()
        self.content_type = content_type
        self.headers = headers or {}
        self.method = method or ("POST" if body is not None else "GET")
        self.path = path or "/"
        self.trigger = trigger
        self.error = error
        # absolute deadline on the time.monotonic() timebase; steps check
        # the remaining budget before executing (serving/resilience.py)
        self.deadline = deadline

    def __str__(self):
        return f"Event(id={self.id}, path={self.path}, body={self.body})"


Event = MockEvent


class MockTrigger:
    def __init__(self, kind: str = "", name: str = ""):
        self.kind = kind
        self.name = name


class Response:
    def __init__(self, headers=None, body=None, content_type=None,
                 status_code=200):
        self.headers = headers or {}
        self.body = body
        self.content_type = content_type or "text/plain"
        self.status_code = status_code


class GraphContext:
    """Context passed to graph step classes (server.py:493)."""

    def __init__(self, level="info", logger_=None, server=None):
        self.state = None
        self.logger = logger_ or logger
        self.worker_id = 0
        self.server = server
        self.project = ""
        self.current_function = ""
        self.stream = None
        self.root = None
        self._secrets = SecretsStore()
        self.is_mock = False
        self.monitoring_stream = None
        # resilience observability: breaker trips, sheds, rejections.
        # The dict stays the compat view; every increment is mirrored
        # into the process-wide registry (mlt_serving_events_total) so
        # /metrics carries the same series with labels
        self.metrics: dict[str, int] = {}
        self._metrics_lock = threading.Lock()
        self.tracer = None  # set by GraphServer.init_states

    def incr(self, name: str, value: int = 1):
        with self._metrics_lock:
            self.metrics[name] = self.metrics.get(name, 0) + value
        SERVING_EVENTS.inc(value, event=name)

    def get_param(self, key: str, default=None):
        if self.server and self.server.parameters:
            return self.server.parameters.get(key, default)
        return default

    def get_secret(self, key: str, default=None):
        return self._secrets.get(key, default)

    def get_store_resource(self, uri: str):
        from ..datastore import store_manager

        return store_manager.object(url=uri)

    def get_remote_endpoint(self, name: str, external: bool = True) -> str:
        db = None
        try:
            from ..db import get_run_db

            db = get_run_db()
            function = db.get_function(name, self.project)
            return function.get("status", {}).get("address", "")
        except Exception:  # noqa: BLE001
            return ""

    def push_error(self, event, message: str, source=None, **kwargs):
        self.logger.error(
            "graph error", error=message, source=source, event_id=getattr(
                event, "id", None))


class GraphServer(ModelObj):
    kind = "server"
    _dict_fields = ["graph", "parameters", "verbose", "load_mode",
                    "function_uri", "graph_initializer", "error_stream",
                    "track_models", "secret_sources", "default_content_type"]

    def __init__(self, graph=None, parameters=None, load_mode=None,
                 function_uri=None, verbose=False, version=None,
                 functions=None, graph_initializer=None, error_stream=None,
                 track_models=None, secret_sources=None,
                 default_content_type=None):
        self._graph = None
        self.graph = graph
        self.function_uri = function_uri
        self.parameters = parameters or {}
        self.verbose = verbose
        self.load_mode = load_mode or "sync"
        self.version = version or "v2"
        self.context = None
        self.graph_initializer = graph_initializer
        self.error_stream = error_stream
        self.track_models = track_models
        self.secret_sources = secret_sources or []
        self.default_content_type = default_content_type
        self._namespace = {}
        self._current_function = None
        # serving-path resilience state (not serialized)
        self._inflight = 0
        self._state_lock = threading.Lock()
        self._draining = False
        # ready-means-warm (docs/serving.md "Engine fleet"): True by
        # default so embedded/test servers stay ready; the ASGI gateway
        # calls begin_warmup() before its warmup pass, flipping readyz
        # false until finish_warmup() — the ring never routes to a pod
        # whose engines would compile/fetch on the first request
        self._warm = True
        self.step_errors: dict[str, int] = {}
        # span factory (not serialized); assign a dedicated Tracer before
        # init_states to isolate this server's spans (tests do), else the
        # process-wide tracer is used
        self.tracer = None

    @property
    def graph(self) -> Union[RootFlowStep, RouterStep]:
        return self._graph

    @graph.setter
    def graph(self, graph):
        if graph is None:
            self._graph = None
            return
        self._graph = graph_root_setter(self, graph)

    def set_current_function(self, function):
        self._current_function = function

    def init_states(self, context, namespace: dict | None = None,
                    logger_=None, is_mock: bool = False,
                    monitoring_mode: str | None = None):
        """Initialize graph steps (reference server.py:150 init_states)."""
        self.context = context or GraphContext(server=self)
        if isinstance(self.context, GraphContext):
            self.context.server = self
            self.context.is_mock = is_mock
            if self.function_uri:
                self.context.project = self.function_uri.split("/")[0]
        if self.secret_sources:
            self.context._secrets = SecretsStore.from_list(self.secret_sources)
        if self.graph_initializer:
            initializer = self.graph_initializer
            if isinstance(initializer, str):
                from .states import get_function

                initializer = get_function(initializer, namespace or {})
            initializer(self)
        if self.track_models and isinstance(self.context, GraphContext):
            from ..model_monitoring.stream_processing import get_monitoring_stream

            self.context.monitoring_stream = get_monitoring_stream(
                self.context.project or mlconf.default_project)
        self._namespace = namespace or {}
        if self.tracer is None:
            self.tracer = get_tracer()
        if isinstance(self.context, GraphContext):
            self.context.tracer = self.tracer
        self.graph.init_object(self.context, self._namespace, self.load_mode)
        self._register_breaker_collector()
        return self

    def init_object(self, namespace: dict | None = None):
        self.graph.init_object(self.context, namespace or self._namespace,
                               self.load_mode)

    def run(self, event: MockEvent, context=None, get_body: bool = False):
        """Process one event through the graph (reference server.py:252).

        Resilience semantics: a draining replica rejects with 503 before
        touching the graph; a deadline/timeout header becomes an absolute
        event deadline every step checks; resilience rejections
        (429/503/504 — see serving/resilience.py) come back as fast
        typed responses, not 500s with tracebacks.
        """
        server_context = self.context
        # header parsing happens BEFORE the inflight increment: a parse
        # exception here must not leak the gauge (the decrement lives in
        # the finally of the graph.run block below)
        if getattr(event, "deadline", None) is None:
            event.deadline = deadline_from_headers(
                getattr(event, "headers", None))
        # admission vs drain must be ATOMIC: checked and incremented under
        # one lock hold, or a request could slip between the drain-flag
        # read and the inflight increment and still be executing after
        # drain() observed inflight == 0 and reported drained
        with self._state_lock:
            admitted = not self._draining
            if admitted:
                self._inflight += 1
        if not admitted:
            self._incr_metric("server.draining_rejected")
            exc = ServerDrainingError("server is draining, not admitting "
                                      "new events",
                                      retry_after_s=retry_after_hint())
            # the hint rides both the body and the Retry-After header so
            # blind-retry clients and header-aware routers both back off
            # on the fleet's schedule
            return Response(body={"error": str(exc),
                                  "retry_after_s": exc.retry_after_s},
                            status_code=exc.status_code,
                            headers={"Retry-After":
                                     f"{exc.retry_after_s:.3f}"})
        SERVER_INFLIGHT.inc()
        # root span: an incoming X-MLT-Trace header joins the caller's
        # trace; otherwise a fresh trace starts here. Steps, remote calls,
        # and engine phases hang their child spans off event.trace_id
        span = None
        tracer = self.tracer
        if tracer is not None:
            trace_id, parent_id = parse_trace_header(
                getattr(event, "headers", None))
            span = tracer.start_span(
                "server.run", trace_id=trace_id, parent_id=parent_id,
                attrs={"path": getattr(event, "path", ""),
                       "event_id": getattr(event, "id", None)},
                activate=True)
            event.trace_id = span.trace_id
            event.span_id = span.span_id
        started = time.perf_counter()
        span_status = "ok"
        try:
            try:
                response = self.graph.run(event)
            except ResilienceError as exc:
                # fast failure: typed status, compact log, no traceback spam
                span_status = "error"
                self._incr_metric(
                    f"server.{type(exc).__name__}")
                logger.warning("serving resilience rejection",
                               error=str(exc), kind=type(exc).__name__,
                               event_id=getattr(event, "id", None),
                               trace_id=getattr(event, "trace_id", None))
                envelope = self._error_envelope(exc, event)
                headers = None
                hint = getattr(exc, "retry_after_s", None)
                if hint is not None:
                    envelope["retry_after_s"] = hint
                    headers = {"Retry-After": f"{hint:.3f}"}
                return Response(
                    body=envelope, headers=headers,
                    status_code=exc.status_code)
            except Exception as exc:  # noqa: BLE001
                span_status = "error"
                message = f"{exc}\n{traceback.format_exc()}"
                if server_context:
                    server_context.push_error(event, message, source="graph")
                if self.error_stream:
                    from .streams import get_stream_pusher

                    get_stream_pusher(self.error_stream).push(
                        {"error": str(exc), "event": str(event.body)})
                status = getattr(exc, "status_code", None)
                if not isinstance(status, int) or status < 400:
                    status = 500
                return Response(body=self._error_envelope(exc, event),
                                status_code=status)
        finally:
            with self._state_lock:
                self._inflight -= 1
            SERVER_INFLIGHT.dec()
            # the request's trace id rides the latency histogram as its
            # bucket exemplar — a latency SLO breach names it, and
            # GET /debug/trace/<id> turns it into a waterfall
            REQUEST_LATENCY.observe(
                time.perf_counter() - started,
                exemplar=span.trace_id if span is not None else None)
            if span is not None:
                tracer.end_span(span, status=span_status)
        if isinstance(response, MockEvent):
            body = response.body
            if get_body:
                return body
            return response
        return response

    def test(self, path: str = "/", body=None, method: str = "",
             headers: dict | None = None, content_type: str | None = None,
             silent: bool = False, get_body: bool = True,
             event_id: str | None = None, trigger: MockTrigger | None = None):
        """Offline graph test entry (reference server.py:196)."""
        if not self.graph:
            raise ValueError("no graph topology was set")
        event = MockEvent(body=body, path=path, method=method,
                          content_type=content_type, headers=headers,
                          event_id=event_id, trigger=trigger)
        result = self.run(event, get_body=get_body)
        if isinstance(result, Response) and result.status_code >= 400 \
                and not silent:
            raise RuntimeError(f"error invoking graph: {result.body}")
        return result

    def wait_for_completion(self):
        """Drain async branches (flow engine)."""
        if self.graph and hasattr(self.graph, "_flush"):
            self.graph._flush()

    # -- observability -------------------------------------------------------
    @staticmethod
    def _error_envelope(exc: Exception, event) -> dict:
        """Error body with the trace id stamped in so a client can hand
        support the exact span timeline of its failed request."""
        envelope = {"error": str(exc)}
        trace_id = getattr(event, "trace_id", None)
        if trace_id:
            envelope["trace_id"] = trace_id
        return envelope

    def _register_breaker_collector(self):
        """Scrape-time gauge of every configured breaker's state
        (0 closed, 1 half-open, 2 open). Weakly bound: the collector
        retires itself once this server is gone."""
        if getattr(self, "_breaker_collector", None) is not None:
            return
        import weakref

        ref = weakref.ref(self)
        state_levels = {"closed": 0, "half_open": 1, "open": 2}

        def collect():
            server = ref()
            if server is None:
                return False
            graph = server.graph
            steps = []
            if graph is not None:
                # a bare RouterStep root keeps children in .routes only
                steps.extend(getattr(graph, "routes", {}).values())
                for step in (getattr(graph, "steps", {}) or {}).values():
                    steps.append(step)
                    steps.extend(getattr(step, "routes", {}).values())
            for step in steps:
                resilience = getattr(step, "_resilience", None)
                breaker = getattr(resilience, "breaker", None)
                if breaker is not None:
                    BREAKER_STATE.set(state_levels.get(breaker.state, 0),
                                      step=step.name or "")
            return None

        self._breaker_collector = collect
        REGISTRY.add_collector(collect)

    # -- resilience: health / readiness / drain ------------------------------
    def _incr_metric(self, name: str, value: int = 1):
        if isinstance(self.context, GraphContext):
            self.context.incr(name, value)

    def record_step_error(self, step: str):
        """Async-branch error counter (QueueStep workers report here so
        tier-1 tests can assert on swallowed-exception counts)."""
        with self._state_lock:
            self.step_errors[step] = self.step_errors.get(step, 0) + 1

    @property
    def inflight(self) -> int:
        with self._state_lock:
            return self._inflight

    @property
    def draining(self) -> bool:
        return self._draining

    def healthz(self) -> dict:
        """Liveness: the process serves, even while draining."""
        return {"status": "ok", "inflight": self.inflight,
                "draining": self._draining}

    def readyz(self) -> dict:
        """Readiness: flips false the moment drain starts so the load
        balancer stops routing before in-flight events finish — and
        stays false until WARMTH (engine warmup + adapter working-set
        prefetch) completes, so ready means warm, not merely alive
        (the fleet's ring join gates on this probe)."""
        ready = (self.graph is not None and self.context is not None
                 and not self._draining and self._warm)
        return {"ready": ready, "draining": self._draining,
                "warm": self._warm, "inflight": self.inflight}

    def begin_warmup(self):
        """Flip readyz false until :meth:`finish_warmup`: the gateway
        calls this before its warmup pass so a cold replica is never
        routed to."""
        self._warm = False

    def finish_warmup(self):
        self._warm = True

    def warmup(self):
        """Warm every graph step that supports it (engine compile +
        first-dispatch, adapter prefetch), then flip ready. One failed
        step logs and continues — a partially warm replica still beats a
        replica that never reports ready (the pre-warm contract:
        failures degrade to cold, never strand capacity)."""
        graph = self.graph
        steps = []
        if graph is not None:
            steps.extend((getattr(graph, "routes", {}) or {}).values())
            for step in (getattr(graph, "steps", {}) or {}).values():
                steps.append(step)
                steps.extend((getattr(step, "routes", {}) or {}).values())
        for step in steps:
            target = getattr(step, "_object", None) or step
            warm = getattr(target, "warmup", None)
            if not callable(warm):
                continue
            try:
                warm()
            except Exception as exc:  # noqa: BLE001 - degrade to cold
                logger.warning("step warmup failed",
                               step=getattr(step, "name", ""),
                               error=str(exc))
        self.finish_warmup()

    def drain(self, timeout: float | None = None) -> bool:
        """Graceful drain: stop admission (readyz → not ready), wait for
        in-flight events, then flush async queue branches — all bounded by
        ``timeout``. Returns True when everything completed in time.

        Wired to the preemption signal via ``drain_on_preemption``: a
        preempted serving replica finishes its in-flight requests inside
        the eviction grace period instead of dropping them.
        """
        if timeout is None:
            resilience_conf = getattr(mlconf.serving, "resilience", None)
            timeout = float(getattr(resilience_conf, "drain_timeout_s",
                                    30.0))
        self._draining = True
        logger.info("serving drain started", inflight=self.inflight,
                    timeout=timeout)
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.inflight == 0:
                break
            time.sleep(0.005)
        drained = self.inflight == 0
        if self.graph is not None and hasattr(self.graph, "_flush"):
            remaining = max(0.0, deadline - time.monotonic())
            drained = self.graph._flush(remaining) and drained
        logger.info("serving drain finished", drained=drained,
                    inflight=self.inflight)
        return drained

    def drain_on_preemption(self, guard, timeout: float | None = None):
        """Arm a watcher that drains this server when the
        ``PreemptionGuard`` latches (SIGTERM on a preemptible slice). The
        watcher blocks on the guard's event — no polling — so readyz
        flips not-ready well before the guard's second-signal escalation
        fires. Returns the watcher thread."""
        return guard.on_preempted(lambda: self.drain(timeout),
                                  name="serving-drain-on-preemption")


def create_graph_server(parameters=None, load_mode=None, graph=None,
                        verbose=False, current_function=None,
                        **kwargs) -> GraphServer:
    """Create a standalone graph server for testing/embedding
    (reference server.py create_graph_server)."""
    server = GraphServer(graph=graph, parameters=parameters,
                         load_mode=load_mode, verbose=verbose, **kwargs)
    server.set_current_function(
        current_function or os.environ.get("SERVING_CURRENT_FUNCTION", ""))
    return server


def v2_serving_init(context, namespace: dict | None = None):
    """Process-start entrypoint: build the server from the serialized spec env
    (reference server.py:315; SERVING_SPEC_ENV contract)."""
    spec_env = os.environ.get("SERVING_SPEC_ENV", "")
    if not spec_env:
        raise ValueError("SERVING_SPEC_ENV is not set")
    spec = json.loads(spec_env)
    server = GraphServer.from_dict(spec)
    server.init_states(context, namespace or get_caller_globals())
    setattr(context, "mlrun_handler", v2_serving_handler)
    setattr(context, "_server", server)
    return server


def v2_serving_handler(context, event, get_body: bool = False):
    """Per-event entrypoint (reference server.py:387)."""
    server: GraphServer = getattr(context, "_server")
    return server.run(event, context, get_body=get_body)


def get_caller_globals(stack_depth: int = 2) -> dict:
    import inspect

    try:
        frame = inspect.stack()[stack_depth][0]
        return frame.f_globals
    except Exception:  # noqa: BLE001
        return {}
