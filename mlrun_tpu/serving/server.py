"""Graph server (reference analog: mlrun/serving/server.py:86 GraphServer,
:252 run, :315 v2_serving_init, :387 v2_serving_handler, :437 MockEvent,
:493 GraphContext — fresh implementation).

The server hosts a serving graph in-process. Online deployments wrap it in the
ASGI app (``mlrun_tpu.serving.asgi``) instead of Nuclio; offline tests call
``server.test(...)`` exactly like the reference's offline-testing flow.
"""

from __future__ import annotations

import json
import os
import socket
import traceback
import uuid
from typing import Any, Optional, Union

from ..config import mlconf
from ..model import ModelObj
from ..secrets import SecretsStore
from ..utils import logger, now_iso
from .states import FlowStep, RootFlowStep, RouterStep, graph_root_setter


class MockEvent:
    """Event object used offline and by the ASGI adapter (server.py:437)."""

    def __init__(self, body=None, content_type=None, headers=None, method=None,
                 path=None, event_id=None, trigger=None, error=None):
        self.id = event_id or uuid.uuid4().hex
        self.key = ""
        self.body = body
        self.time = now_iso()
        self.content_type = content_type
        self.headers = headers or {}
        self.method = method or ("POST" if body is not None else "GET")
        self.path = path or "/"
        self.trigger = trigger
        self.error = error

    def __str__(self):
        return f"Event(id={self.id}, path={self.path}, body={self.body})"


Event = MockEvent


class MockTrigger:
    def __init__(self, kind: str = "", name: str = ""):
        self.kind = kind
        self.name = name


class Response:
    def __init__(self, headers=None, body=None, content_type=None,
                 status_code=200):
        self.headers = headers or {}
        self.body = body
        self.content_type = content_type or "text/plain"
        self.status_code = status_code


class GraphContext:
    """Context passed to graph step classes (server.py:493)."""

    def __init__(self, level="info", logger_=None, server=None):
        self.state = None
        self.logger = logger_ or logger
        self.worker_id = 0
        self.server = server
        self.project = ""
        self.current_function = ""
        self.stream = None
        self.root = None
        self._secrets = SecretsStore()
        self.is_mock = False
        self.monitoring_stream = None

    def get_param(self, key: str, default=None):
        if self.server and self.server.parameters:
            return self.server.parameters.get(key, default)
        return default

    def get_secret(self, key: str, default=None):
        return self._secrets.get(key, default)

    def get_store_resource(self, uri: str):
        from ..datastore import store_manager

        return store_manager.object(url=uri)

    def get_remote_endpoint(self, name: str, external: bool = True) -> str:
        db = None
        try:
            from ..db import get_run_db

            db = get_run_db()
            function = db.get_function(name, self.project)
            return function.get("status", {}).get("address", "")
        except Exception:  # noqa: BLE001
            return ""

    def push_error(self, event, message: str, source=None, **kwargs):
        self.logger.error(
            "graph error", error=message, source=source, event_id=getattr(
                event, "id", None))


class GraphServer(ModelObj):
    kind = "server"
    _dict_fields = ["graph", "parameters", "verbose", "load_mode",
                    "function_uri", "graph_initializer", "error_stream",
                    "track_models", "secret_sources", "default_content_type"]

    def __init__(self, graph=None, parameters=None, load_mode=None,
                 function_uri=None, verbose=False, version=None,
                 functions=None, graph_initializer=None, error_stream=None,
                 track_models=None, secret_sources=None,
                 default_content_type=None):
        self._graph = None
        self.graph = graph
        self.function_uri = function_uri
        self.parameters = parameters or {}
        self.verbose = verbose
        self.load_mode = load_mode or "sync"
        self.version = version or "v2"
        self.context = None
        self.graph_initializer = graph_initializer
        self.error_stream = error_stream
        self.track_models = track_models
        self.secret_sources = secret_sources or []
        self.default_content_type = default_content_type
        self._namespace = {}
        self._current_function = None

    @property
    def graph(self) -> Union[RootFlowStep, RouterStep]:
        return self._graph

    @graph.setter
    def graph(self, graph):
        if graph is None:
            self._graph = None
            return
        self._graph = graph_root_setter(self, graph)

    def set_current_function(self, function):
        self._current_function = function

    def init_states(self, context, namespace: dict | None = None,
                    logger_=None, is_mock: bool = False,
                    monitoring_mode: str | None = None):
        """Initialize graph steps (reference server.py:150 init_states)."""
        self.context = context or GraphContext(server=self)
        if isinstance(self.context, GraphContext):
            self.context.server = self
            self.context.is_mock = is_mock
            if self.function_uri:
                self.context.project = self.function_uri.split("/")[0]
        if self.secret_sources:
            self.context._secrets = SecretsStore.from_list(self.secret_sources)
        if self.graph_initializer:
            initializer = self.graph_initializer
            if isinstance(initializer, str):
                from .states import get_function

                initializer = get_function(initializer, namespace or {})
            initializer(self)
        if self.track_models and isinstance(self.context, GraphContext):
            from ..model_monitoring.stream_processing import get_monitoring_stream

            self.context.monitoring_stream = get_monitoring_stream(
                self.context.project or mlconf.default_project)
        self._namespace = namespace or {}
        self.graph.init_object(self.context, self._namespace, self.load_mode)
        return self

    def init_object(self, namespace: dict | None = None):
        self.graph.init_object(self.context, namespace or self._namespace,
                               self.load_mode)

    def run(self, event: MockEvent, context=None, get_body: bool = False):
        """Process one event through the graph (reference server.py:252)."""
        server_context = self.context
        try:
            response = self.graph.run(event)
        except Exception as exc:  # noqa: BLE001
            message = f"{exc}\n{traceback.format_exc()}"
            if server_context:
                server_context.push_error(event, message, source="graph")
            if self.error_stream:
                from .streams import get_stream_pusher

                get_stream_pusher(self.error_stream).push(
                    {"error": str(exc), "event": str(event.body)})
            return Response(body={"error": str(exc)}, status_code=500)
        if isinstance(response, MockEvent):
            body = response.body
            if get_body:
                return body
            return response
        return response

    def test(self, path: str = "/", body=None, method: str = "",
             headers: dict | None = None, content_type: str | None = None,
             silent: bool = False, get_body: bool = True,
             event_id: str | None = None, trigger: MockTrigger | None = None):
        """Offline graph test entry (reference server.py:196)."""
        if not self.graph:
            raise ValueError("no graph topology was set")
        event = MockEvent(body=body, path=path, method=method,
                          content_type=content_type, headers=headers,
                          event_id=event_id, trigger=trigger)
        result = self.run(event, get_body=get_body)
        if isinstance(result, Response) and result.status_code >= 400 \
                and not silent:
            raise RuntimeError(f"error invoking graph: {result.body}")
        return result

    def wait_for_completion(self):
        """Drain async branches (flow engine)."""
        if self.graph and hasattr(self.graph, "_flush"):
            self.graph._flush()


def create_graph_server(parameters=None, load_mode=None, graph=None,
                        verbose=False, current_function=None,
                        **kwargs) -> GraphServer:
    """Create a standalone graph server for testing/embedding
    (reference server.py create_graph_server)."""
    server = GraphServer(graph=graph, parameters=parameters,
                         load_mode=load_mode, verbose=verbose, **kwargs)
    server.set_current_function(
        current_function or os.environ.get("SERVING_CURRENT_FUNCTION", ""))
    return server


def v2_serving_init(context, namespace: dict | None = None):
    """Process-start entrypoint: build the server from the serialized spec env
    (reference server.py:315; SERVING_SPEC_ENV contract)."""
    spec_env = os.environ.get("SERVING_SPEC_ENV", "")
    if not spec_env:
        raise ValueError("SERVING_SPEC_ENV is not set")
    spec = json.loads(spec_env)
    server = GraphServer.from_dict(spec)
    server.init_states(context, namespace or get_caller_globals())
    setattr(context, "mlrun_handler", v2_serving_handler)
    setattr(context, "_server", server)
    return server


def v2_serving_handler(context, event, get_body: bool = False):
    """Per-event entrypoint (reference server.py:387)."""
    server: GraphServer = getattr(context, "_server")
    return server.run(event, context, get_body=get_body)


def get_caller_globals(stack_depth: int = 2) -> dict:
    import inspect

    try:
        frame = inspect.stack()[stack_depth][0]
        return frame.f_globals
    except Exception:  # noqa: BLE001
        return {}
