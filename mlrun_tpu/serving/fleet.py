"""Engine replica fleet: prefix-affinity routing + prefill/decode
disaggregation (docs/serving.md "Engine fleet").

One continuous-batching engine per process caps throughput far below the
"millions of users" north star, and naive round-robin/random routing
across replicas destroys the prefix-cache locality serving/prefix.py
pays for — every replica re-prefills the hot prefixes its siblings
already cached. This module is the fleet layer above the engines:

- :class:`ConsistentHashRing` — bounded ring with virtual nodes; keys
  are the prompt's leading full-page-size block chains
  (``prefix.block_chain_key``, the same block identity the radix index
  keys on), so requests sharing a hot prefix land on the SAME replica
  where the KV pages already live, and a replica join/leave moves only
  ~1/N of the keyspace.
- :class:`EngineFleet` — owns N engine replicas (in-process workers;
  the dispatch seam is a Future-returning ``submit``, so a
  ``RemoteStep``-backed process replica slots in behind the same
  interface). Dispatch re-routes 503-class failures
  (``EngineStoppedError``, draining, shed) to the next ring node with
  bounded deterministic backoff (``common/retry.compute_backoff``)
  instead of surfacing them to the client.
- Prefill/decode disaggregation: with ``prefill_replicas`` > 0 the
  fleet splits into a prefill pool (affinity-routed — the prefix caches
  live there) and a decode pool (least-loaded). A prefill replica runs
  the (chunked) prefill and exports the slot's KV
  (``KVHandoff``, the batch=1 slot-cache serialization boundary that
  ``gather_prefix_pages``/``insert_prompt_pages`` already define; int8
  pools ship int8 pages + per-vector f32 scales as-is — the payload is
  never densified to the native dtype, and ``KVHandoff.kv_dtype``
  rejects mismatched pools typed); a decode replica imports it and
  ticks — a fleet-wide long prompt can never appear between two decode
  ticks, generalizing chunked prefill across processes.

Everything here is host-side Python with no jax import at module level —
the router must stay importable below the engines (serving/__init__.py
pulls routers.py in eagerly, and routers.py uses the ring).
"""

from __future__ import annotations

import bisect
import hashlib
import random
import threading
import time
from collections import OrderedDict, deque
from concurrent.futures import Future
from typing import Callable, Optional

from ..chaos import FaultPoints, fire
from ..common.retry import RetryPolicy, compute_backoff
from ..config import mlconf
from ..obs import (
    FLEET_DISPATCHES,
    FLEET_HANDOFF_BYTES,
    FLEET_HANDOFF_LATENCY,
    FLEET_REPLICAS,
    HEALTH_TRANSITIONS,
    REPLICA_HEALTH_SCORE,
    REPLICA_HEALTH_STATE,
    get_tracer,
)
from ..utils import logger
from .prefix import block_chain_key
from .resilience import (
    CircuitOpenError,
    EngineStoppedError,
    QueueFullError,
    ReplicaUnavailableError,
    ServerDrainingError,
)

# process-unique fleet ids so two fleets' replica labels never collide
_FLEET_SEQUENCE = iter(range(1, 1 << 30))


def redispatchable(exc: Exception) -> bool:
    """Failures worth re-routing to another replica: the REPLICA is
    unavailable (stopped, draining, breaker-open, shedding, adapter
    working-set full) — not the request (400-class, unknown tenant,
    and per-tenant rate-limit sheds stay fatal: those follow the
    request wherever it routes). Remote process replicas surface the
    same classes as ``RemoteCallError`` with a 429/502/503 status."""
    if isinstance(exc, (EngineStoppedError, ServerDrainingError,
                        QueueFullError, CircuitOpenError)):
        return True
    from .adapters import AdapterCapacityError
    from .remote import RemoteCallError

    if isinstance(exc, AdapterCapacityError):
        # THIS replica's bank slots are all pinned — another replica
        # (its own registry, its own slots) may well have room
        return True
    if isinstance(exc, RemoteCallError):
        return getattr(exc, "status_code", None) in (429, 502, 503)
    return False


class ConsistentHashRing:
    """Bounded consistent-hash ring with virtual nodes.

    Each node owns ``vnodes`` deterministic points on a 64-bit ring
    (sha256 of ``"node#i"`` — stable across processes); a key maps to
    the first point clockwise from it. Adding/removing a node moves only
    the keys whose nearest point belonged to it (~1/N of the keyspace),
    so a replica join/leave relocates a bounded slice of prefix
    residency instead of reshuffling every hot prefix."""

    def __init__(self, vnodes: int = 64):
        if vnodes <= 0:
            raise ValueError(f"vnodes must be > 0, got {vnodes}")
        self.vnodes = int(vnodes)
        self._points: list[int] = []      # sorted ring positions
        self._owners: list[str] = []      # owner node per position
        self._nodes: set[str] = set()
        self._weights: dict[str, float] = {}

    @staticmethod
    def _point(data: str) -> int:
        return int.from_bytes(
            hashlib.sha256(data.encode()).digest()[:8], "big")

    def __len__(self) -> int:
        return len(self._nodes)

    def nodes(self) -> list[str]:
        return sorted(self._nodes)

    def add(self, node: str, weight: float = 1.0):
        """Add (or re-weight) a node. ``weight`` in (0, 1] scales the
        node's vnode count: a de-weighted node keeps the FIRST
        ``round(vnodes * weight)`` of its deterministic points, so
        probation sheds only the keys owned by the dropped points —
        restoring weight 1.0 restores the identical ownership map, and
        keys on the kept points never move at all."""
        weight = min(1.0, max(0.0, float(weight)))
        if node in self._nodes:
            if self._weights.get(node, 1.0) == weight:
                return
            self.remove(node)
        self._nodes.add(node)
        self._weights[node] = weight
        count = max(1, round(self.vnodes * weight))
        for i in range(count):
            point = self._point(f"{node}#{i}")
            idx = bisect.bisect(self._points, point)
            self._points.insert(idx, point)
            self._owners.insert(idx, node)

    def weight(self, node: str) -> float:
        return self._weights.get(node, 1.0) if node in self._nodes else 0.0

    def remove(self, node: str):
        if node not in self._nodes:
            return
        self._nodes.discard(node)
        self._weights.pop(node, None)
        keep = [(p, o) for p, o in zip(self._points, self._owners)
                if o != node]
        self._points = [p for p, _ in keep]
        self._owners = [o for _, o in keep]

    def lookup(self, key: int) -> str:
        """The node owning ``key``; raises when the ring is empty."""
        if not self._points:
            raise ReplicaUnavailableError("hash ring has no nodes")
        idx = bisect.bisect(self._points, key) % len(self._points)
        return self._owners[idx]

    def preference(self, key: int, exclude=()) -> list[str]:
        """Distinct nodes in ring order starting at ``key``'s owner —
        the re-dispatch order (primary first, then each next ring
        node)."""
        if not self._points:
            return []
        exclude = set(exclude)
        start = bisect.bisect(self._points, key) % len(self._points)
        seen: set[str] = set()
        order: list[str] = []
        for offset in range(len(self._points)):
            owner = self._owners[(start + offset) % len(self._points)]
            if owner in seen or owner in exclude:
                continue
            seen.add(owner)
            order.append(owner)
        return order


class EngineReplica:
    """One engine behind a fleet id + role (unified | prefill | decode).

    The dispatch contract is duck-typed on ``submit``/``submit_prefill``/
    ``submit_prefilled`` returning Futures — a remote process replica
    (RemoteStep-backed client) satisfies it without the fleet changing."""

    def __init__(self, replica_id: str, engine, role: str = "unified"):
        self.id = replica_id
        self.engine = engine
        self.role = role
        self.draining = False
        # deferred ring join (serving/podfleet.py): a joining replica is
        # registered (visible in stats, warm-able) but takes NO ring
        # traffic until join_replica() flips this — ready means warm
        self.joining = False
        # fail-slow probation (obs/health.py ReplicaHealthScorer): the
        # scorer de-weights a probated replica's ring vnodes instead of
        # draining it — correct-but-slow deserves less traffic, not death
        self.weight = 1.0
        self.health_state = "healthy"
        # stamp the replica label BEFORE the engine registers metrics
        engine.replica = replica_id

    @property
    def healthy(self) -> bool:
        return not self.draining and not self.joining and not getattr(
            self.engine, "_stopped", False)

    def load(self) -> int:
        """Cheap congestion signal for decode-pool placement: active
        slots + queued admissions (host-side ints, no stats() walk)."""
        engine = self.engine
        active = sum(1 for s in getattr(engine, "_slot_state", ())
                     if s.active)
        return active + engine._queue_depth()


class EngineFleet:
    """N engine replicas behind one ``submit()``.

    ``engine_factory(role)`` builds one engine per replica ("unified",
    or "prefill"/"decode" when ``prefill_replicas`` > 0). Routing:

    - ``"affinity"`` (default): consistent-hash on the prompt's leading
      prefix blocks — hot prefixes stay cache-resident on one replica.
    - ``"random"``: uniform choice (the bench baseline affinity is
      measured against).

    The fleet duck-types the engine surface ``LLMModelServer.predict``
    uses (``submit``/``generate``/``warmup``/``start``/``stop``/
    ``stats``), so it drops in wherever a single engine did.
    """

    ROUTING = ("affinity", "random")

    def __init__(self, engine_factory: Callable[[str], object],
                 replicas: int = 2, prefill_replicas: int = 0,
                 routing: str | None = None,
                 route_blocks: int | None = None,
                 route_block_tokens: int | None = None,
                 vnodes: int | None = None,
                 max_dispatch_attempts: int | None = None,
                 backoff: float | None = None, seed: int = 0):
        fleet_conf = mlconf.serving.fleet
        if routing is None:
            routing = str(fleet_conf.routing)
        if routing not in self.ROUTING:
            raise ValueError(
                f"unknown routing '{routing}' (one of {self.ROUTING})")
        # replicas=0 is valid for pod-backed fleets: a (re)started
        # control plane whose membership is owned entirely by
        # ServingPodFleet (scale-up / crash-recovery adoption) must not
        # fabricate an in-process seed replica the cluster never had
        if replicas < 0:
            raise ValueError(f"replicas must be >= 0, got {replicas}")
        if prefill_replicas < 0:
            raise ValueError(
                f"prefill_replicas must be >= 0, got {prefill_replicas}")
        self.routing = routing
        self.route_blocks = int(route_blocks
                                if route_blocks is not None
                                else fleet_conf.route_blocks)
        self._factory = engine_factory
        self._fleet_id = f"f{next(_FLEET_SEQUENCE)}"
        self._lock = threading.RLock()
        self._rng = random.Random(seed)
        attempts = (max_dispatch_attempts
                    if max_dispatch_attempts is not None
                    else int(fleet_conf.max_dispatch_attempts))
        if attempts < 1:
            raise ValueError("max_dispatch_attempts must be >= 1")
        self.max_dispatch_attempts = attempts
        # cross-replica prefix fetch (docs/serving.md "Hierarchical KV"):
        # when a hot key's ring owner changes, pull the cached pages from
        # the previous owner instead of re-prefilling on the new one
        self._prefix_fetch = bool(fleet_conf.get("prefix_fetch", True))
        self._retry_policy = RetryPolicy(
            max_retries=attempts,
            backoff=(float(backoff) if backoff is not None
                     else float(fleet_conf.backoff)),
            backoff_factor=2.0, backoff_max=1.0, jitter=0.1)
        self._started = False
        self._stopped = False
        self._replica_seq = 0
        self._stats = {"dispatches": 0, "redispatches": 0, "failed": 0,
                       "no_replica": 0, "handoffs": 0, "handoff_bytes": 0,
                       "prefix_fetches": 0, "prefix_fetch_fallbacks": 0}
        # per-replica sliding outcome windows (rid -> deque of 0/1):
        # rates, not lifetime counters — a replica that failed an hour
        # ago and recovered reads 0.0, which is what the health scorer
        # (obs/health.py) and operators actually want to see
        self._dispatch_outcomes: dict[str, deque] = {}
        self._fetch_outcomes: dict[str, deque] = {}
        self._ttft_ring: list = []            # end-to-end, bounded below
        self._ttft_ring_max = 512
        # hot routing keys (bounded LRU):
        # key -> (prompt, route_adapter, last_owner_rid).  A joining pod
        # replays/fetches its REASSIGNED slice of these as pre-warm
        # (serving/podfleet.py) so its first real request on a moved key
        # is a prefix-cache hit; the last owner is where a ring-moved
        # key's pages still live — the cross-replica fetch source
        self._hot_keys: OrderedDict = OrderedDict()
        self._hot_keys_max = 256
        # pools: unified fleets route over _workers; disaggregated fleets
        # affinity-route prefills over _prefill and place decodes
        # least-loaded over _workers
        self._workers: dict[str, EngineReplica] = {}
        self._prefill: dict[str, EngineReplica] = {}
        vnode_count = int(vnodes if vnodes is not None
                          else fleet_conf.vnodes)
        self._ring = ConsistentHashRing(vnodes=vnode_count)
        worker_role = "decode" if prefill_replicas else "unified"
        for _ in range(replicas):
            self.add_replica(worker_role)
        for _ in range(prefill_replicas):
            self.add_replica("prefill")
        # routing-key block size: align with the engines' page size so
        # the routing identity IS the radix index's block identity
        if route_block_tokens is None:
            pool = self._route_pool()
            if pool:
                first = next(iter(pool.values()))
                route_block_tokens = getattr(first.engine, "page_size",
                                             64)
            else:
                route_block_tokens = 64  # empty fleet: engines arrive
                # later via add_replica; the page-size alignment is the
                # caller's job then (pass route_block_tokens explicitly)
        self.route_block_tokens = int(route_block_tokens)

    # -- topology ------------------------------------------------------------
    def _route_pool(self) -> dict[str, EngineReplica]:
        """The pool affinity routing runs over: prefill replicas when
        disaggregated (their prefix caches are the locality that
        matters), the whole fleet otherwise."""
        return self._prefill if self._prefill else self._workers

    def _sync_ring(self):
        """Ring membership == non-draining, non-joining routing-pool
        membership. Caller holds the lock. Adding the first prefill
        replica flips the routing pool from workers to prefill; the sweep
        keeps the ring consistent through that flip, through drains, and
        through deferred pod joins."""
        route = self._route_pool()
        for node in list(self._ring.nodes()):
            if node not in route or route[node].draining \
                    or route[node].joining:
                self._ring.remove(node)
        for rid, replica in route.items():
            if not replica.draining and not replica.joining:
                self._ring.add(rid, weight=replica.weight)

    @property
    def replicas(self) -> list[EngineReplica]:
        with self._lock:
            return list(self._workers.values()) + list(
                self._prefill.values())

    def add_replica(self, role: str = "unified", engine=None,
                    joined: bool = True) -> str:
        """Scale up: build + ring-join one replica (keys move ~1/N).
        ``engine`` adopts an externally built engine (a pod-backed
        client, serving/podfleet.py) instead of calling the factory;
        ``joined=False`` registers the replica WITHOUT ring membership —
        it takes no traffic until :meth:`join_replica`, so a pod can
        pre-warm behind the ring."""
        if role not in ("unified", "prefill", "decode"):
            raise ValueError(f"unknown replica role '{role}'")
        with self._lock:
            rid = f"{self._fleet_id}-{role[0]}{self._replica_seq}"
            self._replica_seq += 1
            if engine is None:
                engine = self._factory(role)
            replica = EngineReplica(rid, engine, role)
            replica.joining = not joined
            pool = self._prefill if role == "prefill" else self._workers
            pool[rid] = replica
            self._sync_ring()
            FLEET_REPLICAS.set(
                sum(1 for r in self.replicas if r.role == role), role=role)
            if self._started:
                engine.start()
        logger.info("fleet replica added", replica=rid, role=role,
                    fleet=self._fleet_id, joined=joined)
        return rid

    def join_replica(self, replica_id: str):
        """Flip a deferred-join replica into the ring (its ~1/N keyspace
        slice moves here). Fires ``fleet.join`` first: an injected delay
        models a slow join (keys keep routing to survivors meanwhile),
        an injected error keeps the replica out of the ring."""
        fire(FaultPoints.fleet_join, replica=replica_id,
             fleet=self._fleet_id)
        with self._lock:
            for pool in (self._workers, self._prefill):
                if replica_id in pool:
                    pool[replica_id].joining = False
                    self._sync_ring()
                    logger.info("fleet replica joined ring",
                                replica=replica_id, fleet=self._fleet_id)
                    return
        raise KeyError(f"unknown replica '{replica_id}'")

    def remove_replica(self, replica_id: str):
        """Scale down: ring-leave (only this replica's keys move), stop
        the engine (queued work fails with EngineStoppedError and the
        dispatch layer re-routes it), and let the engine retire its own
        metric series."""
        with self._lock:
            replica = self._workers.pop(replica_id, None) or \
                self._prefill.pop(replica_id, None)
            if replica is None:
                raise KeyError(f"unknown replica '{replica_id}'")
            replica.draining = True
            self._sync_ring()
            FLEET_REPLICAS.set(
                sum(1 for r in self.replicas if r.role == replica.role),
                role=replica.role)
        replica.engine.stop()
        # the engine retired its mlt_llm_* series in stop(); retire the
        # fleet's per-replica dispatch series too, or a churning fleet
        # pins dead replicas until the family's cardinality bound bites
        for outcome in ("ok", "redispatch", "failed"):
            FLEET_DISPATCHES.remove(replica=replica_id, outcome=outcome)
        # health telemetry rides the same lifecycle: scorer series and
        # outcome windows die with the replica (remove() is a no-op for
        # series the scorer never wrote)
        REPLICA_HEALTH_SCORE.remove(replica=replica_id)
        REPLICA_HEALTH_STATE.remove(replica=replica_id)
        for to in ("healthy", "suspect", "probation"):
            HEALTH_TRANSITIONS.remove(replica=replica_id, to=to)
        with self._lock:
            self._dispatch_outcomes.pop(replica_id, None)
            self._fetch_outcomes.pop(replica_id, None)
        logger.info("fleet replica removed", replica=replica_id,
                    fleet=self._fleet_id)

    def set_replica_weight(self, replica_id: str, weight: float):
        """Scale a replica's share of the ring keyspace (probation
        actuation, obs/health.py). Weight in (0, 1] keeps a deterministic
        prefix of its vnode points, so only the shed slice of keys moves
        to neighbors and restoring 1.0 restores identical ownership.
        Drain/joining state is untouched — a de-weighted replica still
        serves the keys it keeps and all in-flight work."""
        with self._lock:
            for pool in (self._workers, self._prefill):
                if replica_id in pool:
                    pool[replica_id].weight = min(
                        1.0, max(0.0, float(weight)))
                    self._sync_ring()
                    return
        raise KeyError(f"unknown replica '{replica_id}'")

    def drain_replica(self, replica_id: str):
        """Stop routing NEW work to a replica (in-flight work finishes);
        the ring drops its points so its keyspace moves to neighbors."""
        with self._lock:
            for pool in (self._workers, self._prefill):
                if replica_id in pool:
                    pool[replica_id].draining = True
                    self._sync_ring()
                    return
        raise KeyError(f"unknown replica '{replica_id}'")

    # -- lifecycle -----------------------------------------------------------
    def start(self):
        with self._lock:
            self._started = True
            replicas = self.replicas
        for replica in replicas:
            replica.engine.start()

    def warmup(self):
        for replica in self.replicas:
            replica.engine.warmup()

    def stop(self, timeout: float = 10.0):
        with self._lock:
            self._stopped = True
            replicas = self.replicas
        for replica in replicas:
            replica.engine.stop(timeout=timeout)

    def close(self):
        self.stop()

    # -- routing -------------------------------------------------------------
    def routing_key(self, prompt_tokens, adapter: str = "") -> int:
        """Prefix-block routing key, namespaced per tenant: the SAME
        prompt under two adapters is two identities (its KV is not
        shareable across them), while same-tenant shared prefixes still
        land on one replica (docs/serving.md "Multi-tenant LoRA")."""
        return block_chain_key(prompt_tokens, self.route_block_tokens,
                               max_blocks=self.route_blocks,
                               adapter=adapter)

    def reassigned_hot_keys(self, candidate: str) -> list:
        """The hot keys whose ring owner WOULD become ``candidate`` if it
        joined now — exactly the prefix working set a joining pod takes
        over, so pre-warm (serving/podfleet.py) replays these and nothing
        else. Returns ``[(key, prompt, adapter), ...]`` hottest-last
        (LRU order)."""
        with self._lock:
            probe = ConsistentHashRing(vnodes=self._ring.vnodes)
            for node in self._ring.nodes():
                probe.add(node)
            probe.add(candidate)
            items = list(self._hot_keys.items())
        out = []
        for key, (prompt, adapter, _owner) in items:
            if probe.lookup(key) == candidate:
                out.append((key, prompt, adapter))
        return out

    def hot_key_owner(self, key: int) -> Optional[str]:
        """The replica that last SERVED a hot key — where its prefix
        pages still live after a ring reassignment moves the key (the
        cross-replica fetch source, docs/serving.md "Hierarchical KV")."""
        with self._lock:
            entry = self._hot_keys.get(key)
            return entry[2] if entry is not None else None

    def _pick(self, pool: dict, key: int, tried: list,
              affinity: bool) -> Optional[EngineReplica]:
        """Next replica for a key: ring preference order under affinity,
        uniform under random; draining/stopped/already-tried replicas are
        skipped, with a healthy fallback off-ring so a request never
        fails while ANY replica could serve it."""
        with self._lock:
            candidates = [r for r in pool.values()
                          if r.healthy and r.id not in tried]
            if not candidates:
                return None
            if not affinity or self.routing == "random":
                return self._rng.choice(candidates)
            by_id = {r.id: r for r in candidates}
            for rid in self._ring.preference(key, exclude=tried):
                if rid in by_id:
                    return by_id[rid]
            # ring points may lag a drain — any healthy replica beats a
            # client-visible failure
            return candidates[0]

    def _pick_decode(self, tried: list) -> Optional[EngineReplica]:
        """Decode placement is load-, not locality-driven: the KV arrives
        with the handoff, so the least-loaded healthy replica wins."""
        with self._lock:
            candidates = [r for r in self._workers.values()
                          if r.healthy and r.id not in tried]
        if not candidates:
            return None
        return min(candidates, key=lambda r: (r.load(), r.id))

    # -- dispatch ------------------------------------------------------------
    def submit(self, prompt_tokens, max_new_tokens: int = 64,
               eos_id: int | None = None, temperature: float = 0.0,
               top_k: int = 0, top_p: float = 1.0,
               max_wait: float | None = None,
               adapter: str = "", request_key=None) -> Future:
        """Route one request into the fleet; resolves to (tokens, stats)
        exactly like an engine future, with ``stats`` gaining ``replica``
        (and ``prefill_replica``/``prefill_s``/``handoff_bytes`` when
        disaggregated). 503-class replica failures re-dispatch to the
        next ring node up to ``max_dispatch_attempts`` times.
        ``adapter`` is the tenant id: it namespaces the routing key and
        rides the dispatch (and any KV handoff) into the engines. A
        tenant with canary-loop state resolves to its effective
        versioned id BEFORE the routing key is computed
        (serving/canary.py), so canary traffic routes — and caches — as
        its own identity; ``request_key`` pins the split side."""
        out: Future = Future()
        if self._stopped:
            out.set_exception(EngineStoppedError(
                "fleet is stopped, not accepting requests"))
            return out
        route_adapter = adapter or ""
        if adapter:
            from .canary import resolve_adapter

            # key computation only (count=False): the ENGINE is the
            # single resolution/metering authority — it re-resolves with
            # the SAME request key threaded below, so the routing key
            # here and the identity there always agree
            route_adapter = resolve_adapter(adapter, prompt_tokens,
                                            request_key, count=False)
        span = get_tracer().current()
        state = {
            "prompt": list(prompt_tokens),
            "max_new": max_new_tokens, "eos_id": eos_id,
            "sampling": (float(temperature), int(top_k), float(top_p)),
            "max_wait": max_wait,
            "adapter": adapter or "",
            "request_key": request_key,
            "key": self.routing_key(prompt_tokens, adapter=route_adapter),
            "t0": time.perf_counter(),
            "attempts": 0, "tried": [], "tried_decode": [],
            "trace": ((span.trace_id, span.span_id)
                      if span is not None else None),
        }
        with self._lock:
            prev = self._hot_keys.get(state["key"])
            self._hot_keys[state["key"]] = (
                state["prompt"], state["adapter"],
                prev[2] if prev is not None else None)
            self._hot_keys.move_to_end(state["key"])
            while len(self._hot_keys) > self._hot_keys_max:
                self._hot_keys.popitem(last=False)
        if self._prefill:
            self._dispatch_prefill(out, state)
        else:
            self._dispatch_unified(out, state)
        return out

    def generate(self, prompt_tokens, max_new_tokens: int = 64,
                 eos_id: int | None = None, timeout: float = 300.0,
                 temperature: float = 0.0, top_k: int = 0,
                 top_p: float = 1.0, adapter: str = "",
                 request_key=None):
        return self.submit(prompt_tokens, max_new_tokens, eos_id,
                           temperature=temperature, top_k=top_k,
                           top_p=top_p, adapter=adapter,
                           request_key=request_key).result(timeout=timeout)

    # -- adapter source lifecycle (docs/continuous_tuning.md) ----------------
    def add_adapter_source(self, name: str, source):
        """Publish a named adapter on every replica's registry (the
        canary hot-load path) — idempotent for replicas sharing one
        registry."""
        for replica in self.replicas:
            replica.engine.add_adapter_source(name, source)

    def retire_adapter(self, name: str, keep_source: bool = False):
        """Drop an adapter fleet-wide (promotion's old-stable evict / a
        rollback's canary teardown); per-replica in-flight pins finish
        first."""
        for replica in self.replicas:
            replica.engine.retire_adapter(name, keep_source=keep_source)

    def _fail(self, out: Future, state: dict, exc: Exception):
        with self._lock:
            self._stats["failed"] += 1
        if not out.done():
            out.set_exception(exc)

    @staticmethod
    def _merge_timing(state: dict, stats: dict):
        """Fold the fleet's own time attribution into the engine-side
        phase ledger so the end-to-end timing still sums to the
        CLIENT-OBSERVED wall by construction (docs/observability.md
        "Request attribution"): the prefill replica's ledger (riding
        the KV handoff) and the decode replica's ledger add phase-wise,
        the re-dispatch backoff timers land on ``redispatch_backoff``,
        and whatever the engines could not see — dispatch callbacks,
        handoff transfer, a failed attempt's discarded work — is the
        ``network`` remainder (the hop wall minus the server-side
        attributed time, exactly the RemoteStep definition)."""
        from ..obs import merge_timing

        timing = stats.get("timing")
        if not isinstance(timing, dict):
            return
        timing = dict(timing)
        timing["phases"] = dict(timing.get("phases") or {})
        handoff = state.get("handoff")
        if handoff is not None and getattr(handoff, "timing", None):
            merge_timing(timing, handoff.timing)
        phases = timing["phases"]
        backoff = state.get("backoff_s", 0.0)
        if backoff > 0:
            phases["redispatch_backoff"] = \
                phases.get("redispatch_backoff", 0.0) + backoff
        fetch = state.get("fetch_s", 0.0)
        if fetch > 0:
            phases["fetch"] = phases.get("fetch", 0.0) + fetch
        wall = time.perf_counter() - state["t0"]
        attributed = sum(phases.values())
        gap = wall - attributed
        if gap > 0:
            phases["network"] = phases.get("network", 0.0) + gap
        timing["wall_s"] = max(wall, attributed)
        timing["attribution_closed"] = True
        stats["timing"] = timing

    def _retry_later(self, out: Future, state: dict, redo: Callable,
                     exc: Exception | None = None):
        """Deterministic-jitter backoff off-thread: the done-callback
        runs on a replica's scheduler thread, which must never sleep.
        A server-supplied ``Retry-After`` hint riding the failure
        (``exc.retry_after_s``) wins over the local schedule — the
        replica knows its own drain/recovery timeline better than the
        client's blind exponential. The delay is remembered so the final
        timing attributes it to the ``redispatch_backoff`` phase
        (obs/reqledger.py)."""
        with self._lock:
            self._stats["redispatches"] += 1
        hint = getattr(exc, "retry_after_s", None)
        delay = float(hint) if hint is not None else compute_backoff(
            state["attempts"] - 1, self._retry_policy,
            seed=f"fleet:{state['key']}")
        state["backoff_s"] = state.get("backoff_s", 0.0) + delay
        timer = threading.Timer(delay, redo)
        timer.daemon = True
        timer.start()

    def _no_replica(self, out: Future, state: dict, pool: str):
        with self._lock:
            self._stats["no_replica"] += 1
        FLEET_DISPATCHES.inc(replica="", outcome="no_replica")
        # a jitter-free Retry-After derived from the same schedule the
        # fleet retries on: an upstream honoring it lands just after
        # capacity could have returned, instead of hammering blind
        hint = compute_backoff(
            min(state["attempts"], self.max_dispatch_attempts - 1),
            self._retry_policy, seed="retry-after")
        self._fail(out, state, ReplicaUnavailableError(
            f"no healthy {pool} replica left after "
            f"{state['attempts']} attempt(s) "
            f"(tried {state['tried'] or state['tried_decode']})",
            retry_after_s=hint))

    def _budget_left(self, out: Future, state: dict,
                     exc: Exception) -> bool:
        state["attempts"] += 1
        if state["attempts"] < self.max_dispatch_attempts:
            return True
        self._fail(out, state, exc)
        return False

    # -- cross-replica prefix fetch (docs/serving.md "Hierarchical KV") ------
    def _fetch_source(self, state: dict,
                      target: EngineReplica) -> Optional[EngineReplica]:
        """The replica worth pulling this key's cached pages from before
        dispatching to ``target``: the key's LAST owner, when it is a
        different, healthy replica and both ends speak the fetch
        protocol. One attempt per request — fetch is a warm-up, not a
        retry loop — and only on the first dispatch (a re-dispatch means
        replicas are failing; don't add hops). Affinity routing only: a
        moved key there means the RING moved (scale event), a one-time
        migration worth a hop; under random routing every request lands
        off-owner and the hop would re-ship pages per request."""
        if not self._prefix_fetch or self.routing != "affinity" \
                or state.get("fetch_tried") or state["attempts"]:
            return None
        with self._lock:
            entry = self._hot_keys.get(state["key"])
            owner_id = entry[2] if entry is not None else None
            if owner_id is None or owner_id == target.id:
                return None
            owner = self._workers.get(owner_id) \
                or self._prefill.get(owner_id)
        if owner is None or not owner.healthy:
            return None
        if not hasattr(owner.engine, "fetch_prefix") \
                or not hasattr(target.engine, "import_prefix"):
            return None
        return owner

    def _fetch_then(self, state: dict, owner: EngineReplica,
                    target: EngineReplica, resume: Callable):
        """Pull the request's cached prefix pages out of ``owner`` and
        import them into ``target``, then ``resume()`` the dispatch —
        the request's prefill on the new owner becomes a prefix-cache
        hit instead of a cold re-prefill. ANY failure (chaos-armed
        ``llm.kv_fetch``, a miss on the owner, a stopped engine, an
        import error) falls through to the plain dispatch: fetch is an
        optimization, never a gate on the hot path. The elapsed seconds
        land on the ``fetch`` ledger phase via :meth:`_merge_timing`."""
        state["fetch_tried"] = True
        t0 = time.perf_counter()

        def finish(fetched: bool):
            state["fetch_s"] = state.get("fetch_s", 0.0) \
                + (time.perf_counter() - t0)
            with self._lock:
                self._stats["prefix_fetches" if fetched
                            else "prefix_fetch_fallbacks"] += 1
            self._note_fetch(target.id, fetched)
            if fetched:
                logger.info("fleet prefix fetch", key=state["key"],
                            owner=owner.id, target=target.id)
            resume()

        def on_import(fut: Future):
            try:
                fut.result()
            except Exception:  # noqa: BLE001 - fall back to plain dispatch
                finish(False)
                return
            finish(True)

        def on_fetch(fut: Future):
            try:
                payload = fut.result()
            except Exception:  # noqa: BLE001 - miss/stopped owner
                payload = None
            if payload is None:
                finish(False)
                return
            try:
                with self._lock:
                    self._stats["handoff_bytes"] += payload.nbytes()
                FLEET_HANDOFF_BYTES.inc(payload.nbytes())
                target.engine.import_prefix(payload) \
                    .add_done_callback(on_import)
            except Exception:  # noqa: BLE001 - fall back
                finish(False)

        try:
            # an armed error here models a dead fetch path; an armed
            # delay models a slow pull — both degrade to re-prefill
            fire(FaultPoints.llm_kv_fetch, key=state["key"],
                 owner=owner.id, target=target.id)
            owner.engine.fetch_prefix(
                state["prompt"], adapter=state["adapter"]) \
                .add_done_callback(on_fetch)
        except Exception:  # noqa: BLE001 - fall back to plain dispatch
            finish(False)

    def _note_dispatch(self, replica_id: str, ok: bool):
        """Append one outcome to the replica's sliding window (ok=False
        covers both redispatch and terminal failure — either way the
        replica didn't complete work it was handed)."""
        with self._lock:
            self._dispatch_outcomes.setdefault(
                replica_id, deque(maxlen=64)).append(0 if ok else 1)

    def _note_fetch(self, replica_id: str, fetched: bool):
        with self._lock:
            self._fetch_outcomes.setdefault(
                replica_id, deque(maxlen=64)).append(0 if fetched else 1)

    # unified fleet: one replica runs prefill AND decode
    def _dispatch_unified(self, out: Future, state: dict):
        # dispatch runs on done-callback / Timer threads, where an
        # uncaught raise is swallowed by the Future machinery and the
        # client future hangs to its timeout — a synchronous submit()
        # failure (duck-typed remote replica, bad handoff) must fail the
        # request loudly instead
        try:
            replica = self._pick(self._workers, state["key"],
                                 state["tried"], affinity=True)
            if replica is None:
                self._no_replica(out, state, "fleet")
                return
            owner = self._fetch_source(state, replica)
        except Exception as exc:  # noqa: BLE001 - routed to the client
            self._fail(out, state, exc)
            return
        if owner is not None:
            self._fetch_then(state, owner, replica,
                             lambda: self._submit_unified(
                                 out, state, replica))
            return
        self._submit_unified(out, state, replica)

    def _submit_unified(self, out: Future, state: dict,
                        replica: EngineReplica):
        try:
            state["tried"].append(replica.id)
            inner = replica.engine.submit(
                state["prompt"], max_new_tokens=state["max_new"],
                eos_id=state["eos_id"], temperature=state["sampling"][0],
                top_k=state["sampling"][1], top_p=state["sampling"][2],
                max_wait=state["max_wait"], adapter=state["adapter"],
                request_key=state["request_key"], _trace=state["trace"])
        except Exception as exc:  # noqa: BLE001 - routed to the client
            self._fail(out, state, exc)
            return
        inner.add_done_callback(
            lambda fut: self._unified_done(out, state, replica, fut))

    def _unified_done(self, out: Future, state: dict,
                      replica: EngineReplica, fut: Future):
        exc = fut.exception()
        if exc is None:
            tokens, stats = fut.result()
            self._finalize(out, state, replica, tokens, dict(stats))
            return
        if redispatchable(exc):
            FLEET_DISPATCHES.inc(replica=replica.id, outcome="redispatch")
            self._note_dispatch(replica.id, ok=False)
            logger.warning("fleet re-dispatching request",
                           replica=replica.id, error=str(exc),
                           attempt=state["attempts"] + 1)
            if self._budget_left(out, state, exc):
                # a preempted replica may have exported the decode state
                # (ReplicaPreemptedError.handoff): resume it on a
                # survivor via submit_prefilled instead of re-prefilling
                handoff = getattr(exc, "handoff", None)
                if handoff is not None:
                    state["handoff"] = handoff
                    redo = lambda: self._dispatch_handoff(out, state)  # noqa: E731
                else:
                    redo = lambda: self._dispatch_unified(out, state)  # noqa: E731
                self._retry_later(out, state, redo, exc=exc)
            return
        FLEET_DISPATCHES.inc(replica=replica.id, outcome="failed")
        self._note_dispatch(replica.id, ok=False)
        self._fail(out, state, exc)

    def _dispatch_handoff(self, out: Future, state: dict):
        """Resume a preempted request on a survivor: the dying replica
        exported the decode state as a :class:`KVHandoff` (riding the
        :class:`ReplicaPreemptedError`), so the survivor imports it and
        decodes — no re-prefill, no dropped admitted request."""
        try:
            replica = self._pick(self._workers, state["key"],
                                 state["tried"], affinity=True)
            if replica is None:
                self._no_replica(out, state, "fleet")
                return
            state["tried"].append(replica.id)
            handoff = state["handoff"]
            with self._lock:
                self._stats["handoffs"] += 1
                self._stats["handoff_bytes"] += handoff.nbytes()
            FLEET_HANDOFF_BYTES.inc(handoff.nbytes())
            inner = replica.engine.submit_prefilled(
                handoff, max_new_tokens=state["max_new"],
                eos_id=state["eos_id"], max_wait=state["max_wait"],
                _trace=state["trace"])
        except Exception as exc:  # noqa: BLE001 - routed to the client
            self._fail(out, state, exc)
            return
        inner.add_done_callback(
            lambda fut: self._handoff_done(out, state, replica, fut))

    def _handoff_done(self, out: Future, state: dict,
                      replica: EngineReplica, fut: Future):
        exc = fut.exception()
        if exc is None:
            tokens, stats = fut.result()
            stats = dict(stats)
            handoff = state["handoff"]
            FLEET_HANDOFF_LATENCY.observe(stats.get("ttft_s", 0.0))
            stats["handoff_bytes"] = handoff.nbytes()
            stats["cached_prefix"] = handoff.cached_prefix
            stats["resumed_via_handoff"] = True
            self._finalize(out, state, replica, tokens, stats)
            return
        if redispatchable(exc):
            FLEET_DISPATCHES.inc(replica=replica.id, outcome="redispatch")
            self._note_dispatch(replica.id, ok=False)
            newer = getattr(exc, "handoff", None)
            if newer is not None:
                state["handoff"] = newer
            if self._budget_left(out, state, exc):
                self._retry_later(
                    out, state,
                    lambda: self._dispatch_handoff(out, state), exc=exc)
            return
        FLEET_DISPATCHES.inc(replica=replica.id, outcome="failed")
        self._note_dispatch(replica.id, ok=False)
        self._fail(out, state, exc)

    # disaggregated fleet: prefill pool → KV handoff → decode pool
    def _dispatch_prefill(self, out: Future, state: dict):
        try:
            replica = self._pick(self._prefill, state["key"],
                                 state["tried"], affinity=True)
            if replica is None:
                self._no_replica(out, state, "prefill")
                return
            owner = self._fetch_source(state, replica)
        except Exception as exc:  # noqa: BLE001 - routed to the client
            self._fail(out, state, exc)
            return
        if owner is not None:
            self._fetch_then(state, owner, replica,
                             lambda: self._submit_prefill(
                                 out, state, replica))
            return
        self._submit_prefill(out, state, replica)

    def _submit_prefill(self, out: Future, state: dict,
                        replica: EngineReplica):
        try:
            state["tried"].append(replica.id)
            inner = replica.engine.submit_prefill(
                state["prompt"], eos_id=state["eos_id"],
                temperature=state["sampling"][0],
                top_k=state["sampling"][1], top_p=state["sampling"][2],
                max_wait=state["max_wait"], adapter=state["adapter"],
                request_key=state["request_key"], _trace=state["trace"])
        except Exception as exc:  # noqa: BLE001 - routed to the client
            self._fail(out, state, exc)
            return
        inner.add_done_callback(
            lambda fut: self._prefill_done(out, state, replica, fut))

    def _prefill_done(self, out: Future, state: dict,
                      replica: EngineReplica, fut: Future):
        exc = fut.exception()
        if exc is None:
            handoff = fut.result()
            with self._lock:
                self._stats["handoffs"] += 1
                self._stats["handoff_bytes"] += handoff.nbytes()
            FLEET_HANDOFF_BYTES.inc(handoff.nbytes())
            state["handoff"] = handoff
            self._dispatch_decode(out, state)
            return
        if redispatchable(exc):
            FLEET_DISPATCHES.inc(replica=replica.id, outcome="redispatch")
            self._note_dispatch(replica.id, ok=False)
            if self._budget_left(out, state, exc):
                self._retry_later(
                    out, state,
                    lambda: self._dispatch_prefill(out, state), exc=exc)
            return
        FLEET_DISPATCHES.inc(replica=replica.id, outcome="failed")
        self._note_dispatch(replica.id, ok=False)
        self._fail(out, state, exc)

    def _dispatch_decode(self, out: Future, state: dict):
        try:
            replica = self._pick_decode(state["tried_decode"])
            if replica is None:
                self._no_replica(out, state, "decode")
                return
            state["tried_decode"].append(replica.id)
            inner = replica.engine.submit_prefilled(
                state["handoff"], max_new_tokens=state["max_new"],
                eos_id=state["eos_id"], max_wait=state["max_wait"],
                _trace=state["trace"])
        except Exception as exc:  # noqa: BLE001 - routed to the client
            # e.g. submit_prefilled's synchronous KV-dtype mismatch — this
            # runs inside the prefill future's done-callback, which eats
            # uncaught raises
            self._fail(out, state, exc)
            return
        inner.add_done_callback(
            lambda fut: self._decode_done(out, state, replica, fut))

    def _decode_done(self, out: Future, state: dict,
                     replica: EngineReplica, fut: Future):
        exc = fut.exception()
        if exc is None:
            tokens, stats = fut.result()
            stats = dict(stats)
            handoff = state["handoff"]
            # decode-side ttft is the import+queue cost — the handoff
            # latency; end-to-end TTFT = prefill + handoff
            FLEET_HANDOFF_LATENCY.observe(stats.get("ttft_s", 0.0))
            stats["handoff_s"] = stats.get("ttft_s", 0.0)
            stats["handoff_bytes"] = handoff.nbytes()
            stats["prefill_replica"] = handoff.replica
            stats["prefill_s"] = handoff.prefill_s
            stats["cached_prefix"] = handoff.cached_prefix
            stats["ttft_s"] = handoff.prefill_s + stats["handoff_s"]
            self._finalize(out, state, replica, tokens, stats)
            return
        if redispatchable(exc):
            # the handoff is plain host data — replayable on the next
            # decode replica without touching the prefill pool again; a
            # preempted decode replica may ship back a FRESHER handoff
            FLEET_DISPATCHES.inc(replica=replica.id, outcome="redispatch")
            self._note_dispatch(replica.id, ok=False)
            newer = getattr(exc, "handoff", None)
            if newer is not None:
                state["handoff"] = newer
            if self._budget_left(out, state, exc):
                self._retry_later(
                    out, state,
                    lambda: self._dispatch_decode(out, state), exc=exc)
            return
        FLEET_DISPATCHES.inc(replica=replica.id, outcome="failed")
        self._note_dispatch(replica.id, ok=False)
        self._fail(out, state, exc)

    def _finalize(self, out: Future, state: dict,
                  replica: EngineReplica, tokens, stats: dict):
        stats["replica"] = replica.id
        stats["dispatch_attempts"] = state["attempts"] + 1
        if state.get("adapter"):
            stats["adapter"] = state["adapter"]
        self._merge_timing(state, stats)
        FLEET_DISPATCHES.inc(replica=replica.id, outcome="ok")
        self._note_dispatch(replica.id, ok=True)
        with self._lock:
            # remember WHERE this key's pages now live: the fetch source
            # after the ring moves the key to a different replica.
            # Disaggregated fleets cache on the PREFILL replica, not the
            # decode replica finalizing here
            entry = self._hot_keys.get(state["key"])
            if entry is not None:
                owner_rid = stats.get("prefill_replica") or replica.id
                self._hot_keys[state["key"]] = (entry[0], entry[1],
                                                owner_rid)
            self._stats["dispatches"] += 1
            self._ttft_ring.append(stats.get("ttft_s", 0.0))
            if len(self._ttft_ring) > self._ttft_ring_max:
                del self._ttft_ring[:len(self._ttft_ring)
                                    - self._ttft_ring_max]
        if not out.done():
            out.set_result((tokens, stats))

    # -- observability -------------------------------------------------------
    @property
    def stats(self) -> dict:
        """Fleet-level view: routing counters, aggregate prefix hit rate
        (total hits over total queries — the bench/acceptance number),
        end-to-end TTFT percentiles from the fleet's own ring, and a
        ``per_replica`` breakdown feeding the future autoscaler."""
        from .llm_batch import _percentile

        with self._lock:
            out = dict(self._stats)
            ttfts = sorted(self._ttft_ring)
            replicas = self.replicas
            dispatch_windows = {rid: list(win) for rid, win
                                in self._dispatch_outcomes.items()}
            fetch_windows = {rid: list(win) for rid, win
                             in self._fetch_outcomes.items()}
        out["routing"] = self.routing
        out["replicas"] = len(replicas)
        out["prefill_replicas"] = sum(
            1 for r in replicas if r.role == "prefill")
        hits = queries = completed = depth = 0
        per: dict[str, dict] = {}
        for replica in replicas:
            stats = replica.engine.stats
            hits += stats.get("prefix_hits", 0)
            queries += stats.get("prefix_queries", 0)
            completed += stats.get("completed", 0)
            depth += stats.get("queue_depth", 0)
            # page headroom + live load feed the autoscaler's signals
            # (service/autoscaler.py) and the federation stats ingest
            # (obs/federation.py ingest_stats)
            frac_fn = getattr(replica.engine, "_free_page_frac", None)
            try:
                load = replica.load()
            except Exception:  # noqa: BLE001 - a stopping replica's
                load = 0       # queue may already be torn down
            d_win = dispatch_windows.get(replica.id, ())
            f_win = fetch_windows.get(replica.id, ())
            per[replica.id] = {
                "role": replica.role,
                "draining": replica.draining,
                "joining": replica.joining,
                "weight": replica.weight,
                "health_state": replica.health_state,
                # windowed rates (last 64 outcomes), not lifetime
                # counters — what the health scorer and operators read
                "dispatch_failure_rate": (
                    sum(d_win) / len(d_win) if d_win else 0.0),
                "fetch_fallback_rate": (
                    sum(f_win) / len(f_win) if f_win else 0.0),
                "requests": stats.get("requests", 0),
                "completed": stats.get("completed", 0),
                "queue_depth": stats.get("queue_depth", 0),
                "free_page_frac": frac_fn() if frac_fn else None,
                "load": load,
                "prefix_hit_rate": stats.get("prefix_hit_rate", 0.0),
                "handoffs_out": stats.get("handoffs_out", 0),
                "handoffs_in": stats.get("handoffs_in", 0),
            }
            for key in ("ttft_p50_s", "ttft_p95_s", "decode_tick_p50_s",
                        "decode_tick_p95_s", "prefill_chunks",
                        "prefill_kernel_chunks",
                        "prefill_gather_admissions",
                        "spec_rounds", "spec_proposed", "spec_accepted",
                        "acceptance_rate", "spec_tokens_per_round"):
                if key in stats:
                    per[replica.id][key] = stats[key]
        out["completed"] = completed
        out["queue_depth"] = depth
        out["prefix_hits"] = hits
        out["prefix_queries"] = queries
        out["prefix_hit_rate"] = hits / queries if queries else 0.0
        if ttfts:
            out["ttft_p50_s"] = _percentile(ttfts, 0.50)
            out["ttft_p95_s"] = _percentile(ttfts, 0.95)
        out["per_replica"] = per
        return out
